"""Passthrough response types that bypass the JSON envelope.

Parity with gofr `pkg/gofr/http/response/{raw,file}.go`: handlers usually return
plain Python values that get enveloped as ``{"data": ...}``; returning one of
these types instead controls the wire bytes directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Raw:
    """Serialize ``data`` as JSON but WITHOUT the ``{"data": ...}`` envelope."""

    data: object


@dataclass
class File:
    """Binary body with explicit content type (used by swagger-ui serving)."""

    content: bytes
    content_type: str = "application/octet-stream"


@dataclass
class Redirect:
    url: str
    status_code: int = 302


@dataclass
class Response:
    """Full-control response: envelope data plus custom headers/status."""

    data: object
    status_code: int | None = None
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class Passthrough:
    """Verbatim wire response: raw body bytes with explicit status, content
    type and headers, no envelope — what a proxy tier (router data plane)
    returns so a replica's response, its ``Retry-After``/``X-Trace-Id``
    headers included, reaches the client byte-identical."""

    body: bytes
    status_code: int = 200
    content_type: str = "application/json"
    headers: dict[str, str] = field(default_factory=dict)
