"""HTTP layer: request/responder abstractions, typed errors, middleware chain.

Parity with the reference's `pkg/gofr/http` package (router, request binding,
JSON envelope responder, typed status-carrying errors, middleware chain) built
on asyncio/aiohttp instead of gorilla/mux + goroutine-per-request.
"""

from gofr_tpu.http.errors import (
    EntityAlreadyExists,
    EntityNotFound,
    HTTPError,
    InvalidParam,
    InvalidRoute,
    MissingParam,
    PanicRecovery,
    RequestTimeout,
)
from gofr_tpu.http.responses import File, Raw, Redirect, Response

__all__ = [
    "HTTPError",
    "EntityNotFound",
    "EntityAlreadyExists",
    "InvalidParam",
    "MissingParam",
    "InvalidRoute",
    "RequestTimeout",
    "PanicRecovery",
    "Raw",
    "File",
    "Redirect",
    "Response",
]
