"""Multipart form parsing + struct binding (gofr `pkg/gofr/http/multipart_file_bind.go`).

Parses ``multipart/form-data`` bodies without external deps and binds parts into
a user dataclass: ``UploadFile``-annotated fields receive files, ``Zip`` fields
receive zip archives expanded in memory (100MB cap, mirroring
`pkg/gofr/file/zip.go:13-17`), and other fields receive coerced form values.
"""

from __future__ import annotations

import dataclasses
import io
import re
import typing
import zipfile
from dataclasses import dataclass, field

from gofr_tpu.utils import bind as binder
from gofr_tpu.utils.bind import BindError

_MAX_ZIP_BYTES = 100 * 1024 * 1024


@dataclass
class UploadFile:
    filename: str
    content: bytes
    content_type: str = "application/octet-stream"

    def read(self) -> bytes:
        return self.content


@dataclass
class Zip:
    """An uploaded zip archive, expanded in memory."""

    files: dict[str, bytes] = field(default_factory=dict)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Zip":
        out: dict[str, bytes] = {}
        total = 0
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            for info in zf.infolist():
                if info.is_dir():
                    continue
                total += info.file_size
                if total > _MAX_ZIP_BYTES:
                    raise BindError("zip contents exceed 100MB limit")
                out[info.filename] = zf.read(info)
        return cls(files=out)


def parse_multipart(content_type: str, body: bytes) -> list[tuple[str, str | None, str, bytes]]:
    """Return list of (name, filename, part_content_type, data)."""
    m = re.search(r'boundary="?([^";]+)"?', content_type)
    if not m:
        raise BindError("multipart body missing boundary")
    boundary = m.group(1).encode()
    parts: list[tuple[str, str | None, str, bytes]] = []
    for chunk in body.split(b"--" + boundary):
        # strip exactly the delimiter CRLFs, never trailing newlines that are
        # part of the uploaded content
        if chunk.startswith(b"\r\n"):
            chunk = chunk[2:]
        if chunk.endswith(b"\r\n"):
            chunk = chunk[:-2]
        if not chunk or chunk in (b"--", b"--\r\n"):
            continue
        if b"\r\n\r\n" in chunk:
            raw_headers, data = chunk.split(b"\r\n\r\n", 1)
        else:
            raw_headers, data = chunk, b""
        headers: dict[str, str] = {}
        for line in raw_headers.decode(errors="replace").split("\r\n"):
            if ":" in line:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
        disp = headers.get("content-disposition", "")
        name_m = re.search(r'name="([^"]*)"', disp)
        file_m = re.search(r'filename="([^"]*)"', disp)
        if not name_m:
            continue
        parts.append(
            (
                name_m.group(1),
                file_m.group(1) if file_m else None,
                headers.get("content-type", "application/octet-stream"),
                data,
            )
        )
    return parts


def bind_multipart(content_type: str, body: bytes, target: typing.Any) -> typing.Any:
    parts = parse_multipart(content_type, body)
    if target is dict:
        return {
            name: (UploadFile(filename, data, ptype) if filename is not None else data.decode(errors="replace"))
            for name, filename, ptype, data in parts
        }
    if not (isinstance(target, type) and dataclasses.is_dataclass(target)):
        raise BindError("multipart bind target must be a dataclass or dict")
    hints = typing.get_type_hints(target)
    by_name = {name: (filename, ptype, data) for name, filename, ptype, data in parts}
    kwargs: dict[str, typing.Any] = {}
    for f in dataclasses.fields(target):
        if f.name not in by_name:
            if f.default is dataclasses.MISSING and f.default_factory is dataclasses.MISSING:  # type: ignore[misc]
                raise BindError(f"missing multipart field {f.name!r}")
            continue
        filename, ptype, data = by_name[f.name]
        ann = binder.unwrap_optional(hints.get(f.name, typing.Any))
        if ann is UploadFile:
            kwargs[f.name] = UploadFile(filename or f.name, data, ptype)
        elif ann is Zip:
            kwargs[f.name] = Zip.from_bytes(data)
        elif ann is bytes:
            kwargs[f.name] = data
        else:
            kwargs[f.name] = binder.bind_value(data.decode(errors="replace"), ann)
    return target(**kwargs)
