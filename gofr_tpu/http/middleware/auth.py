"""Auth middlewares: Basic, API-key, and OAuth2/JWT with background-refreshed JWKS.

Parity with gofr `pkg/gofr/http/middleware/{basic_auth,apikey_auth,oauth}.go`:
static credential maps or custom validators (container-aware), ``/.well-known/*``
always skipped (`basic_auth.go:25-29`), JWKS polled on a ticker with RSA keys
reconstructed from the JWK ``n``/``e`` members (`oauth.go:53-71,187-207`), and
verified claims injected into the request context (`oauth.go:147-148`).

JWT verification (RS256 via `cryptography`, HS256 via stdlib hmac) is
implemented in-tree — no PyJWT dependency.
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import json
import threading
import time
import urllib.request
from typing import Any, Callable

from aiohttp import web

WELL_KNOWN_PREFIX = "/.well-known/"


def _unauthorized(message: str = "unauthorized") -> web.Response:
    return web.json_response({"error": {"message": message}}, status=401)


def _b64url_decode(data: str) -> bytes:
    data += "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data)


# -- Basic auth ----------------------------------------------------------------


def basic_auth_middleware(users: dict[str, str] | None = None,
                          validator: Callable[..., bool] | None = None,
                          container=None):
    @web.middleware
    async def mw(request: web.Request, handler):
        if request.path.startswith(WELL_KNOWN_PREFIX):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        if not header.startswith("Basic "):
            return _unauthorized()
        try:
            decoded = base64.b64decode(header[6:]).decode()
            username, _, password = decoded.partition(":")
        except Exception:  # noqa: BLE001
            return _unauthorized()
        if validator is not None:
            ok = validator(container, username, password) if container is not None else validator(username, password)
            if not ok:
                return _unauthorized()
        elif users is None or users.get(username) != password:
            return _unauthorized()
        request["gofr_auth"] = {"auth_user": username, "auth_method": "basic"}
        return await handler(request)

    return mw


# -- API key auth --------------------------------------------------------------


def apikey_auth_middleware(keys: list[str] | None = None,
                           validator: Callable[..., bool] | None = None,
                           container=None):
    keyset = set(keys or [])

    @web.middleware
    async def mw(request: web.Request, handler):
        if request.path.startswith(WELL_KNOWN_PREFIX):
            return await handler(request)
        key = request.headers.get("X-API-KEY", "")
        if not key:
            return _unauthorized()
        if validator is not None:
            ok = validator(container, key) if container is not None else validator(key)
            if not ok:
                return _unauthorized()
        elif key not in keyset:
            return _unauthorized()
        request["gofr_auth"] = {"auth_user": "api-key", "auth_method": "apikey"}
        return await handler(request)

    return mw


# -- OAuth / JWT ---------------------------------------------------------------


class JWKSCache:
    """Fetches a JWKS endpoint and refreshes it on a background ticker
    (gofr `oauth.go:53-71`). Keys are kept as `cryptography` public keys."""

    def __init__(self, url: str, refresh_interval: float = 300.0, timeout: float = 5.0):
        self.url = url
        self._interval = refresh_interval
        self._timeout = timeout
        self._keys: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self.refresh()
        self._thread = threading.Thread(target=self._run, name="gofr-jwks-refresh", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.refresh()

    def stop(self) -> None:
        self._stop.set()

    def refresh(self) -> None:
        try:
            with urllib.request.urlopen(self.url, timeout=self._timeout) as resp:
                data = json.loads(resp.read())
        except Exception:  # noqa: BLE001 - keep stale keys on fetch failure
            return
        keys: dict[str, Any] = {}
        for jwk in data.get("keys", []):
            key = self._jwk_to_public_key(jwk)
            if key is not None:
                keys[jwk.get("kid", "")] = key
        if keys:
            with self._lock:
                self._keys = keys

    @staticmethod
    def _jwk_to_public_key(jwk: dict[str, Any]):
        """RSA public key from JWK n/e (gofr `oauth.go:187-207`)."""
        if jwk.get("kty") != "RSA":
            return None
        try:
            from cryptography.hazmat.primitives.asymmetric.rsa import RSAPublicNumbers

            n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
            e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
            return RSAPublicNumbers(e, n).public_key()
        except Exception:  # noqa: BLE001
            return None

    def get(self, kid: str):
        with self._lock:
            if kid in self._keys:
                return self._keys[kid]
            if len(self._keys) == 1 and not kid:
                return next(iter(self._keys.values()))
        return None


def verify_jwt(token: str, jwks: JWKSCache | None = None, hs_secret: bytes | None = None,
               audience: str | None = None, issuer: str | None = None) -> dict[str, Any]:
    """Verify a compact JWT; returns claims or raises ValueError."""
    parts = token.split(".")
    if len(parts) != 3:
        raise ValueError("malformed token")
    header_b64, payload_b64, sig_b64 = parts
    try:
        header = json.loads(_b64url_decode(header_b64))
        claims = json.loads(_b64url_decode(payload_b64))
        signature = _b64url_decode(sig_b64)
    except Exception as e:  # noqa: BLE001
        raise ValueError("malformed token") from e
    signing_input = f"{header_b64}.{payload_b64}".encode()
    alg = header.get("alg")

    if alg == "RS256":
        if jwks is None:
            raise ValueError("RS256 token but no JWKS configured")
        key = jwks.get(header.get("kid", ""))
        if key is None:
            raise ValueError(f"unknown key id {header.get('kid')!r}")
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        try:
            key.verify(signature, signing_input, padding.PKCS1v15(), hashes.SHA256())
        except InvalidSignature as e:
            raise ValueError("invalid signature") from e
    elif alg == "HS256":
        if hs_secret is None:
            raise ValueError("HS256 token but no shared secret configured")
        expected = hmac_mod.new(hs_secret, signing_input, hashlib.sha256).digest()
        if not hmac_mod.compare_digest(expected, signature):
            raise ValueError("invalid signature")
    else:
        raise ValueError(f"unsupported alg {alg!r}")

    now = time.time()
    if "exp" in claims and now > float(claims["exp"]) + 30:
        raise ValueError("token expired")
    if "nbf" in claims and now < float(claims["nbf"]) - 30:
        raise ValueError("token not yet valid")
    if audience is not None:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise ValueError("audience mismatch")
    if issuer is not None and claims.get("iss") != issuer:
        raise ValueError("issuer mismatch")
    return claims


def oauth_middleware(jwks: JWKSCache | None = None, hs_secret: bytes | None = None,
                     audience: str | None = None, issuer: str | None = None):
    @web.middleware
    async def mw(request: web.Request, handler):
        if request.path.startswith(WELL_KNOWN_PREFIX):
            return await handler(request)
        header = request.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            return _unauthorized("missing bearer token")
        try:
            claims = verify_jwt(header[7:], jwks=jwks, hs_secret=hs_secret,
                                audience=audience, issuer=issuer)
        except ValueError as e:
            return _unauthorized(str(e))
        request["gofr_auth"] = {
            "auth_user": str(claims.get("sub", "")),
            "auth_method": "oauth",
            "jwt_claims": claims,
        }
        return await handler(request)

    return mw
