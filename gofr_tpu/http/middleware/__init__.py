"""HTTP middleware chain (gofr `pkg/gofr/http/middleware/`).

Order (outermost first), matching the reference (`http_server.go:25-31`):
ws-upgrade → tracer → logging → CORS → metrics → auth (optional) → handler.
Implemented as aiohttp middlewares; each receives the shared App wiring via
``request.app``.
"""

from __future__ import annotations

import time
import traceback
import uuid
from typing import Any, Callable

from aiohttp import web

from gofr_tpu.tracing import Tracer


CONTAINER_KEY = web.AppKey("gofr_container", object)
SPAN_KEY = "gofr_span"
AUTH_KEY = "gofr_auth"
QOS_KEY = "gofr_qos_class"


def tracer_middleware(tracer: Tracer):
    @web.middleware
    async def mw(request: web.Request, handler):
        traceparent = request.headers.get("traceparent")
        span = tracer.start_span(
            f"{request.method} {request.path}", traceparent=traceparent, kind="SERVER",
            set_current=False,
        )
        span.set_attribute("http.method", request.method)
        span.set_attribute("http.target", request.path_qs)
        request[SPAN_KEY] = span
        try:
            response = await handler(request)
            span.set_attribute("http.status_code", getattr(response, "status", 0))
            if hasattr(response, "headers"):
                # clients (and support tickets) can quote the trace without
                # a propagation-aware client library
                response.headers.setdefault("X-Trace-Id", span.trace_id)
            return response
        except Exception:
            span.set_status("ERROR")
            raise
        finally:
            span.finish()

    return mw


class RequestLog:
    """Structured request record with custom terminal rendering
    (gofr `middleware/logger.go:110-122`)."""

    def __init__(self, trace_id: str, span_id: str, method: str, uri: str,
                 status: int, duration_us: int, ip: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.method = method
        self.uri = uri
        self.status = status
        self.duration_us = duration_us
        self.ip = ip

    def to_log_dict(self) -> dict[str, Any]:
        return {
            "message": "request",
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "method": self.method,
            "uri": self.uri,
            "status": self.status,
            "duration_us": self.duration_us,
            "ip": self.ip,
        }

    def pretty_print(self, w) -> None:
        color = 32 if self.status < 400 else (33 if self.status < 500 else 31)
        w.write(
            f"  \x1b[{color}m{self.status}\x1b[0m {self.method:<7} {self.uri} "
            f"{self.duration_us}µs trace={self.trace_id}\n"
        )


def logging_middleware(logger):
    @web.middleware
    async def mw(request: web.Request, handler):
        start = time.perf_counter()
        span = request.get(SPAN_KEY)
        trace_id = span.trace_id if span else ""
        span_id = span.span_id if span else ""
        correlation = trace_id or uuid.uuid4().hex
        ip = request.headers.get("X-Forwarded-For", request.remote or "")
        if "," in ip:
            ip = ip.split(",")[0].strip()
        try:
            response = await handler(request)
        except web.HTTPException as http_err:
            # aiohttp routing errors (404/405) pass through as responses
            logger.info(RequestLog(trace_id, span_id, request.method, request.path_qs,
                                   http_err.status, int((time.perf_counter() - start) * 1e6), ip))
            raise
        except Exception as exc:  # panic recovery → JSON 500 (logger.go:129-152)
            logger.error({
                "message": "panic recovered",
                "error": repr(exc),
                "stack": traceback.format_exc(),
                "trace_id": trace_id,
                "uri": request.path_qs,
            })
            response = web.json_response(
                {"error": {"message": "some unexpected error has occurred"}}, status=500
            )
        response.headers["X-Correlation-ID"] = correlation
        duration_us = int((time.perf_counter() - start) * 1e6)
        log_fn = logger.info if response.status < 500 else logger.error
        log_fn(RequestLog(trace_id, span_id, request.method, request.path_qs,
                          response.status, duration_us, ip))
        return response

    return mw


def cors_middleware(config, registered_methods: Callable[[], list[str]]):
    def _hdr(name: str, default: str) -> str:
        return config.get_or_default(name, default) if config else default

    @web.middleware
    async def mw(request: web.Request, handler):
        if request.method == "OPTIONS":
            response = web.Response(status=200)
        else:
            response = await handler(request)
        methods = _hdr("ACCESS_CONTROL_ALLOW_METHODS", ", ".join(registered_methods()))
        response.headers.setdefault("Access-Control-Allow-Origin", _hdr("ACCESS_CONTROL_ALLOW_ORIGIN", "*"))
        response.headers.setdefault("Access-Control-Allow-Methods", methods)
        response.headers.setdefault(
            "Access-Control-Allow-Headers",
            _hdr("ACCESS_CONTROL_ALLOW_HEADERS", "Authorization, Content-Type, x-requested-with, X-API-KEY"),
        )
        return response

    return mw


def qos_middleware(controller):
    """Admission control at the transport edge (QoS tier 1/2 — see
    gofr_tpu.qos): rate limits and backlog shedding answer 429/503 with a
    ``Retry-After`` header BEFORE the handler (and therefore the model
    engine) sees the request. The resolved priority class rides on the
    request so ``ctx.generate``/``ctx.infer`` schedule it without handler
    cooperation. Well-known/health routes always pass — a load balancer
    probing an overloaded instance must still see its health."""
    from gofr_tpu.http.errors import retry_after_hint

    @web.middleware
    async def mw(request: web.Request, handler):
        if request.path.startswith("/.well-known/") or request.path == "/favicon.ico":
            return await handler(request)
        cls_name = controller.classify(request.headers)
        span = request.get(SPAN_KEY)
        if span is not None:
            # the admission verdict belongs on the request's trace: a shed
            # request's span shows WHY it never reached the engine
            span.set_attribute("qos.class", cls_name)
        route = request.match_info.route
        template = (getattr(route.resource, "canonical", request.path)
                    if route and route.resource else request.path)
        decision = controller.admit_transport(
            route=template,
            api_key=request.headers.get("X-API-KEY", ""),
            tenant=request.headers.get(controller.policy.tenant_header, ""),
            cls_name=cls_name,
        )
        if not decision.allowed:
            if span is not None:
                span.set_attribute("qos.rejected", decision.reason)
            return web.json_response(
                {"error": {"message": decision.message}},
                status=decision.status,
                headers={"Retry-After": retry_after_hint(decision.retry_after)},
            )
        request[QOS_KEY] = cls_name
        return await handler(request)

    return mw


def metrics_middleware(metrics):
    @web.middleware
    async def mw(request: web.Request, handler):
        start = time.perf_counter()
        status = 500
        try:
            response = await handler(request)
            status = response.status
            return response
        except web.HTTPException as e:
            status = e.status
            raise
        finally:
            route = request.match_info.route
            template = getattr(route.resource, "canonical", request.path) if route and route.resource else request.path
            metrics.record_histogram(
                "app_http_response", time.perf_counter() - start,
                path=template, method=request.method, status=str(status),
            )

    return mw
