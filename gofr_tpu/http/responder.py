"""JSON-envelope responder (gofr `pkg/gofr/http/responder.go`).

Turns a handler's ``(result, error)`` into wire form: ``{"data": ...}`` on
success, ``{"error": {"message": ...}}`` on failure; status derived from the
method and the error's ``status_code`` (POST→201, DELETE→204, typed errors keep
their code). ``Raw``/``File``/``Redirect``/``Response`` bypass or extend the
envelope.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from gofr_tpu.http.errors import retry_after_hint, status_of
from gofr_tpu.http.responses import File, Passthrough, Raw, Redirect, Response


def _default(o: Any) -> Any:
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return dataclasses.asdict(o)
    if hasattr(o, "to_dict"):
        return o.to_dict()
    if hasattr(o, "tolist"):  # numpy / jax arrays
        return o.tolist()
    if hasattr(o, "item") and getattr(o, "shape", None) == ():
        return o.item()
    if isinstance(o, bytes):
        return o.decode(errors="replace")
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    return str(o)


def to_json(data: Any) -> bytes:
    return json.dumps(data, default=_default).encode()


@dataclasses.dataclass
class WireResponse:
    status: int
    body: bytes
    content_type: str = "application/json"
    headers: dict[str, str] = dataclasses.field(default_factory=dict)


def respond(result: Any, err: BaseException | None, method: str = "GET") -> WireResponse:
    if err is not None:
        status = status_of(err, method)
        message = getattr(err, "message", None) or str(err) or "internal server error"
        if status >= 500 and not getattr(err, "status_code", None):
            # don't leak internals for unexpected exceptions
            message = "some unexpected error has occurred"
        headers = {}
        retry_after = getattr(err, "retry_after", None)
        if retry_after is not None and status in (429, 503):
            # QoS rejections (429 rate / 503 shed) tell clients WHEN to come
            # back instead of inviting an immediate retry storm
            headers["Retry-After"] = retry_after_hint(retry_after)
        return WireResponse(status, to_json({"error": {"message": message}}), headers=headers)

    if isinstance(result, Passthrough):
        return WireResponse(result.status_code, result.body,
                            content_type=result.content_type,
                            headers=dict(result.headers))
    if isinstance(result, Redirect):
        return WireResponse(result.status_code, b"", headers={"Location": result.url})
    if isinstance(result, File):
        return WireResponse(200, result.content, content_type=result.content_type)
    if isinstance(result, Raw):
        return WireResponse(status_of(None, method), to_json(result.data))
    if isinstance(result, Response):
        status = result.status_code if result.status_code is not None else status_of(None, method)
        return WireResponse(status, to_json({"data": result.data}), headers=dict(result.headers))

    status = status_of(None, method)
    if status == 204:
        return WireResponse(204, b"")
    return WireResponse(status, to_json({"data": result}))
