"""Typed HTTP errors carrying their status code.

Parity with gofr `pkg/gofr/http/errors.go`: each error knows its HTTP status
(the responder consults ``status_code``); user code can raise these from any
handler (HTTP, gRPC, pub/sub, cron) and the transport maps them appropriately.
Any exception with a ``status_code`` attribute participates (the reference's
``statusCodeResponder`` interface).
"""

from __future__ import annotations


class HTTPError(Exception):
    status_code: int = 500
    # seconds until a retry is worth attempting; the responder surfaces it
    # as a Retry-After header (gRPC: retry-after trailing metadata)
    retry_after: float | None = None

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message or self.default_message()

    def default_message(self) -> str:
        return "internal server error"

    def __str__(self) -> str:
        return self.message


class EntityNotFound(HTTPError):
    status_code = 404

    def __init__(self, name: str = "", value: str = ""):
        self.name, self.value = name, value
        msg = f"No entity found with {name}: {value}" if name else "entity not found"
        super().__init__(msg)


class EntityAlreadyExists(HTTPError):
    status_code = 409

    def default_message(self) -> str:
        return "entity already exists"


class InvalidParam(HTTPError):
    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        n = len(self.params)
        super().__init__(f"'{n}' invalid parameter(s): {', '.join(self.params)}" if n else "invalid parameter")


class MissingParam(HTTPError):
    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        n = len(self.params)
        super().__init__(f"'{n}' missing parameter(s): {', '.join(self.params)}" if n else "missing parameter")


class InvalidRoute(HTTPError):
    status_code = 404

    def default_message(self) -> str:
        return "route not registered"


class RequestTimeout(HTTPError):
    status_code = 408

    def default_message(self) -> str:
        return "request timed out"


class PanicRecovery(HTTPError):
    status_code = 500

    def default_message(self) -> str:
        return "some unexpected error has occurred"


class Unauthorized(HTTPError):
    status_code = 401

    def default_message(self) -> str:
        return "unauthorized"


class Forbidden(HTTPError):
    status_code = 403

    def default_message(self) -> str:
        return "forbidden"


class TooManyRequests(HTTPError):
    """Rate limit / concurrency cap exceeded (QoS tier 1): the request is
    well-formed but the caller is over its budget — retryable after
    ``retry_after`` seconds."""

    status_code = 429

    def __init__(self, message: str = "", retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = retry_after

    def default_message(self) -> str:
        return "too many requests"


class ServiceUnavailable(HTTPError):
    status_code = 503

    def __init__(self, message: str = "", retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = retry_after

    def default_message(self) -> str:
        return "service unavailable"


class DeadlineExceeded(HTTPError):
    """The request's propagated deadline (``X-Request-Deadline-Ms`` or
    the gRPC deadline) is already unmeetable: either expired outright or
    the predicted queue wait exceeds the remaining budget. 504 — unlike
    408 (server-side timeout) and 503 (server refuses work it COULD do
    later), a 504 tells the caller its own clock ran out: retrying the
    same deadline is pointless. Not retryable, so no ``retry_after``."""

    status_code = 504

    def default_message(self) -> str:
        return "deadline exceeded"


def retry_after_hint(seconds: float) -> str:
    """One formatting site for every transport's retry hint (HTTP
    ``Retry-After`` header, gRPC ``retry-after`` trailing metadata):
    whole seconds, floored at 1 so a sub-second hint never reads as 0."""
    import math

    return str(max(1, math.ceil(float(seconds))))


def status_of(err: BaseException | None, method: str = "GET", has_result: bool = False) -> int:
    """Map (error, method) to an HTTP status (gofr `http/responder.go:52-66`)."""
    if err is None:
        if method == "POST":
            return 201
        if method == "DELETE":
            return 204
        return 200
    code = getattr(err, "status_code", None)
    if isinstance(code, int):
        return code
    if isinstance(err, TimeoutError):
        return 408
    return 500
