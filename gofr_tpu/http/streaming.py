"""Streaming responses: SSE over HTTP, per-message over websocket.

The reference streams long work over websockets (`pkg/gofr/websocket.go:37-53`);
the TPU-native analog is token streaming out of a generate engine. A handler
returns ``StreamingResponse(engine-or-ctx stream iterator)`` and the app
drives it:

- HTTP route: ``text/event-stream`` — one ``data: <json>`` event per item,
  then a terminal ``event: done`` (or ``event: error``) frame.
- Websocket route: one websocket message per item.

The iterator may block (the engine's stream queue does), so the app pulls
items on the handler executor, never on the event loop.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Iterator


class StreamingResponse:
    """Wraps a (possibly blocking) iterator of items for incremental
    delivery. ``event`` names the SSE event type for data frames."""

    def __init__(self, iterator: Iterable[Any], *, event: str | None = None):
        self.iterator: Iterator[Any] = iter(iterator)
        self.event = event

    def encode_sse(self, item: Any) -> bytes:
        prefix = f"event: {self.event}\n" if self.event else ""
        return f"{prefix}data: {json.dumps(item)}\n\n".encode()

    @staticmethod
    def sse_done() -> bytes:
        return b"event: done\ndata: {}\n\n"

    @staticmethod
    def sse_error(message: str) -> bytes:
        return f"event: error\ndata: {json.dumps({'message': message})}\n\n".encode()

    def encode_ws(self, item: Any) -> str:
        """Every frame is JSON: data items encode to JSON values (a text
        piece arrives as a JSON string, a token id as a number), and the
        terminal control frame is the object ``{"done": true}`` — a
        streamed piece whose TEXT is '{"done": true}' encodes to a JSON
        string and stays unambiguously data."""
        return json.dumps(item)


class RawStreamingResponse:
    """Raw-bytes streaming passthrough: the handler supplies an iterator of
    wire chunks plus the status/headers to send, and the app writes them
    through verbatim — no SSE encoding, no envelope. This is the proxy
    shape (router data plane forwarding a replica's SSE stream): the
    upstream bytes, event framing included, reach the client as produced.

    ``close`` (or the iterator's own ``close``) is invoked when the client
    disconnects mid-stream, so the proxied upstream transfer is aborted
    instead of draining to a ghost."""

    def __init__(self, iterator: Iterable[bytes], *, status: int = 200,
                 headers: dict[str, str] | None = None,
                 content_type: str = "application/octet-stream",
                 close: Any = None):
        self.iterator: Iterator[bytes] = iter(iterator)
        self.status = int(status)
        self.headers = dict(headers or {})
        self.content_type = content_type
        self._close = close

    def close(self) -> None:
        for closer in (self._close, getattr(self.iterator, "close", None)):
            if callable(closer):
                try:
                    closer()
                except Exception:  # noqa: BLE001 - teardown must not mask the cause
                    pass
