"""QoS subsystem: admission control, priority scheduling, rate limiting,
and load shedding across the serving stack.

The reference framework accepts every request and lets it time out inside
the stack; under overload that *burns a device slot per doomed request*.
This subsystem rejects at the edge instead, in three tiers:

1. **Rate limiting** (``qos/limiter.py``) — token buckets, global and
   keyed by route / API key / tenant. Over-rate traffic gets HTTP 429
   (gRPC ``RESOURCE_EXHAUSTED``) with a ``Retry-After`` hint.
2. **Priority scheduling** (``qos/scheduler.py``) — the engines' FIFO
   queue becomes a weighted-fair, deadline-aware priority queue
   (``interactive`` > ``default`` > ``batch``); FIFO semantics are
   byte-for-byte preserved while QoS is off.
3. **Admission control + load shedding** (this module) — per-class
   concurrency caps, a max-backlog gate, and a queue-wait estimator
   (EWMA of ``app_tpu_step_seconds`` × backlog / lanes) that rejects
   work whose predicted wait already exceeds its deadline — HTTP 503
   with ``Retry-After``, *before* the request occupies anything.

Wiring: ``app.enable_qos()`` (or ``QOS_ENABLED=true``) builds one
``AdmissionController`` from ``QOS_*`` config, registers it on the
container (health: ``DEGRADED`` while shedding), inserts the HTTP
middleware and gRPC interceptor, and binds every served engine
(``bind_engine`` flips the engine queue into priority mode and starts the
wait estimator). Observability: ``app_qos_admitted_total``,
``app_qos_rejected_total`` (by reason/class), ``app_qos_shed_total``,
per-class ``app_qos_queue_depth`` gauges, ``app_qos_queue_wait_seconds``,
and per-engine ``app_qos_predicted_wait_seconds``; per-request, the
admission verdict rides the trace (``qos.class`` / ``qos.rejected`` span
attributes) and the class labels ``app_tpu_e2e_seconds`` plus the flight
recorder's ``/debug/requests`` timelines (docs/observability.md).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from gofr_tpu.http.errors import (DeadlineExceeded, ServiceUnavailable,
                                  TooManyRequests)
from gofr_tpu.qos.limiter import KeyedBuckets, TokenBucket
from gofr_tpu.qos.scheduler import QoSQueue

__all__ = [
    "AdmissionController",
    "Decision",
    "KeyedBuckets",
    "PriorityClass",
    "QoSPolicy",
    "QoSQueue",
    "TokenBucket",
]


@dataclass
class PriorityClass:
    """One scheduling class. ``weight`` sets the weighted-fair share under
    saturation; ``max_concurrency`` caps submitted-but-unfinished requests
    of this class per engine (0 = uncapped)."""

    name: str
    weight: float = 1.0
    max_concurrency: int = 0


# rank-ordered: interactive beats default beats batch at equal funding
DEFAULT_CLASSES = (
    PriorityClass("interactive", weight=8.0),
    PriorityClass("default", weight=4.0),
    PriorityClass("batch", weight=1.0),
)


@dataclass
class QoSPolicy:
    """Declarative QoS policy (config keys in parentheses; docs/qos.md).

    ``classes`` must be rank-ordered, highest priority first. The policy
    is also the class vocabulary OUTSIDE this process: the data-plane
    router (gofr_tpu.router) builds one from the same config to resolve
    ``X-QoS-Class`` and decide spillover, so router and replicas agree on
    what an unknown class means (docs/routing.md)."""

    classes: list[PriorityClass] = field(default_factory=lambda: list(DEFAULT_CLASSES))
    default_class: str = "default"          # QOS_DEFAULT_CLASS
    rate_rps: float = 0.0                   # QOS_RATE_RPS (global; 0 = off)
    rate_burst: float = 0.0                 # QOS_RATE_BURST (default = rps)
    route_rps: float = 0.0                  # QOS_ROUTE_RPS (per route)
    key_rps: float = 0.0                    # QOS_KEY_RPS (per X-API-KEY)
    tenant_rps: float = 0.0                 # QOS_TENANT_RPS (per X-Tenant-ID)
    max_queue: int = 0                      # QOS_MAX_QUEUE (backlog shed; 0 = off)
    shed_window_s: float = 10.0             # QOS_SHED_WINDOW_S (DEGRADED window)
    shed_on_burn: bool = False              # QOS_SHED_ON_BURN (SLO pressure signal)
    class_header: str = "X-QoS-Class"       # QOS_CLASS_HEADER
    tenant_header: str = "X-Tenant-ID"      # QOS_TENANT_HEADER

    def __post_init__(self):
        self._by_name = {c.name: c for c in self.classes}
        if self.default_class not in self._by_name:
            raise ValueError(
                f"QoS default class {self.default_class!r} is not one of "
                f"{sorted(self._by_name)}"
            )

    def resolve(self, name: str | None) -> PriorityClass:
        """Class by name; unknown/absent names land in the default class
        (a client must not gain OR lose service by inventing a class)."""
        if name:
            cls = self._by_name.get(str(name))
            if cls is not None:
                return cls
        return self._by_name[self.default_class]

    @classmethod
    def from_config(cls, config, **overrides: Any) -> "QoSPolicy":
        """Build from ``QOS_*`` config keys; ``overrides`` win (the
        ``enable_qos(**kw)`` programmatic path). ``QOS_CLASSES`` is
        ``name:weight[:max_concurrency],...`` rank-ordered, e.g.
        ``interactive:8:16,default:4,batch:1:4``."""
        kw: dict[str, Any] = {}
        spec = config.get_or_default("QOS_CLASSES", "")
        if spec:
            classes = []
            for part in spec.split(","):
                bits = part.strip().split(":")
                if not bits[0]:
                    continue
                classes.append(PriorityClass(
                    bits[0],
                    weight=float(bits[1]) if len(bits) > 1 and bits[1] else 1.0,
                    max_concurrency=int(bits[2]) if len(bits) > 2 and bits[2] else 0,
                ))
            if classes:
                kw["classes"] = classes
                kw["default_class"] = config.get_or_default(
                    "QOS_DEFAULT_CLASS",
                    "default" if any(c.name == "default" for c in classes)
                    else classes[-1].name)
        else:
            kw["default_class"] = config.get_or_default("QOS_DEFAULT_CLASS", "default")
        kw["rate_rps"] = config.get_float("QOS_RATE_RPS", 0.0)
        kw["rate_burst"] = config.get_float("QOS_RATE_BURST", 0.0)
        kw["route_rps"] = config.get_float("QOS_ROUTE_RPS", 0.0)
        kw["key_rps"] = config.get_float("QOS_KEY_RPS", 0.0)
        kw["tenant_rps"] = config.get_float("QOS_TENANT_RPS", 0.0)
        kw["max_queue"] = config.get_int("QOS_MAX_QUEUE", 0)
        kw["shed_window_s"] = config.get_float("QOS_SHED_WINDOW_S", 10.0)
        kw["shed_on_burn"] = config.get_bool("QOS_SHED_ON_BURN")
        kw["class_header"] = config.get_or_default("QOS_CLASS_HEADER", "X-QoS-Class")
        kw["tenant_header"] = config.get_or_default("QOS_TENANT_HEADER", "X-Tenant-ID")
        kw.update(overrides)
        return cls(**kw)


@dataclass
class Decision:
    """Transport-tier admission verdict. ``status`` is the HTTP status the
    transport should return (gRPC maps 429 → RESOURCE_EXHAUSTED, 503 →
    UNAVAILABLE); ``retry_after`` feeds the Retry-After header/metadata."""

    allowed: bool
    status: int = 200
    retry_after: float = 0.0
    reason: str = ""
    message: str = ""


class AdmissionController:
    """The QoS brain: owns the policy, the rate-limit buckets, the
    per-class concurrency accounting, and the queue-wait estimator.

    One controller serves the whole app — transports call
    ``admit_transport`` before handlers run; bound engines call
    ``admit_engine`` inside ``_submit`` (rejections raise typed HTTP
    errors that every transport already maps, carrying ``retry_after``).
    """

    def __init__(self, policy: QoSPolicy, metrics, logger=None):
        self.policy = policy
        self.metrics = metrics
        self.logger = logger
        burst = policy.rate_burst or None
        self._global = TokenBucket(policy.rate_rps, burst)
        self._routes = KeyedBuckets(policy.route_rps)
        self._keys = KeyedBuckets(policy.key_rps)
        self._tenants = KeyedBuckets(policy.tenant_rps)
        self._engines: dict[str, Any] = {}
        self._inflight: dict[str, int] = {c.name: 0 for c in policy.classes}
        self._ewma_step = 0.0
        self._last_shed = 0.0
        self._lock = threading.Lock()

    # -- engine binding --------------------------------------------------------

    def bind_engine(self, name: str, engine) -> None:
        """Attach QoS to an engine: flips its queue into priority mode and
        points the engine's submit/step hooks at this controller."""
        self._engines[name] = engine
        queue = getattr(engine, "_queue", None)
        if isinstance(queue, QoSQueue):
            queue.set_policy(self.policy, metrics=self.metrics)
        engine.qos = self

    @property
    def engines(self) -> dict[str, Any]:
        return dict(self._engines)

    # -- wait estimation -------------------------------------------------------

    def observe_step(self, seconds: float) -> None:
        """EWMA of device-step wall time, fed by ``_record_step`` on every
        bound engine (one estimator app-wide: steps across engines in one
        process contend for the same host/device anyway). Under the unified
        async pipeline steps are observed at COMPLETION (dequeue) time, so
        a sample spans dispatch→fold — slightly pessimistic while calls
        overlap, which is the right bias for shedding hopeless work."""
        with self._lock:
            self._ewma_step = (seconds if self._ewma_step == 0.0
                               else 0.2 * seconds + 0.8 * self._ewma_step)

    def predicted_wait(self, engine) -> float:
        """Estimated queue wait: EWMA step seconds × backlog / lanes, where
        lanes is the engine's concurrency (decode slots or max batch) — an
        upper-ish bound that only has to be right about *hopeless*, not
        about milliseconds."""
        backlog = engine._backlog()
        if backlog <= 0:
            return 0.0
        lanes = max(1, int(getattr(engine, "num_slots", 0)
                           or getattr(engine, "max_batch", 1)))
        return self._ewma_step * math.ceil(backlog / lanes)

    def max_predicted_wait(self) -> float:
        """Worst predicted queue wait across every bound engine — one of
        the two pressure signals the fleet autoscaler scales out on
        (fleet/autoscaler.py; the other is the SLO fast-window burn)."""
        return max((self.predicted_wait(e) for e in self._engines.values()),
                   default=0.0)

    # -- admission -------------------------------------------------------------

    def classify(self, headers) -> str:
        """Priority-class name from request headers (unknown → default)."""
        raw = headers.get(self.policy.class_header) if headers else None
        return self.policy.resolve(raw).name

    def admit_transport(self, route: str = "", api_key: str = "",
                        tenant: str = "", cls_name: str | None = None) -> Decision:
        """Tier-1 gate, called by the HTTP middleware / gRPC interceptor
        before the handler runs: rate limits (429), then backlog shedding
        (503). Admission increments ``app_qos_admitted_total``."""
        cls = self.policy.resolve(cls_name)
        # most-specific limiter first, short-circuiting: a flooding tenant
        # must be rejected by ITS bucket before any shared bucket is
        # consulted — eager evaluation here would let doomed traffic drain
        # the global budget and starve well-behaved tenants
        for reason, acquire in (
            ("tenant_rate", (lambda: self._tenants.acquire(tenant)) if tenant else None),
            ("key_rate", (lambda: self._keys.acquire(api_key)) if api_key else None),
            ("route_rate", (lambda: self._routes.acquire(route)) if route else None),
            ("rate", lambda: self._global.acquire()),
        ):
            wait = acquire() if acquire is not None else 0.0
            if wait > 0.0:
                self._reject(cls, reason, 429, wait)
                return Decision(False, 429, wait, reason,
                                "rate limit exceeded; retry later")
        if self.policy.max_queue and self._engines:
            # max_queue is a PER-ENGINE ceiling (admit_engine enforces it
            # for the request's actual engine); the transport — which does
            # not know the target engine yet — sheds only when EVERY bound
            # engine is at the ceiling, so one full engine can't 503
            # traffic headed for an idle one
            backlog = min(e._backlog() for e in self._engines.values())
            if backlog >= self.policy.max_queue:
                wait = max((self.predicted_wait(e) for e in self._engines.values()),
                           default=1.0) or 1.0
                self._reject(cls, "queue", 503, wait)
                return Decision(False, 503, wait, "queue",
                                "server overloaded; retry later")
        self.metrics.increment_counter("app_qos_admitted_total", 1,
                                       qos_class=cls.name)
        return Decision(True)

    def admit_engine(self, engine, cls_name: str | None,
                     timeout: float | None) -> PriorityClass:
        """Tier-3 gate, called by ``_EngineBase._submit``: backlog cap,
        per-class concurrency cap, then the deadline check — if the
        predicted queue wait already exceeds the request's remaining
        budget (propagated deadline or explicit timeout) it is rejected
        NOW with 504/``deadline_exceeded`` instead of burning a slot and
        timing out later (docs/resilience.md). Returns the resolved
        class (capacity acquired; released by the request's done
        callback via ``track``)."""
        cls = self.policy.resolve(cls_name)
        if getattr(engine, "_restarting", False):
            # shed-during-restart: the device loop is inside its crash-
            # recovery backoff window — new work would only deepen the
            # backlog the restarted loop must drain (queued work already
            # there survives the restart; docs/qos.md)
            wait = self._ewma_step or 1.0
            self._reject(cls, "restart", 503, wait)
            raise ServiceUnavailable(
                "engine restarting after a device fault; retry later",
                retry_after=wait)
        if self.policy.shed_on_burn:
            # SLO pressure signal (metrics/slo.py, QOS_SHED_ON_BURN): while
            # a strictly higher-priority class is burning its fast-window
            # error budget, lower classes are shed — the freed capacity is
            # exactly what the burning class needs (docs/qos.md)
            slo = getattr(engine, "slo", None)
            if slo is not None and slo.should_shed(cls.name):
                wait = self._ewma_step or 1.0
                self._reject(cls, "slo_burn", 503, wait)
                raise ServiceUnavailable(
                    f"class {cls.name!r} shed while a higher class burns "
                    "its SLO error budget; retry later", retry_after=wait)
        if self.policy.max_queue and engine._backlog() >= self.policy.max_queue:
            wait = self.predicted_wait(engine) or 1.0
            self._reject(cls, "queue", 503, wait)
            raise ServiceUnavailable("engine queue full; retry later",
                                     retry_after=wait)
        predicted = self.predicted_wait(engine)
        if timeout and predicted > timeout:
            # the request-lifetime plane (docs/resilience.md): the caller's
            # budget — propagated deadline or explicit timeout — cannot be
            # met even before a slot is taken. 504/DEADLINE_EXCEEDED, not
            # 503: retrying the same deadline is pointless, so no hint.
            self._reject(cls, "deadline_exceeded", 504, predicted)
            self.metrics.increment_counter(
                "app_request_deadline_exceeded_total", 1, where="qos")
            raise DeadlineExceeded(
                f"predicted queue wait {predicted:.2f}s exceeds deadline "
                f"{timeout:.2f}s")
        if cls.max_concurrency:
            with self._lock:
                if self._inflight[cls.name] >= cls.max_concurrency:
                    wait = predicted or self._ewma_step or 1.0
                    capped = True
                else:
                    self._inflight[cls.name] += 1
                    capped = False
            if capped:
                self._reject(cls, "capacity", 429, wait)
                raise TooManyRequests(
                    f"class {cls.name!r} at its concurrency cap "
                    f"({cls.max_concurrency})", retry_after=wait)
        self.metrics.increment_counter("app_qos_admitted_total", 1,
                                       qos_class=cls.name)
        return cls

    def track(self, request, cls: PriorityClass) -> None:
        """Release the class's concurrency share when the request
        completes (success, error, timeout, or engine death alike)."""
        if cls.max_concurrency:
            request.add_done_callback(lambda _r: self._release(cls.name))

    def _release(self, name: str) -> None:
        with self._lock:
            self._inflight[name] = max(0, self._inflight[name] - 1)

    def _reject(self, cls: PriorityClass, reason: str, status: int,
                retry_after: float) -> None:
        self.metrics.increment_counter("app_qos_rejected_total", 1,
                                       reason=reason, qos_class=cls.name)
        if reason in ("queue", "deadline_exceeded", "capacity", "restart",
                      "slo_burn"):
            # overload-driven (we turned away feasible work because of
            # load), as opposed to a client exceeding its rate budget —
            # this is what flips health to DEGRADED for the shed window
            self.metrics.increment_counter("app_qos_shed_total", 1,
                                           reason=reason)
            with self._lock:
                self._last_shed = time.monotonic()

    # -- observability ---------------------------------------------------------

    @property
    def shedding(self) -> bool:
        """True while a 503 shed happened within the policy window — the
        health signal (DEGRADED) operators and load balancers act on."""
        return (time.monotonic() - self._last_shed) < self.policy.shed_window_s \
            if self._last_shed else False

    def health_check(self) -> dict[str, Any]:
        details = {
            "inflight": dict(self._inflight),
            "ewma_step_s": round(self._ewma_step, 6),
        }
        if self.shedding:
            details["shedding"] = True
            return {"status": "DEGRADED", "details": details}
        return {"status": "UP", "details": details}

    def sample_gauges(self, _registry=None) -> None:
        """Metrics collect hook: per-class queue depth (summed across
        engines) and per-engine predicted wait, refreshed on scrape."""
        depths: dict[str, int] = {c.name: 0 for c in self.policy.classes}
        for name, engine in self._engines.items():
            q = getattr(engine, "_queue", None)
            if isinstance(q, QoSQueue):
                for cname, depth in q.depths().items():
                    depths[cname] = depths.get(cname, 0) + depth
            self.metrics.set_gauge("app_qos_predicted_wait_seconds",
                                   self.predicted_wait(engine), engine=name)
        for cname, depth in depths.items():
            self.metrics.set_gauge("app_qos_queue_depth", depth, qos_class=cname)
