"""Token-bucket rate limiting (QoS tier 1).

A ``TokenBucket`` admits up to ``burst`` requests instantly and refills at
``rate`` tokens/second — the standard shape for per-route / per-API-key /
per-tenant request limits (the reference framework has no rate limiting at
all; its resilience surface stops at the inter-service circuit breaker,
``gofr_tpu/service``). ``KeyedBuckets`` fans one (rate, burst) policy out
over an LRU-bounded key space so an attacker spraying unique API keys
cannot grow host memory without bound.

Thread-safety: transports call ``acquire`` from handler threads and the
asyncio loop concurrently; every bucket mutation happens under a lock.
Rejections return the *retry-after* hint (seconds until one token exists)
so the transport can emit ``Retry-After`` / RESOURCE_EXHAUSTED metadata
instead of a bare refusal.
"""

from __future__ import annotations

import collections
import threading
import time


class TokenBucket:
    """Classic token bucket: ``burst`` capacity, ``rate`` tokens/second.

    ``acquire(n)`` returns 0.0 when admitted, else the seconds until the
    bucket could admit ``n`` tokens (the Retry-After hint). ``rate <= 0``
    disables the limiter (always admits).
    """

    __slots__ = ("rate", "burst", "_tokens", "_stamp", "_lock")

    def __init__(self, rate: float, burst: float | None = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(1.0, self.rate)
        self._tokens = self.burst
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self, n: float = 1.0, now: float | None = None) -> float:
        if self.rate <= 0:
            return 0.0
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst, self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate

    def peek(self, now: float | None = None) -> float:
        """Current token count (test/introspection hook; no side effects
        beyond the refill fold)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst, self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            return self._tokens


class KeyedBuckets:
    """One (rate, burst) policy per dynamic key (route, API key, tenant).

    Keys are LRU-bounded at ``max_keys``: evicting a stale key merely
    resets its bucket to full burst, which only ever errs in the client's
    favor — bounded memory is worth that slack.
    """

    def __init__(self, rate: float, burst: float | None = None, max_keys: int = 4096):
        self.rate = float(rate)
        self.burst = burst
        self.max_keys = max_keys
        self._buckets: collections.OrderedDict[str, TokenBucket] = collections.OrderedDict()
        self._lock = threading.Lock()

    def acquire(self, key: str, n: float = 1.0) -> float:
        if self.rate <= 0:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst)
                self._buckets[key] = bucket
                while len(self._buckets) > self.max_keys:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(key)
        return bucket.acquire(n)

    def __len__(self) -> int:
        return len(self._buckets)
