"""Priority scheduling (QoS tier 2): the engines' queue, QoS-aware.

``QoSQueue`` is a drop-in replacement for the FIFO ``queue.Queue`` both
engines drain (``_EngineBase._queue``): same ``put/get/get_nowait/qsize``
surface, same blocking/timeout/Empty semantics (it subclasses
``queue.Queue`` and overrides only the storage hooks, so all locking and
condition-variable behavior is literally the stdlib's).

Two modes:

- **FIFO (default, QoS off)** — storage is the same ``collections.deque``
  ``queue.Queue`` uses; behavior is byte-for-byte the seed engine's, so
  existing engine tests and the EDF prefill planner in ``native/`` see no
  change.
- **Priority (after ``set_policy``)** — one EDF heap per priority class
  (ordered by ``(deadline, arrival)``; no deadline sorts last so deadline
  traffic overtakes best-effort inside its class), scheduled across
  classes by *weighted fair credits*: every replenish cycle grants each
  class ``weight`` credits, and ``get`` serves the highest-priority
  funded non-empty class. Under saturation classes drain in weight
  proportion (e.g. interactive:default:batch = 8:4:1) while idle classes
  never block others and no class starves.

Items are duck-typed: a priority class rides on ``item.kw["_qos_class"]``
and the deadline on ``item.deadline`` (the engine ``Request`` shape);
anything else lands in the default class as best-effort.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import math
import queue
import time
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from gofr_tpu.qos import QoSPolicy


class QoSQueue(queue.Queue):
    def __init__(self, policy: "QoSPolicy | None" = None, metrics=None):
        super().__init__()  # calls _init
        if policy is not None:
            self.set_policy(policy, metrics=metrics)

    # -- queue.Queue storage hooks (called under self.mutex) -------------------

    def _init(self, maxsize: int) -> None:
        self.queue: collections.deque = collections.deque()  # FIFO-mode storage
        self._policy: "QoSPolicy | None" = None
        self._metrics = None
        self._heaps: dict[str, list] = {}
        self._credits: dict[str, float] = {}
        self._seq = itertools.count()

    def _qsize(self) -> int:
        if self._policy is None:
            return len(self.queue)
        return sum(len(h) for h in self._heaps.values())

    def _put(self, item) -> None:
        if self._policy is None:
            self.queue.append(item)
        else:
            self._route(item)

    def _get(self):
        if self._policy is None:
            return self.queue.popleft()
        item = self._pick()
        if self._metrics is not None:
            enq = getattr(item, "enqueued_at", None)
            if enq is not None:
                cls = self._policy.resolve(getattr(item, "kw", {}).get("_qos_class"))
                self._metrics.record_histogram(
                    "app_qos_queue_wait_seconds", time.monotonic() - enq,
                    qos_class=cls.name,
                )
        return item

    # -- QoS mode --------------------------------------------------------------

    def set_policy(self, policy: "QoSPolicy", metrics=None) -> None:
        """Flip FIFO → priority scheduling, or swap policies. ALL queued
        work is re-routed under the new policy — the FIFO deque on first
        enable, and the old class heaps when a controller re-registers
        (dropping heap backlog would strand accepted requests until their
        callers time out)."""
        with self.mutex:
            backlog = list(self.queue)
            self.queue.clear()
            for heap in self._heaps.values():
                backlog.extend(entry[2] for entry in sorted(heap))
            self._policy = policy
            self._metrics = metrics
            self._heaps = {c.name: [] for c in policy.classes}
            self._credits = {c.name: float(c.weight) for c in policy.classes}
            for item in backlog:
                self._route(item)

    def _route(self, item) -> None:
        cls = self._policy.resolve(getattr(item, "kw", {}).get("_qos_class"))
        deadline = getattr(item, "deadline", None)
        key = deadline if deadline is not None else math.inf
        heapq.heappush(self._heaps[cls.name], (key, next(self._seq), item))

    def _pick(self):
        # policy.classes is rank-ordered (interactive first): among funded
        # non-empty classes the highest priority wins; when every waiting
        # class is out of credit, replenish all by weight — one cycle hands
        # out `weight` turns per class, which is the fairness guarantee.
        nonempty = [c for c in self._policy.classes if self._heaps[c.name]]
        funded = [c for c in nonempty if self._credits[c.name] >= 1.0]
        if not funded:
            for c in self._policy.classes:
                self._credits[c.name] = min(
                    self._credits[c.name] + c.weight, 2.0 * c.weight)
            funded = [c for c in nonempty if self._credits[c.name] >= 1.0] or nonempty
        cls = funded[0]
        self._credits[cls.name] -= 1.0
        return heapq.heappop(self._heaps[cls.name])[2]

    def wait_nonempty(self, timeout: float) -> bool:
        """Block until the queue is non-empty (or timeout), WITHOUT
        consuming — the engine's idle poke. A get/put round trip here
        would record a spurious queue-wait sample and debit a fairness
        credit per idle loop iteration."""
        with self.not_empty:
            if not self._qsize():
                self.not_empty.wait(timeout)
            return bool(self._qsize())

    def depths(self) -> dict[str, int]:
        """Per-class backlog snapshot (the ``app_qos_queue_depth`` gauge);
        empty in FIFO mode."""
        with self.mutex:
            if self._policy is None:
                return {}
            return {name: len(h) for name, h in self._heaps.items()}
