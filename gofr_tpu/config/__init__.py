"""Config: 12-factor env configuration with dotenv layering.

Mirrors the reference's config semantics (gofr `pkg/gofr/config/godotenv.go:34-68`):
load ``./configs/.env`` first, then overlay ``.{APP_ENV}.env`` (or ``.local.env``
when APP_ENV is unset); every read ultimately consults the process environment so
real env vars always win.
"""

from __future__ import annotations

import os
from typing import Mapping, Protocol


class Config(Protocol):
    """Consumer-facing config interface (gofr `pkg/gofr/config/config.go`)."""

    def get(self, key: str) -> str | None: ...

    def get_or_default(self, key: str, default: str) -> str: ...


def parse_dotenv(text: str) -> dict[str, str]:
    """Parse KEY=VALUE lines; supports comments, blank lines, and quoted values."""
    out: dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("export "):
            line = line[len("export ") :].lstrip()
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip()
        value = value.strip()
        if value and value[0] in ("'", '"'):
            quote = value[0]
            closing = value.find(quote, 1)
            if closing != -1:
                # anything after the closing quote (e.g. an inline comment) is dropped
                value = value[1:closing]
        elif " #" in value:
            # strip trailing inline comment on unquoted values
            value = value.split(" #", 1)[0].rstrip()
        if key:
            out[key] = value
    return out


class TypedGetters:
    """Typed convenience getters shared by every config implementation;
    subclasses provide ``get``."""

    def get(self, key: str) -> str | None:  # pragma: no cover - overridden
        raise NotImplementedError

    def get_or_default(self, key: str, default: str) -> str:
        value = self.get(key)
        return value if value not in (None, "") else default

    def get_int(self, key: str, default: int) -> int:
        value = self.get(key)
        if value in (None, ""):
            return default
        try:
            return int(value)  # type: ignore[arg-type]
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        value = self.get(key)
        if value in (None, ""):
            return default
        try:
            return float(value)  # type: ignore[arg-type]
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self.get(key)
        if value in (None, ""):
            return default
        return str(value).strip().lower() in ("1", "true", "yes", "on")


class EnvConfig(TypedGetters):
    """Layered env-file config.

    Order of precedence (highest first):
      1. real process environment (``os.environ``)
      2. ``{folder}/.{APP_ENV}.env`` (or ``.local.env`` when APP_ENV unset)
      3. ``{folder}/.env``
    """

    def __init__(self, folder: str = "./configs", environ: Mapping[str, str] | None = None):
        self._environ = environ if environ is not None else os.environ
        self._values: dict[str, str] = {}
        self._load(folder)

    def _load(self, folder: str) -> None:
        base = os.path.join(folder, ".env")
        if os.path.isfile(base):
            with open(base, encoding="utf-8") as f:
                self._values.update(parse_dotenv(f.read()))
        app_env = self._environ.get("APP_ENV", "") or self._values.get("APP_ENV", "")
        overlay_name = f".{app_env}.env" if app_env else ".local.env"
        overlay = os.path.join(folder, overlay_name)
        if os.path.isfile(overlay):
            with open(overlay, encoding="utf-8") as f:
                self._values.update(parse_dotenv(f.read()))

    def get(self, key: str) -> str | None:
        if key in self._environ:
            return self._environ[key]
        return self._values.get(key)


class DictConfig(TypedGetters):
    """In-memory config for tests (analog of gofr's mock config)."""

    def __init__(self, values: Mapping[str, str] | None = None):
        self._values = dict(values or {})

    def get(self, key: str) -> str | None:
        return self._values.get(key)

    def set(self, key: str, value: str) -> None:
        self._values[key] = value
