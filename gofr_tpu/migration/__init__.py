"""Versioned migrations (gofr `pkg/gofr/migration/`).

User supplies ``{version:int -> Migration(up=fn)}``; the runner sorts versions,
skips those at or below the last applied, wraps each in a per-datasource
transaction, records completions in ``gofr_migrations`` (`sql.go:12-18`
semantics), and rolls back on failure (`migration.go:28-91`). The datasource
handle passed to ``up`` exposes sql/redis/kv/pubsub so migrations can touch any
wired store (chain-of-responsibility per `interface.go:44-51`).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Migration:
    up: Callable[["MigrationDatasource"], Any]


class MigrationDatasource:
    """Narrow view of the container handed to each migration."""

    def __init__(self, container, tx=None):
        self._container = container
        self.sql = tx if tx is not None else container.sql
        self.redis = container.redis
        self.kv = container.kv
        self.pubsub = container.pubsub
        self.logger = container.logger


MIGRATION_TABLE_DDL = (
    "CREATE TABLE IF NOT EXISTS gofr_migrations ("
    "version INTEGER PRIMARY KEY, method TEXT, start_time TEXT, duration_ms INTEGER)"
)


def run_migrations(migrations: dict[int, Migration | Any], container) -> list[int]:
    """Run pending migrations in version order; returns versions applied."""
    logger = container.logger
    if not migrations:
        return []
    db = container.sql
    if db is None:
        raise RuntimeError("migrations require a SQL datasource (set DB_DIALECT)")

    db.execute(MIGRATION_TABLE_DDL)
    row = db.query_row("SELECT MAX(version) AS v FROM gofr_migrations")
    last = row["v"] if row and row["v"] is not None else 0

    applied: list[int] = []
    for version in sorted(migrations):
        if version <= last:
            continue
        migration = migrations[version]
        up = migration.up if isinstance(migration, Migration) else migration
        start = time.time()
        with db.begin() as tx:
            try:
                up(MigrationDatasource(container, tx=tx))
                duration_ms = int((time.time() - start) * 1000)
                tx.execute(
                    "INSERT INTO gofr_migrations (version, method, start_time, duration_ms) VALUES (?, ?, ?, ?)",
                    (version, "UP", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(start)), duration_ms),
                )
                tx.commit()
            except Exception as e:
                tx.rollback()
                logger.errorf("migration %d failed, rolled back: %r", version, e)
                raise
        logger.infof("migration %d applied in %dms", version, int((time.time() - start) * 1000))
        applied.append(version)
    return applied
