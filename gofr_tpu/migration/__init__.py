"""Versioned migrations (gofr `pkg/gofr/migration/`).

User supplies ``{version:int -> Migration(up=fn)}``; the runner sorts versions,
skips those at or below the last applied, wraps each in per-datasource
transactions, records completions per datasource, and rolls back on failure
(`migration.go:28-91`). The datasource handle passed to ``up`` exposes
sql/redis/kv/pubsub so migrations can touch any wired store
(chain-of-responsibility per `interface.go:44-51`):

- **SQL**: statements run inside a real transaction; the completion row in
  ``gofr_migrations`` commits with the migration's own writes (`sql.go:12-18`).
- **Redis**: the handle is a BUFFERING transaction view (``RedisTx``) — the
  reference swaps ``ds.Redis`` for a ``TxPipeline`` the same way
  (`migration.go:69-71`, `redis.go:78-127`). Writes queue locally and are
  shipped as one MULTI/EXEC at commit together with the completion record in
  the ``gofr_migrations`` hash; a failing migration discards the buffer, so
  no partial Redis state survives. Reads pass through to the live client and
  see pre-transaction state (MULTI semantics: queued writes are not readable
  before EXEC).
- **Pub/Sub**: ``d.pubsub.create_topic``/``delete_topic`` for topic
  migrations (`interface.go:28-31`); brokers offer no transactions, so these
  apply immediately — order topic creates FIRST in a migration.
- Completion bookkeeping lives in EVERY wired transactional datasource; the
  skip point is the max across them (`redis.go:34-76` getLastMigration).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Callable


@dataclass
class Migration:
    up: Callable[["MigrationDatasource"], Any]


class RedisTx:
    """Buffered Redis view handed to migrations: write commands queue and
    execute atomically (MULTI/EXEC in one pipeline) at commit; reads pass
    through to the live client."""

    def __init__(self, redis):
        self._redis = redis
        self._buffer: list[tuple[Any, ...]] = []

    # -- buffered writes -------------------------------------------------------

    def command(self, *args: Any) -> "RedisTx":
        self._buffer.append(args)
        return self

    def set(self, key: str, value: Any, ex: int | None = None) -> "RedisTx":
        return self.command(*(("SET", key, value) + (("EX", ex) if ex is not None else ())))

    def delete(self, *keys: str) -> "RedisTx":
        return self.command("DEL", *keys)

    def hset(self, key: str, field: str, value: Any) -> "RedisTx":
        return self.command("HSET", key, field, value)

    def lpush(self, key: str, *values: Any) -> "RedisTx":
        return self.command("LPUSH", key, *values)

    def incr(self, key: str) -> "RedisTx":
        return self.command("INCR", key)

    def expire(self, key: str, seconds: int) -> "RedisTx":
        return self.command("EXPIRE", key, seconds)

    # -- passthrough reads (pre-transaction state) -----------------------------

    def get(self, key: str):
        return self._redis.get(key)

    def hget(self, key: str, field: str):
        return self._redis.hget(key, field)

    def hgetall(self, key: str):
        return self._redis.hgetall(key)

    def keys(self, pattern: str = "*"):
        return self._redis.keys(pattern)

    # -- lifecycle (runner-only) -----------------------------------------------

    def _commit(self) -> None:
        if not self._buffer:
            return
        pipe = self._redis.pipeline()
        pipe.command("MULTI")
        for parts in self._buffer:
            pipe.command(*parts)
        pipe.command("EXEC")
        pipe.execute()
        self._buffer = []

    def _discard(self) -> None:
        self._buffer = []


class MigrationDatasource:
    """Narrow view of the container handed to each migration."""

    def __init__(self, container, tx=None, redis=None):
        self._container = container
        self.sql = tx if tx is not None else container.sql
        self.redis = redis if redis is not None else container.redis
        self.kv = container.kv
        self.pubsub = container.pubsub
        self.logger = container.logger


MIGRATION_TABLE_DDL = (
    "CREATE TABLE IF NOT EXISTS gofr_migrations ("
    "version INTEGER PRIMARY KEY, method TEXT, start_time TEXT, duration_ms INTEGER)"
)
REDIS_MIGRATION_KEY = "gofr_migrations"


def _last_applied(db, redis) -> int:
    last = 0
    if db is not None:
        row = db.query_row("SELECT MAX(version) AS v FROM gofr_migrations")
        if row and row["v"] is not None:
            last = int(row["v"])
    if redis is not None:
        for key in redis.hgetall(REDIS_MIGRATION_KEY):
            k = key.decode() if isinstance(key, bytes) else str(key)
            try:
                last = max(last, int(k))
            except ValueError:
                continue
    return last


def run_migrations(migrations: dict[int, Migration | Any], container) -> list[int]:
    """Run pending migrations in version order; returns versions applied."""
    logger = container.logger
    if not migrations:
        return []
    db = container.sql
    redis = container.redis
    if db is None and redis is None:
        raise RuntimeError(
            "migrations require a transactional datasource (set DB_DIALECT or REDIS_HOST)"
        )

    if db is not None:
        db.execute(MIGRATION_TABLE_DDL)
        # a version recorded as redis-pending means a previous run
        # committed SQL but died before (or during) the Redis EXEC: its
        # Redis writes were NEVER applied, and because the skip point is
        # the max across datasources a silent rerun would skip them
        # forever. Refuse to proceed until the operator replays the
        # migration's Redis writes and clears the marker
        # (UPDATE gofr_migrations SET method='UP' WHERE version=N) —
        # docs/migrations.md#redis-pending.
        row = db.query_row(
            "SELECT version FROM gofr_migrations WHERE method = 'UP:redis-pending'"
        )
        if row and row.get("version") is not None:
            raise RuntimeError(
                f"migration {row['version']} is marked UP:redis-pending (SQL "
                "committed, Redis EXEC unconfirmed). Check Redis first: "
                f"HGET gofr_migrations {row['version']} — if the completion "
                "record EXISTS the EXEC succeeded and only the marker-clear "
                "failed (do NOT replay; just clear the marker); if ABSENT, "
                "replay the migration's Redis writes manually. Then clear: "
                f"UPDATE gofr_migrations SET method='UP' WHERE "
                f"version={row['version']} (docs/migrations.md#redis-pending)"
            )
    last = _last_applied(db, redis)

    applied: list[int] = []
    for version in sorted(migrations):
        if version <= last:
            continue
        migration = migrations[version]
        up = migration.up if isinstance(migration, Migration) else migration
        start = time.time()
        tx = db.begin().__enter__() if db is not None else None
        redis_tx = RedisTx(redis) if redis is not None else None
        try:
            up(MigrationDatasource(container, tx=tx, redis=redis_tx))
            duration_ms = int((time.time() - start) * 1000)
            stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(start))
            # Commit order: SQL FIRST, then the Redis EXEC. The skip point
            # is the max across datasources, so whichever commits last must
            # be the one that can't fail for data-dependent reasons — SQL
            # (DDL conflicts, constraints) fails far more often than an
            # EXEC of already-validated commands. An SQL failure here rolls
            # everything back cleanly; a Redis failure after the SQL commit
            # leaves SQL recorded and is surfaced loudly below.
            if tx is not None:
                # with Redis also in play, the version commits as
                # 'UP:redis-pending' and flips to 'UP' only after the EXEC
                # confirms — a crash in the window leaves a durable marker
                # that run_migrations refuses to skip past (ADVICE r4;
                # docs/migrations.md#redis-pending)
                method = "UP:redis-pending" if redis_tx is not None else "UP"
                tx.execute(
                    "INSERT INTO gofr_migrations (version, method, start_time, duration_ms) VALUES (?, ?, ?, ?)",
                    (version, method, stamp, duration_ms),
                )
                tx.commit()
            if redis_tx is not None:
                # completion record rides the same MULTI/EXEC as the
                # migration's own writes (redis.go:90-119)
                redis_tx.hset(REDIS_MIGRATION_KEY, str(version), json.dumps(
                    {"method": "UP", "startTime": stamp, "duration": duration_ms}))
                try:
                    redis_tx._commit()
                except Exception:
                    if tx is not None:
                        logger.errorf(
                            "migration %d: SQL committed but the Redis EXEC failed — "
                            "Redis writes for this version were NOT applied; the "
                            "version stays marked UP:redis-pending and the next "
                            "run_migrations will refuse to start until it is "
                            "replayed and cleared (docs/migrations.md#redis-pending)",
                            version,
                        )
                    raise
                if tx is not None:
                    # EXEC confirmed: clear the pending marker. A failure
                    # RIGHT HERE must not read as a failed migration — the
                    # writes are fully applied; the stale marker is a
                    # safe-side false positive (the refusal message tells
                    # the operator how to distinguish it via HGET).
                    try:
                        db.execute(
                            "UPDATE gofr_migrations SET method = 'UP' WHERE version = ?",
                            (version,),
                        )
                    except Exception as clear_err:  # noqa: BLE001
                        logger.errorf(
                            "migration %d: Redis EXEC CONFIRMED but clearing the "
                            "redis-pending marker failed (%r). Do NOT replay — "
                            "just clear the marker: UPDATE gofr_migrations SET "
                            "method='UP' WHERE version=%d",
                            version, clear_err, version,
                        )
        except Exception as e:  # noqa: BLE001
            if redis_tx is not None:
                redis_tx._discard()
            if tx is not None:
                tx.rollback()
            logger.errorf("migration %d failed, rolled back: %r", version, e)
            raise
        finally:
            if tx is not None:
                tx.__exit__(None, None, None)
        logger.infof("migration %d applied in %dms", version, int((time.time() - start) * 1000))
        applied.append(version)
    return applied
