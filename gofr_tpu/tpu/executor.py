"""Device-dispatch executor layer for ``GenerateEngine``.

The scheduler/executor split (ROADMAP O1/O4; the seam engine roles are
built on): tpu/engine.py keeps the SCHEDULER half — admission planning,
slot/lane/page bookkeeping, QoS/deadline accounting, and the ``_dq``
fold loop — while this module owns DEVICE DISPATCH: packed-array
assembly and the compiled-program calls for batched prefill, chunked
prefill, host-tier swap-ins and spill materialization, warmup
compilation, and the handoff page gathers. tpu/decode.py's decode/spec
dispatch paths are re-exported here, so this module is the single
device-dispatch façade an engine role composes over (``ENGINE_ROLE`` —
a prefill worker never calls :func:`dispatch_decode`; a decode worker
never warms the batched-prefill programs).

Locking contract: everything here runs on the engine's device thread
and — with one documented exception — OUTSIDE the state lock. The
scheduler snapshots whatever a dispatch needs into a plan object before
releasing the lock (packing is pure numpy; a wedged device call must
never hold the lock, or ``stop()``'s ``_fail_all`` would deadlock
behind it). The exception is :func:`gather_pages`: a pure DISPATCH
(async, no readback) that is safe under the lock — the same discipline
``_evict_prefix_page`` established for spill gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.tpu.decode import (  # noqa: F401 - the decode half of the façade
    dispatch_decode,
    dispatch_spec,
    dispatch_spec_paged,
    process_decode,
)
from gofr_tpu.tpu.lockstep import TAG_CHUNK, TAG_DECODE, TAG_PREFILL, TAG_SPEC


def prefill_cols(eng) -> int:
    """Width of the packed prefill ``rows`` block: the block-table columns
    (paged) or the slot-id column (slot) — plus, for paged with spec on,
    ONE trailing slot-id column so the prefill programs can seed the
    device-resident history rows by lane (tpu/programs.py docstring).
    Every prefill pack site (dispatch, warmup, lockstep replay) must
    agree with build_programs' W, so they all call this."""
    if eng.kv_layout != "paged":
        return 1
    return eng.pages_per_slot + (1 if eng.spec_tokens else 0)


class PrefillPlan:
    """Snapshot of one batched-prefill admission round, taken under the
    state lock by ``engine._admit_prefill``: everything the unlocked
    packing + device call needs. ``ready`` is immutable (requests +
    prompt token arrays); lanes/table rows were copied under the lock."""

    __slots__ = ("ready", "meta", "nb", "lb", "w", "rows", "table_rows",
                 "step", "t0")

    def __init__(self, ready, meta, nb, lb, w, rows, table_rows, step, t0):
        self.ready = ready
        self.meta = meta
        self.nb = nb
        self.lb = lb
        self.w = w
        self.rows = rows
        self.table_rows = table_rows
        self.step = step
        self.t0 = t0


class ChunkPlan:
    """Snapshot of one chunked-prefill dispatch (``_advance_chunked``'s
    locked planning half): slot identity, chunk geometry, and the copied
    block-table row."""

    __slots__ = ("idx", "slot", "chunk", "offset", "last", "lb",
                 "table_row", "temp", "step", "t0")

    def __init__(self, idx, slot, chunk, offset, last, lb, table_row,
                 temp, step, t0):
        self.idx = idx
        self.slot = slot
        self.chunk = chunk
        self.offset = offset
        self.last = last
        self.lb = lb
        self.table_row = table_row
        self.temp = temp
        self.step = step
        self.t0 = t0


def dispatch_prefill(eng, plan: PrefillPlan) -> None:
    """Pack and dispatch one batched prefill (the device half of
    ``_admit_prefill``). Pure-numpy packing outside the state lock:
    token/temp data rides the immutable ``plan.ready`` list, lanes and
    table rows were snapshotted under the lock."""
    nb, lb, w = plan.nb, plan.lb, plan.w
    # block-table columns (w may add a trailing slot-id col on top)
    wp = eng.pages_per_slot if eng.kv_layout == "paged" else 0
    # ae: one extra column carrying each row's adapter pool slot, between
    # the rows block and temps (zero = base — padding rows' zero sel
    # selects the all-zeros base adapter, whose delta is exactly 0.0).
    # OFF keeps the pack byte-identical to the pre-adapter layout.
    ae = 1 if eng._adapters_enabled else 0
    packed = eng._staging("prefill", (nb, lb + w + 3 + ae))
    packed[:, lb] = 1  # padding rows: length 1
    temps = np.zeros((nb,), np.float32)
    if eng.kv_layout == "paged":
        packed[:, lb + 1:lb + 1 + wp] = eng.total_pages
        if eng.spec_tokens:
            # padding rows' hist seeding drops via an OOB lane id
            packed[:, lb + 1 + wp] = eng.num_slots
    else:
        packed[:, lb + 1] = eng.num_slots
    for i, (req, toks) in enumerate(plan.ready):
        packed[i, : toks.shape[0]] = toks
        packed[i, lb] = toks.shape[0]
        if eng.kv_layout == "paged":
            packed[i, lb + 1:lb + 1 + wp] = plan.table_rows[i]
            if eng.spec_tokens:
                packed[i, lb + 1 + wp] = plan.rows[i]
        else:
            packed[i, lb + 1] = plan.rows[i]
        if ae:
            packed[i, lb + 1 + w] = plan.meta[i][1].adapter_slot
        temps[i] = float(req.kw.get("temperature", 0.0))
    packed[:, lb + 1 + w + ae] = temps.view(np.int32)
    packed[0, lb + 2 + w + ae] = plan.step

    eng._announce(TAG_PREFILL, lb, nb, packed)
    first_dev, eng.cache = eng._prefill_sample(
        eng.params, eng._base_key, eng.cache, jnp.asarray(packed),
        *((eng._adapter_args(),) if ae else ())
    )
    # tokens, never logits — and NEVER read back here: the future rides
    # the in-flight queue; _fold_prefill activates the claimed slots at
    # dequeue, overlapped with whatever dispatches after this call
    pstep = (eng.perf.step_prefill(
        sum(toks.shape[0] for _, toks in plan.ready), plan.t0)
        if eng.perf is not None else None)
    eng._dq.append(("prefill", first_dev, plan.meta, plan.t0,
                    len(plan.ready) / nb, ("prefill", lb, nb), pstep))


def dispatch_chunk(eng, plan: ChunkPlan) -> None:
    """Pack and dispatch one prefill chunk (the device half of
    ``_advance_chunked``). Everything below is immutable
    (prompt_tokens) or snapshotted under the lock (table row, step)."""
    s, lb, chunk, offset = plan.slot, plan.lb, plan.chunk, plan.offset
    w = prefill_cols(eng)
    wp = eng.pages_per_slot if eng.kv_layout == "paged" else 0
    ae = 1 if eng._adapters_enabled else 0  # sel col after the offset
    packed = eng._staging("chunk", (1, lb + w + 4 + ae))
    packed[0, :chunk] = s.prompt_tokens[offset:offset + chunk]
    packed[0, lb] = chunk
    if eng.kv_layout == "paged":
        packed[0, lb + 1:lb + 1 + wp] = plan.table_row
        if eng.spec_tokens:
            packed[0, lb + 1 + wp] = plan.idx  # hist row to seed
    else:
        packed[0, lb + 1] = plan.idx
    packed[0, lb + 1 + w] = offset  # chunk offset
    if ae:
        packed[0, lb + 2 + w] = s.adapter_slot
    packed[0, lb + 2 + w + ae] = np.float32(plan.temp).view(np.int32)
    packed[0, lb + 3 + w + ae] = plan.step

    eng._announce(TAG_CHUNK, lb, 1, packed)
    first_dev, eng.cache = eng._chunk_prefill(
        eng.params, eng._base_key, eng.cache, jnp.asarray(packed),
        *((eng._adapter_args(),) if ae else ())
    )
    pstep = (eng.perf.step_chunk(chunk, offset, plan.t0)
             if eng.perf is not None else None)
    eng._dq.append(("chunk", first_dev,
                    (plan.idx, s, chunk, offset, plan.last),
                    plan.t0, chunk / lb, ("prefill_chunk", lb, 1), pstep))


def dispatch_swapins(eng) -> bool:
    """Dispatch one async host→device page upload per staged prefix hit
    onto the unified in-flight queue (outside the state lock — packing
    is host memcpy and the device call must never wedge under the
    lock). Pages were claimed and nodes promoted at hit time; the fold
    (``_fold_swapin``) settles the nodes and records the metrics, and
    discards slot bookkeeping by identity like every other entry."""
    from gofr_tpu.ops.paged import swap_in_pages
    from gofr_tpu.tpu.engine import next_bucket
    import time

    items, eng._pending_swapins = eng._pending_swapins, []
    # uploads target the KV pool only (the spec history plane, when the
    # cache is the (kv, hist) tuple, is slot-indexed — never swapped)
    leaves_proto = jax.tree.leaves(eng.kv_cache)
    for idx, slot, keys, pids, payloads in items:
        t0 = time.monotonic()
        n = len(pids)
        # smallest bucketed upload width: padding is at most 2x the
        # pages actually swapped, never the full pages_per_slot
        w = next_bucket(n, eng._swapin_buckets)
        ids = np.full((w,), eng.total_pages, np.int32)  # pad rows: OOB, dropped
        ids[:n] = pids
        stacked = []
        for li, proto in enumerate(leaves_proto):
            buf = np.zeros((proto.shape[0], w) + tuple(proto.shape[2:]),
                           np.asarray(payloads[0][li]).dtype)
            for j in range(n):
                buf[:, j] = payloads[j][li]
            stacked.append(buf)
        payload_tree = jax.tree.unflatten(eng._cache_treedef, stacked)
        kv, marker = swap_in_pages(
            eng.kv_cache, jnp.asarray(ids), payload_tree)
        eng.cache = ((kv, eng.cache[1])
                     if isinstance(eng.cache, tuple) else kv)
        leaves_proto = jax.tree.leaves(kv)
        # the histogram records the ACTUAL transfer (padded width) so
        # swap-in latency and bytes stay comparable
        nbytes = w * eng._page_bytes
        pstep = (eng.perf.step_swapin(nbytes, t0)
                 if eng.perf is not None else None)
        eng._dq.append(("swapin", marker, (idx, slot, keys, n, nbytes),
                        t0, n / w, ("swapin", w), pstep))
    return True


def materialize_spills(eng) -> None:
    """Complete staged spill copies OUTSIDE the state lock: eviction
    dispatched each page's gather asynchronously (so pool pressure
    never blocks the lock on a device round trip) and left the node
    holding the small gathered device buffers; this step — device
    thread, once per loop iteration — blocks on those buffers, copies
    them to host memory, and swaps the node payload. Nodes dropped or
    promoted in between simply skip the replacement."""
    items, eng._pending_spills = eng._pending_spills, []
    for key, dev_payload in items:
        host_payload = tuple(np.asarray(x) for x in dev_payload)
        with eng._state_lock:
            if eng._prefix is not None:
                eng._prefix.replace_host_payload(key, host_payload)


def gather_pages(eng, pages: list[int]) -> list[tuple]:
    """DISPATCH one per-page gather per pool page id and return the
    device-buffer tuples (no readback — callers block on them outside
    the lock). Safe under the state lock: async dispatch only, the
    ``_evict_prefix_page`` discipline. Used by the prefill-role handoff
    export (tpu/handoff.py) and shaped exactly like a host-tier spill
    payload, so the decode side can register it as a host node."""
    from gofr_tpu.ops.paged import gather_page

    return [tuple(jax.tree.leaves(gather_page(eng.kv_cache, jnp.int32(p))))
            for p in pages]


def warmup_compile(eng, lbs: list[int], bbs: list[int]) -> int:
    """Compile every program signature this engine's ROLE can dispatch
    (the body of ``engine.warmup()``; see its docstring for the cache-
    safety argument). Role scoping is the disaggregation warmup win: a
    prefill-only worker skips the decode/spec compiles, a decode-only
    worker skips the batched-prefill ladder — both keep chunked prefill
    (the decode side computes post-hit remainders through it) and the
    host-tier/handoff programs their role needs."""
    count = 0
    warm_prefill = eng.role != "decode"
    warm_decode = eng.role != "prefill"
    w = prefill_cols(eng)
    wp = eng.pages_per_slot if eng.kv_layout == "paged" else 0
    # adapter-enabled engines compile the sel-bearing signatures (every
    # pack grows by the sel row/column; zero sel = base adapter, and the
    # warmup ships the pool args exactly like live dispatch)
    ae = 1 if eng._adapters_enabled else 0
    ad = (eng._adapter_args(),) if ae else ()
    oob = eng.total_pages if eng.kv_layout == "paged" else eng.num_slots
    if warm_prefill:
        for lb in lbs:
            for nb in bbs:
                packed = np.zeros((nb, lb + w + 3 + ae), np.int32)
                packed[:, lb] = 1  # lengths
                packed[:, lb + 1:lb + 1 + w] = oob  # all-OOB rows: writes dropped
                if eng.kv_layout == "paged" and eng.spec_tokens:
                    packed[:, lb + 1 + wp] = eng.num_slots  # OOB hist lanes
                eng._announce(TAG_PREFILL, lb, nb, packed)
                toks, eng.cache = eng._prefill_sample(
                    eng.params, eng._base_key, eng.cache,
                    jnp.asarray(packed), *ad
                )
                jax.block_until_ready(toks)
                eng._compiled.add(("prefill", lb, nb))
                count += 1
    if eng._chunked_ok:
        # chunked-prefill programs (batch 1, one per len bucket). OOB
        # rows — block-table entries (paged) or the slot id (slot) —
        # drop their writes, so a warmup never touches live cache state.
        # Both roles need these: prefill serves long prompts through
        # them, decode computes the post-hit prompt remainder.
        for lb in lbs:
            packed = np.zeros((1, lb + w + 4 + ae), np.int32)
            packed[0, lb] = 1
            packed[0, lb + 1:lb + 1 + w] = oob
            if eng.kv_layout == "paged" and eng.spec_tokens:
                packed[0, lb + 1 + wp] = eng.num_slots  # OOB hist lane
            eng._announce(TAG_CHUNK, lb, 1, packed)
            toks, eng.cache = eng._chunk_prefill(
                eng.params, eng._base_key, eng.cache, jnp.asarray(packed),
                *ad
            )
            jax.block_until_ready(toks)
            eng._compiled.add(("prefill_chunk", lb, 1))
            count += 1
    n, k = eng.num_slots, eng.decode_chunk
    wt = eng.pages_per_slot if eng.kv_layout == "paged" else 0
    packed = np.zeros((5 + ae + wt, n), np.int32)
    if eng.kv_layout == "paged":
        packed[5 + ae:] = eng.total_pages  # OOB table: writes dropped
    else:
        packed[1, :] = eng._cache_len  # OOB positions: writes dropped
    if warm_decode and not eng.spec_tokens:
        # spec mode never calls decode.dispatch_decode — don't compile
        # the (expensive) plain decode program it would throw away
        eng._announce(TAG_DECODE, 0, 0, packed)  # a=0: warmup, no carry
        out, _, eng.cache = eng._decode_chunk(
            eng.params, eng._base_key, eng.cache, k, jnp.asarray(packed),
            jnp.zeros((n,), jnp.int32), *ad
        )
        jax.block_until_ready(out)
        eng._compiled.add(("decode", n, k))
        count += 1
    if warm_decode and eng.spec_tokens:
        # BOTH layouts: all lanes host-arbitrated and OOB, so no
        # cache/history write survives. Announced with b=0 (warmup,
        # mirroring the TAG_DECODE convention): both sides feed a
        # zeros carry and DISCARD the output carry, so leader and
        # followers stay carry-identical without relying on a
        # warmup-produced value (ADVICE r5).
        if eng.kv_layout == "paged":
            sw = eng.pages_per_slot
            spec_packed = np.zeros((5 + ae + sw, n), np.int32)
            spec_packed[1, :] = sw * eng.page_size + 1  # all lanes OOB
            spec_packed[2, :] = 1
            spec_packed[5 + ae:] = eng.total_pages  # all-OOB tables
        else:
            spec_packed = np.zeros((5 + ae, n), np.int32)
            spec_packed[1, :] = eng._cache_len + 1
            spec_packed[2, :] = 1
        eng._announce(TAG_SPEC, spec_packed.shape[0], 0, spec_packed)
        carry = (jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
        toks, _, eng.cache, _warm_carry = eng._spec_chunk_fn(
            eng.params, eng._base_key, eng.cache, k,
            jnp.asarray(spec_packed), carry, *ad)
        del _warm_carry  # never stored: _loop starts from None
        jax.block_until_ready(toks)
        eng._compiled.add(("decode_spec", n, k, eng.spec_tokens))
        count += 1
    if (eng.kv_layout == "paged" and eng._prefix is not None
            and (eng._prefix.host_budget or eng.role == "prefill")):
        # host-tier spill/swap-in programs: a first spill or swap-in
        # mid-serving would otherwise pay its XLA compile inside the
        # latency window the tier exists to shrink. The swap-in warmup
        # uses an all-OOB id vector, so every upload write is dropped.
        # A prefill-role worker compiles the gather too — its handoff
        # export dispatches per-page gathers under the state lock.
        from gofr_tpu.ops.paged import gather_page, swap_in_pages

        jax.block_until_ready(
            jax.tree.leaves(gather_page(eng.kv_cache, jnp.int32(0)))[0])
        count += 1
        if eng._prefix.host_budget:
            for wb in eng._swapin_buckets:
                ids = np.full((wb,), eng.total_pages, np.int32)
                payload = jax.tree.unflatten(eng._cache_treedef, [
                    np.zeros((leaf.shape[0], wb) + tuple(leaf.shape[2:]), leaf.dtype)
                    for leaf in jax.tree.leaves(eng.kv_cache)])
                kv, marker = swap_in_pages(
                    eng.kv_cache, jnp.asarray(ids), payload)
                eng.cache = ((kv, eng.cache[1])
                             if isinstance(eng.cache, tuple) else kv)
                jax.block_until_ready(marker)
                eng._compiled.add(("swapin", wb))
                count += 1
    return count


__all__ = [
    "ChunkPlan", "PrefillPlan", "dispatch_chunk", "dispatch_decode",
    "dispatch_prefill", "dispatch_spec", "dispatch_spec_paged",
    "dispatch_swapins", "gather_pages", "materialize_spills",
    "prefill_cols", "process_decode", "warmup_compile",
]
