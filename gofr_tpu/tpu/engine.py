"""Continuous-batching serving engines.

This replaces the reference's goroutine-per-request hot path
(`pkg/gofr/handler.go:58-92`, SURVEY.md §3.2) with the TPU-native shape:
handlers *enqueue* work and block on a future; a single device thread
drains the queue, packs requests into fixed-shape batches, and runs one
compiled XLA program per step.

Two engines:

- ``BatchEngine`` — stateless models (embed / classify): drain up to
  max_batch, pad to a (length, batch) bucket, run, scatter results.
- ``GenerateEngine`` — decoder LMs: slot-based continuous batching.
  N decode slots share one SlotKVCache; arriving prompts are prefilled
  (batched per length bucket) into free slots while decode keeps stepping
  the active ones; every step samples all slots in one program. A
  cancelled/timed-out request just frees its slot — its lane computes
  garbage until reused (slot invalidation; SURVEY.md §7 hard part (b)).

Shape discipline: every compiled signature is (batch_bucket, len_bucket)
with power-of-two buckets, so the compile-cache population is tiny and
steady-state serving is 100% cache hits (tracked in app_tpu_* metrics).

Dispatch discipline (round-6 unification): every asynchronous device
call — batched prefill, chunked prefill, decode chunk, slot-layout spec
round — goes through ONE bounded in-flight queue (``_dq``, depth
``pipeline_depth``). Dispatch claims slot/page state and enqueues the
device futures; readback + slot bookkeeping happen at dequeue,
overlapped with younger dispatches, so arriving prompts no longer stall
decoding slots for a prefill round trip (the mixed-arrival device-idle
bubble). Results are folded only if the lane's slot object is unchanged
since dispatch — preemption, cancel, stop(), and crash recovery all ride
that identity check.

Module layout (round-5 split): tpu/programs.py builds the jitted packed
programs and documents every packed layout; tpu/decode.py holds the
decode dispatch paths and the unified queue processing; this file keeps
engine state, admission/prefill, streaming, supervision, and the
build_engine factory.
"""

from __future__ import annotations

import collections
import itertools
import math
import os
import queue
from functools import partial
import threading
import time
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.fleet import chaos
from gofr_tpu.http.errors import DeadlineExceeded, RequestTimeout, ServiceUnavailable
from gofr_tpu.qos.scheduler import QoSQueue
from gofr_tpu.tracing import RequestTrace, current_span
from gofr_tpu.tpu.lockstep import TAG_CHUNK, TAG_DECODE, TAG_PREFILL, TAG_SPEC
from gofr_tpu.native import plan_prefill
from gofr_tpu.models.base import ModelSpec, get_family
from gofr_tpu.parallel import shard_pytree
from gofr_tpu.tpu import executor
from gofr_tpu.tpu.executor import (
    dispatch_decode,
    dispatch_spec,
    dispatch_spec_paged,
    process_decode,
)
from gofr_tpu.tpu.programs import build_programs


def next_bucket(n: int, buckets: list[int]) -> int:
    """Smallest bucket ≥ n (buckets sorted ascending); raises if too long."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"input length {n} exceeds max bucket {buckets[-1]}")


def _pow2_buckets(lo: int, hi: int) -> list[int]:
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


class EngineClosed(RuntimeError):
    pass


class Request:
    _ids = itertools.count()

    __slots__ = ("id", "inputs", "kw", "enqueued_at", "deadline", "stream_q",
                 "_done", "_result", "_error", "cancelled", "cancel_reason",
                 "_complete_lock", "_callbacks")

    def __init__(self, inputs: Any, kw: dict[str, Any], timeout: float | None, stream: bool = False):
        self.id = next(Request._ids)
        self.inputs = inputs
        self.kw = kw
        self.enqueued_at = time.monotonic()
        self.deadline = self.enqueued_at + timeout if timeout else None
        self.stream_q: queue.SimpleQueue | None = queue.SimpleQueue() if stream else None
        self._done = threading.Event()
        self._complete_lock = threading.Lock()
        self._result: Any = None
        self._error: Exception | None = None
        self._callbacks: list = []
        self.cancelled = False
        self.cancel_reason: str | None = None

    def complete(self, result: Any = None, error: Exception | None = None) -> None:
        # Idempotent, first-writer-wins: stop()'s _fail_all can race a stuck
        # device thread that later produces a result — the late writer must
        # not overwrite the recorded outcome (ADVICE.md round 1).
        with self._complete_lock:
            if self._done.is_set():
                return
            self._result, self._error = result, error
            if self.stream_q is not None:
                self.stream_q.put(None)  # sentinel
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:  # outside the lock: callbacks may be arbitrary
            try:
                cb(self)
            except Exception:  # noqa: BLE001 - a bad callback must not kill the engine
                import traceback

                traceback.print_exc()  # surfaced, not swallowed: a dropped
                # callback means some awaiter never resolves

    def add_done_callback(self, fn) -> None:
        """Invoke ``fn(request)`` on completion (immediately if already
        done). This is how asyncio transports await an engine future without
        parking a thread per in-flight request."""
        with self._complete_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def outcome(self) -> tuple[Any, Exception | None]:
        """(result, error) once complete — the non-blocking accessor done
        callbacks use, so outcome extraction lives in one place."""
        if not self._done.is_set():
            raise RuntimeError("request is not complete")
        return self._result, self._error

    def cancel(self, reason: str = "cancelled") -> None:
        """Cooperative: flags the request; the device loop reclaims the
        slot/pages at its next bookkeeping pass. ``reason`` lands in the
        flight-recorder timeline (``client_disconnect``, ``timeout``,
        ``hedge_loser``, ...) — first caller wins."""
        if not self.cancelled:
            self.cancel_reason = reason
        self.cancelled = True

    def result(self, timeout: float | None = None) -> Any:
        # Unify on remaining budget: a request constructed with a deadline
        # never blocks past it, even with no explicit wait — previously
        # result() with its own timeout could outlive the deadline by the
        # full wait (the double-timeout bug).
        wait = timeout
        if self.deadline is not None:
            budget = max(0.0, self.deadline - time.monotonic())
            wait = budget if wait is None else min(wait, budget)
        if not self._done.wait(wait):
            self.cancel("timeout")
            raise RequestTimeout()
        if self._error is not None:
            raise self._error
        return self._result

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class _EngineBase:
    """Queue + device thread + metrics plumbing shared by both engines."""

    def __init__(self, container, *, default_timeout: float | None = None,
                 max_restarts: int = 3):
        self.container = container
        self.logger = container.logger
        self.metrics = container.metrics
        self.tpu = container.tpu
        self.default_timeout = default_timeout
        # observability plumbing (docs/observability.md): the tracer drives
        # the engine span timeline ONLY while a real exporter is configured
        # (Tracer.enabled guards every span construction); the flight
        # recorder is always on — a bounded ring of completed request
        # timelines + device steps served at /debug/requests, /debug/engine
        self.tracer = getattr(container, "tracer", None)
        self.flight = getattr(container, "flight", None)
        # SLO engine (metrics/slo.py): fed from the exact callsites that
        # record the raw latency histograms, so attainment and the
        # histograms can never disagree about what was measured
        self.slo = getattr(container, "slo", None)
        self._obs_lock = threading.Lock()
        self._inflight_requests = 0
        # QoS-capable queue: pure FIFO (byte-for-byte queue.Queue behavior)
        # until an AdmissionController binds this engine and flips it into
        # weighted-fair priority mode (gofr_tpu.qos; App.enable_qos).
        self._queue: QoSQueue = QoSQueue()
        self.qos = None  # AdmissionController once bound; None = QoS off
        self._thread: threading.Thread | None = None
        # requests currently inside a device call — visible to _fail_all so a
        # wedged step can't strand its batch (their complete is idempotent)
        self._inflight: list[Request] = []
        self._stop = threading.Event()
        self._poisoned = False  # set when a wedged thread failed to join
        # Serializes _pending/_inflight/slot bookkeeping between the device
        # thread and stop()/_fail_all on the caller thread (VERDICT r2 weak
        # #3: unsynchronized list mutation could corrupt state mid-_admit).
        self._state_lock = threading.RLock()
        self._compiled: set[tuple] = set()
        self._startup_error: Exception | None = None
        # Supervision (SURVEY §5.3; reference reconnects SQL in a loop,
        # sql.go:108-133): a crashed device loop restarts with backoff
        # instead of dying permanently. In-flight/slot-resident work fails
        # (its device state is suspect); queued work survives the restart.
        self.max_restarts = max_restarts
        self._restarts = 0
        self._restarting = False
        # scale-in drain (fleet/autoscaler.py): while set, _submit sheds new
        # arrivals with a retryable 503 and the device loop stops claiming
        # slots for queued work — in-flight slot work runs to completion
        self._draining = False
        # crashes further apart than this don't count against the restart
        # budget — the give-up is for crash LOOPS, not lifetime fault totals
        self.restart_window_s = 60.0
        self._last_crash_at = 0.0
        # chaos fault points (fleet/chaos.py; None — one branch — unless a
        # GOFR_CHAOS spec arms them): "engine.step" fires at the top of
        # every device-loop iteration, "engine.restart" inside the restart
        # backoff window (the deterministic latch the DEGRADED-window
        # contract tests pin open)
        self._chaos_step = chaos.hook("engine.step")
        self._chaos_restart = chaos.hook("engine.restart")

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self._poisoned:
            # the wedged device thread from the previous life may still wake;
            # a fresh thread would share (and race) its state
            raise EngineClosed(
                "engine was stopped with a wedged device thread; build a new engine"
            )
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name=f"gofr-engine-{id(self):x}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():
                # Stuck device step: Request.complete is first-writer-wins,
                # so failing everything now cannot be overwritten by a late
                # result from the wedged thread. Poison the engine so that a
                # LATE-waking loop iteration exits before touching slot/page
                # bookkeeping we are about to mutate here (ADVICE.md round 2).
                self._poisoned = True
                self.logger.warn("engine thread did not stop within 10s; failing in-flight requests")
            self._thread = None
        self._fail_all(EngineClosed("engine stopped"))

    def _fail_all(self, error: Exception) -> None:
        """Fail everything waiting — the queue AND the drained-but-unadmitted
        pending list (GenerateEngine extends this with slot-resident requests)."""
        with self._state_lock:
            while True:
                try:
                    self._queue.get_nowait().complete(error=error)
                except queue.Empty:
                    break
            for req, _ in getattr(self, "_pending", []):
                req.complete(error=error)
            if hasattr(self, "_pending"):
                self._pending = []
            for req, _ in getattr(self, "_pending_long", []):
                req.complete(error=error)
            if hasattr(self, "_pending_long"):
                self._pending_long = []
            for req in self._inflight:
                req.complete(error=error)

    def _crash_recover(self, error: Exception) -> None:
        """Fail work whose device state the crash made suspect (in-flight
        batches; GenerateEngine adds slot-resident requests + page pool
        reset). Queued/pending work survives — it re-plans after restart."""
        with self._state_lock:
            for req in self._inflight:
                req.complete(error=error)
            self._inflight = []

    def _backlog(self) -> int:
        return (self._queue.qsize() + len(getattr(self, "_pending", []))
                + len(getattr(self, "_pending_long", [])))

    def _trace_scope(self):
        """Context every trace-driving section runs under: paged engines pin
        the KV append lowering they resolved at construction
        (ops/paged.write_mode_scope), and generate engines pin the decode
        attention backends their warmup autotuner measured
        (ops/autotune.decision_scope) — so no trace re-reads os.environ and
        every trace this engine drives resolves 'auto' the same way."""
        import contextlib

        stack = contextlib.ExitStack()
        mode = getattr(self, "paged_kv_write", None)
        if mode:
            from gofr_tpu.ops.paged import write_mode_scope

            stack.enter_context(write_mode_scope(mode))
        ctx = self._kv_shard_ctx() if hasattr(self, "_kv_shard_ctx") else None
        if ctx is not None:
            from gofr_tpu.ops.paged import kv_shard_scope

            stack.enter_context(kv_shard_scope(ctx))
        pins = getattr(self, "_autotune_pins", None)
        if pins:
            from gofr_tpu.ops import autotune

            stack.enter_context(autotune.decision_scope(pins))
        return stack

    def _run(self) -> None:
        from gofr_tpu.ops.pallas import platform_hint

        while True:
            try:
                # Pin kernel-backend resolution to where this engine's device
                # actually is (a CPU test mesh under an attached TPU would
                # otherwise trace Pallas kernels it can't lower).
                with platform_hint(getattr(self.tpu, "platform", None)), self._trace_scope():
                    self._loop()
                return  # clean stop
            except Exception as e:  # noqa: BLE001
                self.logger.log_exception(e, "model engine step crashed")
                self._crash_recover(e)
                now = time.monotonic()
                if now - self._last_crash_at > self.restart_window_s:
                    self._restarts = 0  # isolated fault, not a crash loop
                self._last_crash_at = now
                if self._stop.is_set() or self._restarts >= self.max_restarts:
                    self._startup_error = e
                    self._fail_all(e)
                    ls = getattr(self, "_ls", None)
                    if ls is not None:
                        # dying ON the device thread: no concurrent
                        # collective exists, so release blocked followers
                        try:
                            ls.stop()
                        except Exception:  # noqa: BLE001
                            pass
                    return
                self._restarts += 1
                self.metrics.increment_counter("app_tpu_engine_restarts", 1)
                self._restarting = True
                try:
                    ls = getattr(self, "_ls", None)
                    if ls is not None:
                        # rejoin-capable fleet leader (a collective-transport
                        # leader never reaches here: max_restarts is 0): the
                        # crash may have cut an announce mid-frame, so drop
                        # every follower connection — each redials into the
                        # pending set and the restarted loop admits them all
                        # at a bumped epoch (_fleet_admit)
                        ls.reset_connections()
                    if self._chaos_restart is not None:
                        self._chaos_restart(attempt=self._restarts)
                except Exception as e2:  # noqa: BLE001
                    # an exception ESCAPING this handler would kill the
                    # device thread without _fail_all — every queued caller
                    # would hang to its timeout. Restart-path faults must
                    # never outrank the restart itself.
                    self.logger.log_exception(e2, "engine restart path")
                time.sleep(min(0.1 * (2 ** self._restarts), 5.0))
                self._restarting = False
                self.logger.warn(
                    f"engine device loop restarting (attempt {self._restarts}/{self.max_restarts})"
                )

    def _loop(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- submission ------------------------------------------------------------

    def _submit(self, inputs: Any, timeout: float | None, stream: bool = False, **kw: Any) -> Request:
        if self._thread is None:
            self.start()
        if self._startup_error is not None:
            raise self._startup_error
        if self._draining:
            # draining replica (scale-in): the registry already stopped
            # routing here, so anything arriving now raced the transition —
            # shed retryable, the ring successor owns the key by the retry
            self.metrics.increment_counter("app_tpu_drain_shed_total", 1)
            raise ServiceUnavailable("replica draining", retry_after=1.0)
        if "qos_class" in kw:  # public spelling of the internal routing key
            kw["_qos_class"] = kw.pop("qos_class")
        # the inbound server span, carried EXPLICITLY (contextvars don't
        # cross the submit-thread → device-loop boundary); popped even when
        # tracing is off so a span object never lingers in request kw
        parent_span = kw.pop("_parent_span", None)
        # optional caller hook: receives the Request the moment it exists,
        # so transports can track in-flight work for disconnect-driven
        # cancellation (Context._qos_kw, docs/resilience.md)
        on_submit = kw.pop("_on_submit", None)
        # chaos point "replica.slow" (fleet/chaos.py): a delay action here
        # simulates a slow replica's admission path — the hedging drill's
        # way of making one ring member consistently late
        chaos.fire("replica.slow")
        eff_timeout = timeout if timeout is not None else self.default_timeout
        if eff_timeout is not None and eff_timeout <= 0:
            # the propagated deadline is already spent: shed pre-queue with
            # 504 — computing tokens nobody can wait for helps no one
            self.metrics.increment_counter(
                "app_request_deadline_exceeded_total", 1, where="engine")
            raise DeadlineExceeded(
                "request deadline already expired at submission")
        # multi-LoRA routing (gofr_tpu.adapters; docs/serving.md): resolve
        # the adapter BEFORE QoS admission — an adapter's declared default
        # class must key the class gates below — and take its per-adapter
        # concurrency share (429 at the cap, the per-tenant analog of the
        # per-class cap; released on the done callback like qos.track).
        if "adapter_id" in kw:  # public spelling of the internal routing key
            kw["_adapter"] = kw.pop("adapter_id")
        registry = getattr(self, "adapters", None)
        aname = kw.get("_adapter") or None
        aspec = None
        if aname:
            if registry is None:
                raise ValueError(
                    f"request names adapter {aname!r} but this engine has no "
                    "adapter plane (set ADAPTER_SLOTS or ADAPTER_POOL_MB)")
            try:
                aspec = registry.admit(aname)
            except KeyError as e:
                raise ValueError(str(e.args[0]) if e.args else str(e)) from None
            if aspec.qos_class and not kw.get("_qos_class"):
                kw["_qos_class"] = aspec.qos_class
        we = getattr(self, "weights_epoch", None)
        if we is not None:
            # base-weight epoch at submission: surfaced by the flight
            # recorder so "which weights answered this" stays debuggable
            # across live hot-swaps (engine.adopt_weights)
            kw["_weights_epoch"] = we
        qos, cls = self.qos, None
        if qos is not None:
            # admission BEFORE the request exists: backlog cap, per-class
            # concurrency cap, and the predicted-wait-vs-deadline check —
            # hopeless work is rejected with 429/503 + Retry-After here
            # instead of burning a slot and timing out later (docs/qos.md)
            try:
                cls = qos.admit_engine(self, kw.get("_qos_class"), eff_timeout)
            except Exception:
                if aspec is not None:
                    registry.release(aname)  # the class gate shed us first
                raise
            kw["_qos_class"] = cls.name
        req = Request(inputs, kw, eff_timeout, stream)
        if cls is not None:
            qos.track(req, cls)
        if aspec is not None:
            req.add_done_callback(lambda _r, _n=aname: registry.release(_n))
        if on_submit is not None:
            on_submit(req)
        self._observe_submit(req, parent_span)
        self._queue.put(req)
        self.metrics.set_gauge("app_tpu_queue_depth", self._backlog())
        return req

    # -- request-lifecycle observability ---------------------------------------

    def _observe_submit(self, req: Request, parent_span) -> None:
        """Open the request's observability lifecycle: span timeline (only
        behind ``Tracer.enabled`` — with ``TRACE_EXPORTER=none`` this whole
        path costs one branch and allocates nothing), the in-flight gauge,
        and the completion hook that records SLO metrics + the flight
        timeline however the request ends (result, error, timeout, stop)."""
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            if parent_span is None:
                parent_span = current_span()
            if parent_span is None or parent_span.sampled:
                rt = RequestTrace(tracer, parent_span)
                req.kw["_rt"] = rt
                rt.begin("engine.queue_wait",
                         **{"qos.class": req.kw.get("_qos_class") or "none",
                            "queue.depth": self._backlog()})
        with self._obs_lock:
            # per-engine counter; the app_tpu_inflight_requests gauge is
            # summed across registered engines at scrape time (container
            # collect hook) — an engine-side set here would flap the global
            # gauge between per-engine values when several engines serve
            self._inflight_requests += 1
        req.add_done_callback(self._observe_done)

    def _observe_done(self, req: Request) -> None:
        now = time.monotonic()
        with self._obs_lock:
            self._inflight_requests -= 1
        result, error = req.outcome()
        kw = req.kw
        rt = kw.pop("_rt", None)
        if rt is not None:
            rt.close_all(error)
        e2e = now - req.enqueued_at
        if self.slo is not None:
            # availability counts EVERY outcome (errors, timeouts, sheds all
            # burn budget); the e2e latency objective, like the histogram
            # below, judges completed work only
            self.slo.observe_outcome(kw.get("_qos_class"), error is None)
        if error is None:
            # completed work only: a timeout/shed storm must not drag the
            # served-latency SLO histogram toward its own failure mode
            self.metrics.record_histogram(
                "app_tpu_e2e_seconds", e2e, qos_class=kw.get("_qos_class") or "none")
            if self.slo is not None:
                self.slo.observe(kw.get("_qos_class"), "e2e", e2e)
        spec_proposed = kw.get("_spec_proposed")
        if spec_proposed:
            # lifetime per-adapter acceptance numerators for the
            # app_tpu_spec_accept_ratio gauge (container scrape divides;
            # keeping raw counts is what lets federation sum, not average)
            with self._obs_lock:
                tot = self._spec_totals.setdefault(
                    kw.get("_adapter") or "base", [0.0, 0.0])
                tot[0] += float(kw.get("_spec_accepted", 0))
                tot[1] += float(spec_proposed)
        if self.flight is None:
            return
        admitted = kw.get("_admitted_at")
        first = kw.get("_first_token_at")
        entry: dict[str, Any] = {
            "id": req.id,
            "completed_at": time.time(),
            "qos_class": kw.get("_qos_class"),
            "e2e_s": round(e2e, 6),
            "queue_wait_s": round(admitted - req.enqueued_at, 6) if admitted is not None else None,
            "ttft_s": round(first - req.enqueued_at, 6) if first is not None else None,
            "slot": kw.get("_slot"),
            "prompt_len": kw.get("_prompt_len"),
            "preemptions": kw.get("_preemptions", 0),
            "trace_id": rt.trace_id if rt is not None else None,
        }
        if kw.get("_adapter"):
            # which LoRA adapter served this request (None lanes omit the
            # field entirely — the common base-model case stays compact)
            entry["adapter"] = kw.get("_adapter")
        if kw.get("_weights_epoch") is not None:
            entry["weights_epoch"] = kw.get("_weights_epoch")
        dev = {label: round(kw[f], 6) for label, f in (
            ("prefill_s", "_dev_prefill_s"), ("decode_s", "_dev_decode_s"),
            ("swapin_s", "_dev_swapin_s")) if kw.get(f)}
        if dev:
            # device-queue residency while this request had work in flight,
            # per phase (folds accumulate it from the perf plane's clipped
            # step times) — with queue_wait_s and e2e_s this answers
            # "queue, device, or fold?" for a slow request
            entry["device"] = dev
        proposed = kw.get("_spec_proposed")
        if proposed:
            entry["spec_accept_rate"] = round(
                kw.get("_spec_accepted", 0) / proposed, 4)
        prefix = kw.get("_prefix")
        if prefix:
            # per-tier prefix-cache hit breakdown (hbm/host tokens + pages
            # swapped in from host DRAM) — docs/observability.md
            entry["prefix"] = prefix
        if req.cancelled and req.cancel_reason:
            # why the lifetime ended early (client_disconnect, timeout,
            # hedge_loser, ...) — the /debug/requests timeline's answer to
            # "who killed this request" (docs/resilience.md)
            entry["cancel_reason"] = req.cancel_reason
        if error is not None:
            entry["error"] = type(error).__name__
        elif isinstance(result, dict) and "finish_reason" in result:
            entry["finish_reason"] = result.get("finish_reason")
            toks = result.get("tokens")
            if toks is not None:
                entry["new_tokens"] = len(toks)
                if first is not None and len(toks) > 1:
                    entry["tpot_s"] = round((now - first) / (len(toks) - 1), 6)
        self.flight.record_request(entry)

    def _mark_admitted(self, req: Request, now: float) -> None:
        """First pick-up by the device loop: close the queue-wait phase.
        Guarded so preemption-by-recompute re-admissions don't double-count
        the SLO histogram."""
        if "_admitted_at" not in req.kw:
            req.kw["_admitted_at"] = now
            self.metrics.record_histogram(
                "app_tpu_queue_wait_seconds", now - req.enqueued_at)
        rt = req.kw.get("_rt")
        if rt is not None:
            rt.end("engine.queue_wait")

    def _mark_first_token(self, req: Request) -> None:
        """Stamp TTFT exactly once (preemption preserves the original)."""
        if "_first_token_at" not in req.kw:
            ft = time.monotonic()
            req.kw["_first_token_at"] = ft
            self.metrics.record_histogram(
                "app_tpu_ttft_seconds", ft - req.enqueued_at)
            if self.slo is not None:
                self.slo.observe(req.kw.get("_qos_class"), "ttft",
                                 ft - req.enqueued_at)

    def _record_step(self, kind: str, seconds: float, occupancy: float,
                     signature: tuple, pstep=None, adapter_ids=None) -> float:
        # called at COMPLETION (dequeue) time under the unified pipeline:
        # `seconds` spans dispatch→fold, so it includes the overlapped
        # in-flight wait, not just device compute. `pstep` (a perf.StepPerf
        # built at dispatch, t_ready stamped right after readback) carries
        # the roofline side: the perf plane clips it to true device-queue
        # residency and bubble, recorded separately from this wall span.
        self.metrics.record_histogram("app_tpu_step_seconds", seconds, kind=kind)
        self.metrics.record_histogram("app_tpu_batch_occupancy", occupancy, kind=kind)
        device_s = 0.0
        perf = getattr(self, "perf", None)
        if pstep is not None and perf is not None:
            from gofr_tpu.metrics.perf import occupancy_band

            now_perf = time.monotonic()
            # band label keys the controller's evidence windows: the same
            # knob can win at high occupancy and lose near-empty, so
            # judgments (and persisted pins) are per occupancy band
            perf.note(pstep, now_perf, band=occupancy_band(occupancy))
            if adapter_ids:
                # per-adapter roofline attribution (metrics/perf.py): one
                # id per dispatched lane ("base" for adapterless lanes), a
                # complete partition of the step — per-adapter device-
                # seconds sum exactly to the step's, the COGS invariant
                perf.note_adapters(adapter_ids, pstep, now_perf)
            device_s = pstep.device_s
            self.metrics.record_histogram(
                "app_tpu_step_device_seconds", device_s, kind=kind)
        if self.flight is not None:
            # active knob vector on every step entry: a replayed anomaly
            # bundle shows WHICH tuning the anomalous step ran under
            # (BatchEngine has no knobs — None elides the field)
            kv_fn = getattr(self, "knob_vector", None)
            knobs = kv_fn() if kv_fn is not None else None
            if pstep is not None:
                self.flight.record_step(
                    kind, seconds, occupancy, signature,
                    self._backlog(), len(getattr(self, "_dq", ())),
                    device_s=device_s, bytes_=pstep.bytes,
                    flops=pstep.flops, bubble_s=pstep.bubble_s, knobs=knobs)
            else:
                self.flight.record_step(kind, seconds, occupancy, signature,
                                        self._backlog(), len(getattr(self, "_dq", ())),
                                        knobs=knobs)
        if self.qos is not None:
            self.qos.observe_step(seconds)  # feeds the queue-wait estimator
        if signature in self._compiled:
            self.metrics.increment_counter("app_tpu_compile_cache_hits", 1)
        else:
            self._compiled.add(signature)
            self.tpu.record_compile()
        return device_s

    def health_check(self) -> dict[str, Any]:
        if self._startup_error is not None:
            return {"status": "DOWN", "details": {"error": str(self._startup_error)}}
        if self._restarting:
            return {"status": "DEGRADED",
                    "details": {"restarting": True, "restarts": self._restarts}}
        detail: dict[str, Any] = {"queue_depth": self._backlog(), "restarts": self._restarts}
        if self._draining:
            detail["draining"] = True
        return {
            "status": "UP" if self._thread is not None and self._thread.is_alive() else "DEGRADED",
            "details": detail,
        }


# -- stateless batching (embed / classify) -------------------------------------


class BatchEngine(_EngineBase):
    """Drain-and-batch engine for stateless models.

    ``apply_fn(padded_inputs, lengths) -> outputs[B, ...]`` must be
    jit-compiled with static shapes per (len_bucket, batch_bucket).
    ``encode_fn`` turns one request's inputs into a 1-D token array (or
    fixed-shape array for images, in which case buckets only apply to
    batch).
    """

    def __init__(
        self,
        apply_fn: Callable,
        container,
        *,
        encode_fn: Callable[[Any], np.ndarray] | None = None,
        decode_fn: Callable[[np.ndarray], Any] | None = None,
        max_batch: int = 32,
        len_buckets: list[int] | None = None,
        max_wait_ms: float = 2.0,
        default_timeout: float | None = None,
        max_restarts: int = 3,
    ):
        super().__init__(container, default_timeout=default_timeout, max_restarts=max_restarts)
        self.apply_fn = apply_fn
        self.encode_fn = encode_fn or (lambda x: np.asarray(x))
        self.decode_fn = decode_fn or (lambda row: row)
        self.max_batch = max_batch
        self.len_buckets = sorted(len_buckets) if len_buckets else _pow2_buckets(16, 512)
        self.max_wait = max_wait_ms / 1000.0
        self.batch_buckets = _pow2_buckets(1, max_batch)

    def infer(self, inputs: Any, timeout: float | None = None, **kw: Any) -> Any:
        req = self._submit(inputs, timeout, **kw)
        return req.result(timeout if timeout is not None else self.default_timeout)

    def warmup(self, example: Any, len_buckets: list[int] | None = None,
               batch_buckets: list[int] | None = None) -> int:
        """Pre-compile the (len bucket × batch bucket) apply signatures so no
        XLA compile lands in the serving window (GenerateEngine.warmup
        parity). ``example`` is one representative request input — token
        sequences warm every (len, batch) pair, fixed-shape inputs (images)
        warm batch buckets only. Call before serving traffic."""
        from gofr_tpu.ops.pallas import platform_hint

        arr = np.asarray(self.encode_fn(example))
        bbs = sorted(batch_buckets) if batch_buckets else self.batch_buckets
        count = 0
        with platform_hint(getattr(self.tpu, "platform", None)):
            if arr.ndim == 1:
                lbs = sorted(len_buckets) if len_buckets else self.len_buckets
                for lb in lbs:
                    for nb in bbs:
                        # via numpy so dtype canonicalization matches _step
                        # (a direct jnp.zeros(int64) would warn per bucket)
                        tokens = jnp.asarray(np.zeros((nb, lb), arr.dtype))
                        lens = jnp.asarray(np.ones((nb,), np.int32))
                        jax.block_until_ready(self.apply_fn(tokens, lens))
                        self._compiled.add(("batch", lb, nb))
                        count += 1
            else:
                for nb in bbs:
                    stacked = jnp.asarray(np.zeros((nb, *arr.shape), arr.dtype))
                    jax.block_until_ready(self.apply_fn(stacked))
                    self._compiled.add(("batch", arr.shape, nb))
                    count += 1
        return count

    def _drain(self) -> list[Request]:
        """Block for one request, then grab whatever arrives within
        max_wait (micro-batch accumulation), up to max_batch."""
        try:
            first = self._queue.get(timeout=0.2)
        except queue.Empty:
            return []
        batch = [first]
        deadline = time.monotonic() + self.max_wait
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        self.metrics.set_gauge("app_tpu_queue_depth", self._queue.qsize())
        now = time.monotonic()
        live = []
        for r in batch:
            if r.cancelled or r.expired(now):
                r.complete(error=RequestTimeout())
            else:
                live.append(r)
        return live

    def _loop(self) -> None:
        while not self._stop.is_set() and not self._poisoned:
            batch = self._drain()
            if not batch:
                continue
            try:
                self._step(batch)
            except Exception as e:  # noqa: BLE001
                self.logger.log_exception(e, "batch engine step")
                for r in batch:
                    r.complete(error=e)

    def _step(self, batch: list[Request]) -> None:
        arrays = [np.asarray(self.encode_fn(r.inputs)) for r in batch]
        n = len(arrays)
        nb = next_bucket(n, self.batch_buckets)
        now = time.monotonic()
        for r in batch:
            self._mark_admitted(r, now)
            rt = r.kw.get("_rt")
            if rt is not None:
                rt.begin("engine.infer", **{"batch.size": n, "batch.bucket": nb})
        self._inflight = list(batch)
        t0 = time.monotonic()

        if arrays[0].ndim == 1:  # token sequences: pad to a length bucket
            lengths = np.array([a.shape[0] for a in arrays], np.int32)
            lb = next_bucket(int(lengths.max()), self.len_buckets)
            tokens = np.zeros((nb, lb), arrays[0].dtype)
            for i, a in enumerate(arrays):
                tokens[i, : a.shape[0]] = a
            lens = np.zeros((nb,), np.int32)
            lens[:n] = lengths
            lens[n:] = 1  # padded rows: nonzero length avoids div-by-zero paths
            signature = ("batch", lb, nb)
            out = self.apply_fn(jnp.asarray(tokens), jnp.asarray(lens))
        else:  # fixed-shape inputs (images): batch bucket only
            stacked = np.zeros((nb, *arrays[0].shape), arrays[0].dtype)
            for i, a in enumerate(arrays):
                stacked[i] = a
            signature = ("batch", arrays[0].shape, nb)
            out = self.apply_fn(jnp.asarray(stacked))

        out = np.asarray(out)
        self._inflight = []
        self._record_step("batch", time.monotonic() - t0, n / nb, signature)
        self.metrics.increment_counter("app_tpu_tokens_total", int(n))
        for i, r in enumerate(batch):
            rt = r.kw.get("_rt")
            if rt is not None:
                rt.end("engine.infer", **{"batch.occupancy": n / nb})
            r.complete(result=self.decode_fn(out[i]))  # idempotent: no-op if already failed


# -- continuous batching (generate) --------------------------------------------


class _Slot:
    """One active generation. Invariants: ``generated`` holds every output
    token so far (last one's K/V not yet in cache); ``pos`` is the cache
    position the last token will be written to on the next decode step,
    i.e. ``prompt_len + len(generated) - 1``.

    A slot admitted with ``first_token=None`` is in the *prefill* stage —
    its lane is claimed (reserved against decode, admission, and page
    reuse) while the prefill device work is in flight. Batched prefills
    dispatch the whole prompt at once (``dispatched == prompt_len``) and
    activate at dequeue; chunked prefills stream the prompt in
    bucket-sized chunks (``written`` counts tokens whose write was read
    back), joining decode once the final chunk's dequeue samples the
    first token (SURVEY §7 hard parts (a)/(b): long prompts stream into
    the cache between decode steps instead of inflating one batch's
    padding or being rejected)."""

    __slots__ = ("request", "prompt_len", "pos", "generated", "max_total", "eos",
                 "last_token", "first_token_at", "admit_seq", "prompt_tokens",
                 "written", "dispatched", "inflight", "adapter_id", "adapter_slot",
                 "handoff")

    def __init__(self, request: Request, prompt_len: int, max_total: int, eos: int | None,
                 first_token: int | None, admit_seq: int = 0, prompt_tokens: Any = None,
                 adapter_id: str | None = None, adapter_slot: int = 0):
        self.request = request
        self.prompt_len = prompt_len
        self.pos = prompt_len
        self.generated = [first_token] if first_token is not None else []
        self.max_total = max_total
        self.eos = eos
        self.last_token = first_token
        self.first_token_at = time.monotonic()
        self.admit_seq = admit_seq       # preemption order (paged layout)
        self.prompt_tokens = prompt_tokens  # kept for preemption re-prefill
        self.written = prompt_len if first_token is not None else 0
        # prompt tokens whose device write is DISPATCHED (>= written, which
        # counts tokens whose write was read back): the chunked path advances
        # `dispatched` at dispatch and `written` at dequeue, so several
        # chunks of one prompt can ride the in-flight queue at once
        self.dispatched = self.written
        self.inflight = 0  # decode chunks dispatched but not yet processed
        # multi-LoRA lane binding (gofr_tpu.adapters): the registry name
        # and the device pool slot whose factors this lane gathers in
        # every step; (None, 0) is the base model (pool slot 0 is the
        # reserved all-zeros adapter — bit-identical to no adapters)
        self.adapter_id = adapter_id
        self.adapter_slot = adapter_slot
        # streaming KV handoff transfer (prefill role, tpu/handoff.py
        # StreamTransfer): pages of a still-prefilling slot ship per
        # chunk fold instead of all-at-once at activation
        self.handoff = None

    @property
    def prefilling(self) -> bool:
        # the lane-set stage predicate (engine._claim_slot / testutil.
        # assert_lane_sets_consistent): a batched-prefill slot has
        # written == 0 but leaves the prefill stage only when its fold
        # delivers the first token
        return self.last_token is None


class _StreamIterator:
    """Token-stream iterator with an explicit ``cancel()`` so transports can
    free the slot when the client disconnects mid-generation (otherwise the
    engine would decode to max_new_tokens for a client that is gone)."""

    def __init__(self, req: Request, gen: Iterator[Any]):
        self._req = req
        self._gen = gen

    def __iter__(self) -> "_StreamIterator":
        return self

    def __next__(self) -> Any:
        return next(self._gen)

    def cancel(self, reason: str = "client_disconnect") -> None:
        self._req.cancel(reason)


class GenerateEngine(_EngineBase):
    """Slot-based continuous batching for decoder LMs (family must expose
    ``prefill``, ``decode_step``, ``make_cache`` — see models.llama)."""

    def __init__(
        self,
        family: Any,
        cfg: Any,
        params: Any,
        container,
        *,
        slots: int = 8,
        max_len: int = 2048,
        prefill_buckets: list[int] | None = None,
        max_prefill_batch: int = 4,
        decode_chunk: int = 8,
        eos_token_id: int | None = None,
        top_k: int = 0,
        top_p: float = 1.0,
        tokenizer: Any = None,
        default_timeout: float | None = None,
        seed: int = 0,
        kv_layout: str = "slot",
        page_size: int = 128,
        total_pages: int | None = None,
        paged_kv_write: str = "",
        max_restarts: int = 3,
        decode_pipeline: int = 2,
        prefix_cache: bool = True,
        prefix_host_mb: float = 0.0,
        spec_tokens: int = 0,
        kv_quantize: str = "",
        kv_shard: str = "auto",
        prefill_attn_fn: Any = None,
        prefill_attn_divisor: int = 1,
        lockstep_role: str | None = None,
        fleet: Any = None,
        spec_draft: tuple | None = None,
        pipeline_depth: int | None = None,
        role: str = "both",
        handoff_target: str | None = None,
        handoff_listen: str | None = None,
        handoff_timeout_s: float = 5.0,
        handoff_streams: int = 2,
        handoff_chunk_pages: int = 4,
        handoff_pace_mbps: float = 0.0,
        adapter_slots: int = 0,
        adapter_rank: int = 16,
        adapter_pool_mb: float = 0.0,
        adapter_host_mb: float = 256.0,
        adapter_hotswap_dir: str | None = None,
        adapter_hotswap_poll_s: float = 5.0,
        quality_shadow_rate: float = 0.0,
        quality_seed: int | None = None,
        quality_max_pending: int = 16,
        quality_max_tokens: int = 64,
        quality_top1_min: float = 0.9,
        quality_kl_max: float = 1.0,
        quality_recent: int = 32,
        control_enable: bool = False,
    ):
        super().__init__(container, default_timeout=default_timeout, max_restarts=max_restarts)
        self.family = family
        self.cfg = cfg
        self.params = params
        self.num_slots = slots
        self.max_len = min(max_len, cfg.max_seq_len)
        self.prefill_buckets = sorted(prefill_buckets) if prefill_buckets else _pow2_buckets(
            16, self.max_len
        )
        if prefill_attn_fn is not None and prefill_attn_divisor > 1:
            bad = [b for b in self.prefill_buckets if b % prefill_attn_divisor]
            if bad:
                # fail at BUILD time, not on the first prompt that lands in
                # an indivisible bucket mid-serving (the top bucket is
                # max_len itself, which need not be a power of two)
                raise ValueError(
                    f"prefill buckets {bad} are not divisible by the "
                    f"sequence-parallel axis size {prefill_attn_divisor}; "
                    f"set ENGINE_MAX_LEN (or prefill_buckets) to multiples of it"
                )
        self.max_prefill_batch = max_prefill_batch
        self.eos_token_id = eos_token_id
        self.tokenizer = tokenizer
        self.top_k = top_k
        self.top_p = top_p

        # K decode steps run on-device per host round trip, with sampling
        # fused into the step — the host sees [slots, K] int32 tokens, never
        # logits. This is the difference between per-token host syncs (the
        # reference's per-request goroutine equivalent) and a device-resident
        # loop; it also keeps serving fast over high-latency device links.
        self.decode_chunk = max(1, decode_chunk)

        # Speculative decoding (VERDICT r3 #6): each outer decode step
        # proposes spec_tokens continuation tokens — prompt-lookup from the
        # slot's own device-resident history, or a draft MODEL (spec_draft)
        # — then ONE target forward verifies all of them. Acceptance is
        # distribution-exact rejection sampling (programs.speculative_
        # sample): sampled requests emit tokens distributed exactly as
        # plain sampled decode, and greedy requests (temperature 0) are the
        # special case whose outputs are bit-identical to plain greedy
        # decode — up to spec_tokens+1 tokens per target forward at the
        # memory-bound occupancies where decode wastes bandwidth.
        self.spec_tokens = max(0, int(spec_tokens))
        if self.spec_tokens:
            need = "verify_step" if kv_layout == "slot" else "verify_step_paged"
            if not hasattr(family, need):
                raise ValueError(
                    f"family {getattr(family, '__name__', family)!r} has no {need}; "
                    "speculative decoding needs it"
                )
        # Draft-model speculative decoding (VERDICT r4 #4): spec_draft is a
        # (family, cfg, params) triple for a small model sharing the target's
        # tokenizer/vocab. Drafts come from g autoregressive draft-model
        # steps on device instead of prompt lookup (tpu/programs.py); the
        # bit-exact greedy verify is unchanged, so the draft only moves the
        # acceptance rate — real text accepts far more than lookup can.
        if spec_draft is not None:
            if not self.spec_tokens:
                raise ValueError("spec_draft requires spec_tokens > 0")
            if kv_layout != "slot":
                raise ValueError(
                    "spec_draft (draft-model speculative decoding) is "
                    "slot-layout only (v1): the paged layout's page allocation "
                    "would need the draft cache paged too — use "
                    "kv_layout='slot' or drop spec_draft"
                )
            dfam = spec_draft[0]
            missing = [a for a in ("prefill", "decode_step", "make_cache")
                       if not hasattr(dfam, a)]
            if missing:
                raise ValueError(
                    f"spec_draft family {getattr(dfam, '__name__', dfam)!r} "
                    f"lacks {missing}; the draft must follow the slot-cache "
                    "decoder protocol"
                )
            if (getattr(family, "SLOT_CHUNKED_PREFILL", False)
                    and not getattr(dfam, "SLOT_CHUNKED_PREFILL", False)):
                raise ValueError(
                    "spec_draft family has no chunked (offset) prefill, but the "
                    "target serves long prompts through it — use a draft "
                    "family with SLOT_CHUNKED_PREFILL"
                )
        self._draft = None  # (family, cfg) once validated (slot branch below)
        # Unified device pipeline (depth 2 = one call in flight): EVERY
        # device call — batched prefill, chunked prefill, decode chunk,
        # slot-layout spec round — is dispatched onto one bounded in-flight
        # queue (self._dq) and its readback + host bookkeeping happen at
        # DEQUEUE, overlapped with the next dispatch. The decode data
        # dependency (t+1's input token = t's last output) stays ON DEVICE
        # via the `prev_last` carry — or, for speculative rounds on the
        # slot layout, the (token, hlen) spec carry plus the device-resident
        # history (tpu/programs.py); prefill has no such dependency (the
        # prompt is host-known), so its futures simply ride the queue.
        # Depth 1 drains the queue every iteration (the synchronous path,
        # token-identical). Over the round-3 tunnel (~100ms/sync) this is
        # the difference between RTT-bound and compute-bound serving.
        # `pipeline_depth` is the canonical knob (ENGINE_PIPELINE);
        # `decode_pipeline` (ENGINE_DECODE_PIPELINE) is the legacy alias.
        depth = pipeline_depth if pipeline_depth is not None else decode_pipeline
        self.pipeline_depth = max(1, min(4, int(depth)))
        self.decode_pipeline = self.pipeline_depth  # legacy alias (bench/tests)
        # Online-controller knob state (gofr_tpu.control): boot values are
        # the operator-provisioned CEILINGS — the step controller explores
        # within [1 .. boot], never past what the deployment was sized for.
        # ``prefill_chunk`` caps how much of a long prompt one chunked-
        # prefill dispatch takes (_advance_chunked); it is always a member
        # of prefill_buckets so the compiled-signature population stays the
        # boot set. Foreign threads (controller ticks run on the device
        # thread, but debug endpoints and bench drills do not) enqueue
        # changes via request_knobs; the device loop drains them at its
        # loop-top safe seam, the ONLY place knobs mutate.
        self._boot_pipeline_depth = self.pipeline_depth
        self._boot_prefill_batch = self.max_prefill_batch
        self._boot_spec_tokens = self.spec_tokens
        self.prefill_chunk = self.prefill_buckets[-1]
        self._knob_requests: collections.deque = collections.deque()
        self._control = None
        # cache slack one chunk can write past max_len: each spec round
        # writes up to spec_tokens+1 positions plus spec_tokens draft slots.
        # Sized from the BOOT spec_tokens and never resized: the controller
        # only lowers g below boot, so the dispatch-time masking bound
        # (pos + chunk_span*inflight) and the paged over-claim stay
        # conservative for every live g <= boot.
        chunk_span = (self.decode_chunk * (self.spec_tokens + 1) + self.spec_tokens
                      if self.spec_tokens else self.decode_chunk)
        self._chunk_span = chunk_span
        # One chunk_span of slack suffices at ANY pipeline depth: dispatch
        # masks a lane once its worst-case in-flight position
        # (pos + chunk_span*inflight) reaches max_total, so at dispatch
        # time the device-side hlen is < max_total and the new round's
        # writes stay < max_total + chunk_span — the same dead-lane bound
        # plain pipelined decode relies on (decode.dispatch_spec).
        requested_max_len = self.max_len
        self.max_len = min(self.max_len, cfg.max_seq_len - chunk_span)
        if self.max_len < requested_max_len:
            # Chunked decode needs decode_chunk of cache headroom past the
            # last admitted position; surface the shrink so operators see why
            # prompts near the advertised limit are rejected (ADVICE.md).
            self.logger.warn(
                f"engine max_len reduced {requested_max_len} -> {self.max_len} "
                f"(decode_chunk={self.decode_chunk} headroom within cfg.max_seq_len={cfg.max_seq_len})"
            )

        if kv_layout not in ("slot", "paged"):
            raise ValueError(f"kv_layout {kv_layout!r}: use 'slot' or 'paged'")
        # pp serving (models/llama_pp.py): decode runs microbatches over the
        # slot dimension. A non-dividing value would silently degrade to
        # gcd(slots, microbatches) — potentially 1 microbatch, the WORST
        # bubble fraction — so fail at build time like the sp bucket guard
        # (docs/configs.md documents the divisibility requirement).
        fam_mb = getattr(family, "microbatches", 0)
        if fam_mb and slots % fam_mb:
            raise ValueError(
                f"pipeline microbatches {fam_mb} (ENGINE_PP_MICROBATCHES, "
                f"default = the pp mesh degree) does not divide the slot "
                f"count {slots}: decode would fall back to "
                f"gcd={math.gcd(slots, fam_mb)} microbatches "
                f"(worse pipeline bubbles); align it with ENGINE_SLOTS"
            )
        if kv_layout == "paged" and not hasattr(family, "make_paged_cache"):
            raise ValueError(f"model family {family.__name__} has no paged-cache support")
        self.kv_layout = kv_layout

        # Engine role (disaggregated serving; tpu/handoff.py): "both"
        # keeps today's colocated behavior bit-for-bit; "prefill" exports
        # each prompt's full KV pages to the decode pool after prefill
        # instead of decoding locally; "decode" imports handed-off pages
        # as host-tier prefix nodes and serves the decode phase. Role
        # workers need the paged layout — the handoff payload IS pool
        # pages — and cannot combine with lockstep (followers could
        # never replay a transfer that arrived over a side channel).
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"ENGINE_ROLE {role!r}: use 'both', 'prefill' or 'decode'")
        if role != "both" and kv_layout != "paged":
            raise ValueError(
                f"ENGINE_ROLE={role} needs kv_layout='paged' "
                "(the KV handoff ships pool pages)")
        if role != "both" and lockstep_role:
            raise ValueError(
                "ENGINE_ROLE prefill/decode cannot combine with lockstep")
        self.role = role

        if kv_quantize not in ("", "int8", "int4"):
            raise ValueError(
                f"kv_quantize={kv_quantize!r}: use '', 'int8' or 'int4'")
        if kv_quantize == "int4" and kv_layout != "paged":
            # int4 exists as a PAGE format (two nibbles per byte packed
            # along head_dim; ops/paged.Q4PagedKVCache) — the slot layout
            # keeps int8 as its only quantized option
            raise ValueError(
                "kv_quantize='int4' needs kv_layout='paged' (packed-nibble "
                "pages); the slot layout supports '' or 'int8'")
        # tensor-parallel pool sharding (ENGINE_KV_SHARD): 1 = unsharded.
        # Resolved before the cache is built; the slot layout never shards.
        self.kv_shards = 1
        self._kv_pool_sharding = None
        if kv_layout == "paged":
            kvq_attr = ("make_paged_cache_q4" if kv_quantize == "int4"
                        else "make_paged_cache_q")
            if kv_quantize and not hasattr(family, kvq_attr):
                raise ValueError(
                    f"family {getattr(family, '__name__', family)!r} has no "
                    f"{kv_quantize} paged-KV support ({kvq_attr})"
                )
            self.kv_quantize = kv_quantize
            # Paged cache (ops.paged): HBM scales with tokens in flight, not
            # slots x max_len. Per-slot logical capacity stays max_len +
            # decode_chunk; physical pages are pooled and allocated on demand
            # (admission gate + preemption-by-recompute in _admit/_decode).
            self.page_size = page_size
            self.pages_per_slot = -(-(self.max_len + self._chunk_span) // page_size)
            # default pool = same HBM as the slot cache; shrink to
            # oversubscribe, or keep and raise `slots` for more concurrency
            self.total_pages = total_pages if total_pages else slots * self.pages_per_slot
            # KV append lowering, resolved from GOFR_PAGED_KV_WRITE exactly
            # ONCE here and pinned for every trace this engine drives
            # (_trace_scope → ops/paged.write_mode_scope) — ops/paged never
            # re-reads os.environ at trace time on the engine's behalf.
            from gofr_tpu.ops.paged import resolve_write_mode

            self.paged_kv_write = resolve_write_mode(paged_kv_write or None)
            # Shard the pool over the mesh's tp axis along KV heads
            # (ops/paged.pool_sharding): per-device plane bytes drop to
            # 1/tp, and every trace this engine drives pins a KVShardCtx
            # (_trace_scope) so the paged decode ops run per-shard under
            # shard_map. "auto" stands down (1 shard, bit-identical to the
            # unsharded engine) whenever the mesh/geometry can't split.
            self.kv_shards, self._kv_pool_sharding = self._resolve_kv_shard(kv_shard)
            # The in-place Pallas page append redirects OOB rows' aliased
            # tile fetch to page 0 (ops/pallas/kv_append.py) — reserve it
            # as a never-allocated sink so an OOB copy-through can never
            # share a tile with a real write in the same call (ADVICE r4)
            self._page_sink = 1 if self.paged_kv_write == "pallas" else 0
            if self.total_pages - self._page_sink < self.pages_per_slot:
                raise ValueError(
                    f"total_pages {self.total_pages} (minus {self._page_sink} "
                    f"sink) < pages_per_slot {self.pages_per_slot}: one "
                    "max-length request cannot fit"
                )
            self.cache = self._build_paged_cache()
            self._free_pages: list[int] = list(range(self._page_sink, self.total_pages))
            self._slot_pages: list[list[int]] = [[] for _ in range(slots)]
            # OOB convention: unallocated entries point one past the pool
            self._table = np.full((slots, self.pages_per_slot), self.total_pages, np.int32)
            # Pages are refcounted: slots AND the prefix cache hold shares,
            # and a page returns to the free pool only at refcount zero —
            # a prefix hit splices cached pages into several slots' tables
            # at once (tpu/prefix.py invariants).
            self._page_refs = np.zeros(self.total_pages, np.int64)
            from gofr_tpu.tpu.prefix import PrefixCache

            # Hierarchical cache host tier (ENGINE_PREFIX_HOST_MB): pages the
            # LRU eviction would drop are spilled to a bounded host-DRAM
            # buffer instead and swapped back in asynchronously over the
            # unified pipeline on a later hit (docs/serving.md). 0 keeps the
            # single-tier behavior bit-for-bit. Not wired under lockstep:
            # swap-in payloads are host-resident K/V that followers never
            # saw, so announcing the upload cannot reproduce it.
            host_mb = max(0.0, float(prefix_host_mb))
            if host_mb and lockstep_role:
                container.logger.warn(
                    "ENGINE_PREFIX_HOST_MB ignored under lockstep (swap-in "
                    "payloads cannot be announced to followers)"
                )
                host_mb = 0.0
            # per-page host-copy footprint across every cache plane (k/v for
            # bf16; k/v/ks/vs for int8) — the page axis is always axis 1
            self._page_bytes = sum(
                leaf.nbytes // self.total_pages for leaf in jax.tree.leaves(self.kv_cache)
            )
            # whole-pool LOGICAL footprint (.nbytes is global even for a
            # sharded array); page_pool_stats and /debug/perf report the
            # per-device slice (// kv_shards) so fleet sum-of-parts rollups
            # stay exact on sharded engines
            self._pool_bytes = sum(
                leaf.nbytes for leaf in jax.tree.leaves(self.kv_cache)
            )
            host_budget = int(host_mb * (1 << 20))
            if host_budget and host_budget < self._page_bytes:
                # a budget that cannot hold even one page would turn every
                # pool-pressure eviction into a gather+copy that is then
                # immediately dropped — pure overhead, no caching
                container.logger.warn(
                    f"ENGINE_PREFIX_HOST_MB={host_mb:g} is below one page's "
                    f"footprint ({self._page_bytes} bytes); host tier disabled"
                )
                host_budget = 0
            if role == "decode" and prefix_cache and not host_budget:
                # a decode worker IMPORTS handed-off pages as host-tier
                # nodes — without a budget every transfer would be dropped
                # at the door. Default a working buffer (the budget is a
                # cap, not an allocation); ENGINE_PREFIX_HOST_MB overrides.
                host_budget = max(self._page_bytes, 256 << 20)
            self._prefix = (PrefixCache(page_size, host_budget_bytes=host_budget)
                            if prefix_cache else None)
            if role == "decode" and (self._prefix is None
                                     or not self._prefix.host_budget):
                raise ValueError(
                    "ENGINE_ROLE=decode needs the prefix cache with a host "
                    "tier (the handoff import target); keep "
                    "ENGINE_PREFIX_CACHE on")
            self._cache_treedef = jax.tree.structure(self.kv_cache)
            # swap-in upload widths: a power-of-two bucket ladder like the
            # prefill buckets — one compiled upload program per bucket, and
            # a 1-page hit never ships pages_per_slot pages of zero padding
            self._swapin_buckets = _pow2_buckets(1, self.pages_per_slot)
            # swap-ins staged by _prefix_hit under the state lock, dispatched
            # by _admit right after releasing it; spills staged by
            # _evict_prefix_page, materialized to host by _materialize_spills
            # (both device-thread only)
            self._pending_swapins: list = []
            self._pending_spills: list = []
            if self._prefix is not None and (self._prefix.host_budget
                                             or role == "prefill"):
                # compile the spill gather EAGERLY: it is the one program
                # dispatched while the state lock is held (_evict_prefix_
                # page — and the prefill-role handoff export, which
                # gathers every exported page the same way), and warmup()
                # is optional — a first-spill JIT compile under the lock
                # would stall submit()/stop() for the compile duration.
                # The swap-in upload programs compile in warmup() or
                # lazily at dispatch, which runs unlocked.
                from gofr_tpu.ops.paged import gather_page

                jax.block_until_ready(
                    jax.tree.leaves(gather_page(self.kv_cache, jnp.int32(0)))[0])
            self._set_prefix_gauges()  # authoritative from construction on
        else:
            # cache headroom so a chunk never writes past Smax; round to a
            # kernel-friendly multiple of 128 when the model allows it
            cache_len = min(-(-(self.max_len + self._chunk_span) // 128) * 128,
                            cfg.max_seq_len)
            self._cache_len = cache_len
            # int8 KV (kvcache.QSlotKVCache): halves the cache bytes decode
            # attention streams per step — the long-context bandwidth lever
            # on top of weight-only int8 (VERDICT r3 #2)
            if kv_quantize and not hasattr(family, "make_cache_q"):
                raise ValueError(
                    f"family {getattr(family, '__name__', family)!r} has no int8 KV support"
                )
            self.kv_quantize = kv_quantize
            if spec_draft is not None:
                dfam, dcfg, dparams = spec_draft
                if getattr(dcfg, "max_seq_len", cache_len) < cache_len:
                    raise ValueError(
                        f"spec_draft max_seq_len {dcfg.max_seq_len} < engine "
                        f"cache length {cache_len}: the draft cache must cover "
                        "every position the target serves"
                    )
                self._draft = (dfam, dcfg)
                # every compiled program sees one params pytree; with a
                # draft it is {'t': target, 'd': draft} (tpu/programs.py)
                params = {"t": params, "d": dparams}
                self.params = params
            self.cache = self._build_slot_cache()
            self._prefix = None  # prefix caching needs the paged layout
        # -- live perf plane (metrics/perf.py; ROADMAP O3) -------------------
        # Exact accounting from the live pytrees: parameter bytes post-
        # quantization and the per-position pool footprint read off the
        # cache leaves (the 512/144/80 bf16/int8/int4 planes on the tiny
        # CPU config — NOT a nominal-dtype estimate, which would be 2x off
        # on backends that promote bf16 to fp32). Defensive: an exotic
        # family/pytree must never take the engine down with its meter.
        try:
            from gofr_tpu.metrics.perf import CostModel, PerfPlane
            from gofr_tpu.ops.quant import quantized_bytes

            if kv_layout == "paged":
                positions = self.total_pages * self.page_size
            else:
                positions = slots * self._cache_len
            pool_bytes = sum(
                leaf.nbytes for leaf in jax.tree.leaves(self.kv_cache))
            devices = getattr(self.tpu, "devices", None)
            dev_kind = (getattr(devices[0], "device_kind", None) if devices
                        else None) or getattr(self.tpu, "platform", "cpu")
            # per-DEVICE pricing: a tp-sharded pool moves 1/kv_shards of
            # every plane byte through each device, and the fleet rollup
            # (sum-of-parts, metrics/perf.py) multiplies back by summing
            # over devices — the gap vs the single-chip roofline is then
            # the measured interconnect cost
            shards = max(1, getattr(self, "kv_shards", 1))
            self.perf = PerfPlane(
                CostModel(
                    n_params=sum(
                        leaf.size for leaf in jax.tree.leaves(self.params)),
                    weight_bytes=quantized_bytes(self.params),
                    kv_bytes_per_pos=pool_bytes / max(1, positions) / shards,
                    page_bytes=getattr(self, "_page_bytes", 0.0) / shards,
                    page_size=page_size if kv_layout == "paged" else 0,
                    kv_dtype=self.kv_quantize or "bf16",
                    kv_shards=shards,
                ),
                str(dev_kind))
        except Exception as e:  # pragma: no cover - meter must not gate serving
            container.logger.warn(f"perf plane disabled: {e}")
            self.perf = None
        # multi-host lockstep (tpu/lockstep.py): the leader announces every
        # device call so follower processes issue the same global programs.
        # ``fleet`` (a fleet.FleetConfig) switches the announce transport to
        # the host-side channel (fleet/channel.py): membership becomes
        # elastic (epoch-based rejoin) and the device-loop restart budget
        # stays available — a leader restart is an epoch bump, not fleet
        # death. Without it the collective transport's v1 semantics hold:
        # a crash-RESTART would reset step/carry state on the leader only,
        # desynchronizing followers — never restart in collective lockstep.
        self.lockstep_role = lockstep_role
        self._ls = None
        self._fleet = fleet
        self._seed = seed
        if lockstep_role and fleet is None:
            self.max_restarts = 0
        # follower liveness deadline (lockstep.py): leader heartbeats at a
        # third of it so watchdogs only fire on true leader death
        deadline = container.config.get_float("LOCKSTEP_DEADLINE_S", 0.0)
        self._hb_interval = deadline / 3 if deadline > 0 else 0.0
        if lockstep_role:
            # the cache is created process-locally; a multi-host global
            # program needs it placed as a GLOBAL (replicated) array (on a
            # fleet's process-local mesh the same placement replicates it
            # across the local devices)
            self.cache = self._place_cache(self.cache)
        self.slots: list[_Slot | None] = [None] * slots
        # Lane sets, maintained INCREMENTALLY at claim/free/stage-transition
        # time: the device loop consults free/decoding/prefilling lanes
        # several times per iteration, and rescanning self.slots was three
        # O(num_slots) attribute-chasing sweeps per step (hot at slots≥128).
        # Invariant: the three sets partition range(num_slots); a lane is in
        # _prefill_lanes iff its slot exists and has no first token yet.
        self._free_lanes: set[int] = set(range(slots))
        self._decode_lanes: set[int] = set()
        self._prefill_lanes: set[int] = set()
        # Reusable packed staging buffers keyed by (kind, shape): a steady-
        # state step re-zeroes a preallocated int32 buffer per signature
        # instead of paying an np.zeros allocation per device call. Buffers
        # rotate through a ring (STAGING_RING; see _staging) because the
        # per-replica host→device fetch of a dispatched call is async —
        # immediate reuse could be rewritten under a lagging replica. All
        # packing runs on the device thread; the population is bounded
        # like _compiled (bucket ladder).
        self._staging_bufs: dict[tuple, tuple] = {}
        # Warmup-time kernel-backend autotuner (ops/autotune.py; ROADMAP O3):
        # {op: backend} pins consulted by every trace via _trace_scope, the
        # report served at /debug/engine, and an injectable timer for
        # CPU-safe unit tests. Empty until warmup() measures (or loads the
        # GOFR_AUTOTUNE_CACHE entry for this exact shape/device).
        self._autotune_pins: dict[str, str] = {}
        self._autotune: dict | None = None
        self._autotune_timer = None
        self._pending: list[tuple[Request, np.ndarray]] = []
        # prompts longer than the largest prefill bucket: admitted one at a
        # time and streamed into the cache chunk-by-chunk. Paged always
        # supports this (prefill_paged offsets); slot layouts need the
        # family's prefill to accept offsets (SLOT_CHUNKED_PREFILL flag).
        self._pending_long: list[tuple[Request, np.ndarray]] = []
        self._chunked_ok = (kv_layout == "paged"
                            or getattr(family, "SLOT_CHUNKED_PREFILL", False))
        self._admit_seq = 0  # admission order (preemption picks newest)
        self._base_key = jax.random.key(seed)
        self._step_count = 0
        self._dq: collections.deque = collections.deque()  # dispatched, unprocessed
        self._prev_last = None  # device-resident [slots] last-sampled-token carry
        self._spec_carry = None  # device-resident ([slots] token, [slots] hlen)

        # -- multi-LoRA adapter plane (gofr_tpu.adapters; docs/serving.md) ---
        # Registry = host tier (named specs, per-adapter concurrency caps,
        # ADAPTER_HOST_MB budget); pool = device tier (fixed-shape HBM
        # slots, refcounted + LRU like KV pages; slot 0 is the reserved
        # all-zeros BASE adapter). The pool arrays ride every program call
        # as DYNAMIC jit args, so uploads/evictions — and the full-model
        # hot-swap below — never recompile. Disabled (the default), the
        # packed layouts and program signatures are byte-identical to the
        # pre-adapter engine.
        ad_slots = int(adapter_slots)
        ad_rank = max(1, int(adapter_rank))
        if adapter_pool_mb and not ad_slots:
            from gofr_tpu.adapters import AdapterPool

            ad_slots = AdapterPool.slots_for_budget(
                float(adapter_pool_mb), cfg.hidden_size, cfg.vocab_size, ad_rank)
        if ad_slots and lockstep_role:
            # the ENGINE_PREFIX_HOST_MB precedent (above): adapter uploads
            # are host-initiated device writes the announce stream cannot
            # reproduce on followers
            container.logger.warn(
                "ADAPTER_* ignored under lockstep (pool uploads cannot be "
                "announced to followers)")
            ad_slots = 0
        if ad_slots and not getattr(family, "SUPPORTS_ADAPTERS", False):
            raise ValueError(
                f"family {getattr(family, '__name__', family)!r} does not "
                "support per-lane adapters (no SUPPORTS_ADAPTERS entry "
                "points); drop ADAPTER_SLOTS/ADAPTER_POOL_MB")
        self._adapters_enabled = bool(ad_slots)
        self.adapters = None
        self._adapter_pool = None
        if self._adapters_enabled:
            from gofr_tpu.adapters import AdapterPool, AdapterRegistry

            self._adapter_pool = AdapterPool(
                max(2, ad_slots), cfg.hidden_size, cfg.vocab_size, ad_rank)
            self.adapters = AdapterRegistry(
                host_budget_mb=float(adapter_host_mb))
        # -- live weight hot-swap (adopt_weights / adopt_checkpoint) ---------
        # weights_epoch counts full-model adoptions; it feeds fleet.epoch_of
        # so router gossip sees a strict epoch bump and never routes one
        # request across mismatched weights (docs/serving.md).
        self.weights_epoch = 0
        self._pending_weights = None
        self._swap_lock = threading.Lock()
        hotswap_dir = str(adapter_hotswap_dir or "") or None
        if hotswap_dir and lockstep_role:
            container.logger.warn(
                "ADAPTER_HOTSWAP_DIR ignored under lockstep (weight adoption "
                "cannot be announced to followers)")
            hotswap_dir = None
        self._hotswap_dir = hotswap_dir
        self._hotswap_poll_s = max(0.5, float(adapter_hotswap_poll_s))
        self._hotswap_last = 0.0
        # steps already present at build time ARE the serving weights —
        # only checkpoints that appear later trigger adoption
        self._hotswap_seen = (self._scan_hotswap_steps()
                              if self._hotswap_dir else None)

        # -- quality plane (metrics/quality.py; docs/observability.md) -------
        # Shadow-score a sampled fraction of completed requests against the
        # reference configuration (dense bf16 KV, base weights), on idle
        # device-loop iterations only. Rate 0 (the default) never constructs
        # the plane: the serving path pays exactly one `is None` branch and
        # stays bit-identical to the pre-quality engine.
        self._quality = None
        rate = max(0.0, min(1.0, float(quality_shadow_rate)))
        if rate > 0.0 and not hasattr(family, "forward"):
            container.logger.warn(
                "QUALITY_SHADOW_RATE ignored: family "
                f"{getattr(family, '__name__', family)!r} has no teacher-"
                "forcing `forward` entry point")
            rate = 0.0
        if rate > 0.0:
            from gofr_tpu.metrics.quality import QualityPlane

            def _adapter_factors(name: str):
                if self.adapters is None:
                    return None
                try:
                    spec = self.adapters.get(name)
                except KeyError:
                    return None
                return (spec.a, spec.b, spec.scale)

            self._quality = QualityPlane(
                family, cfg,
                # late-bound: hot-swap replaces self.params; the reference
                # arm must always score with the CURRENTLY served weights
                lambda: self.params,
                metrics=self.metrics,
                slo=self.slo,
                rate=rate,
                # QUALITY_SEED unset (None / negative) → the engine's own
                # sampler seed, so one knob replays the shadow schedule too
                seed=(self._seed if quality_seed is None
                      or int(quality_seed) < 0 else int(quality_seed)),
                kv_dtype=self.kv_quantize or "bf16",
                backend_fn=self._quality_backend,
                adapter_fn=_adapter_factors,
                max_pending=quality_max_pending,
                max_tokens=quality_max_tokens,
                top1_min=quality_top1_min,
                kl_max=quality_kl_max,
                recent=quality_recent,
            )
        # per-adapter lifetime (accepted, proposed) speculative-decode
        # totals — the always-on quality proxy the container samples into
        # the app_tpu_spec_accept_ratio gauge (sum-of-parts, never averaged)
        self._spec_totals: dict[str, list[float]] = {}

        # Compiled packed-program handles (tpu/programs.py documents the
        # packed layouts; lockstep followers call the same handles).
        progs = build_programs(
            family, cfg,
            kv_layout=kv_layout,
            spec_tokens=self.spec_tokens,
            top_k=top_k,
            top_p=top_p,
            pages_per_slot=getattr(self, "pages_per_slot", 0),
            page_size=page_size,
            cache_len=getattr(self, "_cache_len", 0),
            prefill_attn_fn=prefill_attn_fn,
            draft=self._draft,
            adapters=self._adapters_enabled,
        )
        self._prefill_sample = progs.prefill_sample
        if progs.chunk_prefill is not None:
            self._chunk_prefill = progs.chunk_prefill
        self._decode_chunk = progs.decode_chunk
        if progs.spec_chunk is not None:
            self._spec_chunk_fn = progs.spec_chunk
        # per-g spec program map for the controller's spec_tokens knob: the
        # round length g is baked into the jitted spec round, so moving the
        # knob swaps the compiled handle rather than re-tracing mid-flight.
        # Build kwargs are kept so other g values (always < boot) compile
        # lazily on first use (_spec_fn_for); only spec_chunk is taken from
        # those rebuilds — every other program handle is g-independent.
        self._progs_kw = dict(
            kv_layout=kv_layout, top_k=top_k, top_p=top_p,
            pages_per_slot=getattr(self, "pages_per_slot", 0),
            page_size=page_size, cache_len=getattr(self, "_cache_len", 0),
            prefill_attn_fn=prefill_attn_fn, draft=self._draft,
            adapters=self._adapters_enabled)
        self._spec_fns = ({self.spec_tokens: progs.spec_chunk}
                          if progs.spec_chunk is not None else {})

        # Online step controller (gofr_tpu.control, docs/serving.md): OFF
        # by default — CONTROL_ENABLE=0 never constructs it, leaving the
        # engine bit-identical to the pre-controller build (the quality-
        # plane discipline). Lockstep replicas never get one either:
        # leader-only knob moves would change compiled signatures the
        # followers are not announced.
        if control_enable and self.perf is not None and lockstep_role is None:
            try:
                self._control = self._build_controller(container)
            except Exception as e:  # pragma: no cover - control must not gate serving
                container.logger.warn(f"step controller disabled: {e}")

        # lockstep announcer, last: a fleet LEADER starts listening here
        # and blocks until FLEET_FOLLOWERS identical-fingerprint followers
        # dialed in — the whole engine must exist first (the fingerprint
        # covers the resolved geometry, and admitted followers immediately
        # receive whatever warmup()/the device loop announces next)
        if lockstep_role == "leader":
            from gofr_tpu.tpu.lockstep import LockstepLeader

            if fleet is not None:
                from gofr_tpu.fleet import FleetLeaderChannel

                ch = FleetLeaderChannel(
                    fleet.listen, fingerprint=self.fleet_fingerprint(),
                    logger=self.logger, metrics=self.metrics)
                self._ls = LockstepLeader(channel=ch, epoch=fleet.epoch)
                self.metrics.set_gauge("app_fleet_epoch", self._ls.epoch)
                if fleet.followers:
                    self._ls.wait_ready(fleet.followers, fleet.ready_timeout_s)
                    self.metrics.set_gauge(
                        "app_fleet_followers", self._ls.follower_count())
                    self.logger.infof(
                        "fleet leader ready: %d follower(s) at epoch %d (port %d)",
                        self._ls.follower_count(), self._ls.epoch, ch.port)
            else:
                self._ls = LockstepLeader()

        # -- disaggregation handoff plumbing (tpu/handoff.py) ----------------
        # decode role: listen for KV frames from prefill workers; prefill
        # role: export to HANDOFF_TARGET (without a target the worker
        # decodes locally — the colocated fallback keeps it correct while
        # the decode pool is still coming up). handoff_addr rides the
        # gossip snapshot so the router's fleet view can show the wiring.
        self.handoff_timeout_s = float(handoff_timeout_s)
        # GOFR-HANDOFF2 streaming knobs (docs/serving.md "Streaming
        # handoff"): streams=0 forces the HANDOFF1 blob path outright;
        # chunk_pages batches staged pages per wire chunk; pace_mbps is
        # the emulated/egress bandwidth cap (0 = off)
        self.handoff_streams = max(0, int(handoff_streams))
        self.handoff_chunk_pages = max(1, int(handoff_chunk_pages))
        self.handoff_pace_mbps = max(0.0, float(handoff_pace_mbps))
        self._handoff_exporter = None
        self._handoff_server = None
        self.handoff_addr = ""
        if self.role == "decode":
            from gofr_tpu.tpu.handoff import HandoffServer

            self._handoff_server = HandoffServer(
                self, handoff_listen or "127.0.0.1:0",
                logger=self.logger, metrics=self.metrics)
            self.handoff_addr = self._handoff_server.addr
            self.logger.infof("kv handoff import listening at %s",
                              self.handoff_addr)
        elif self.role == "prefill":
            if handoff_target:
                from gofr_tpu.tpu.handoff import HandoffExporter

                self._handoff_exporter = HandoffExporter(
                    handoff_target, engine=self,
                    timeout_s=self.handoff_timeout_s,
                    streams=self.handoff_streams,
                    chunk_pages=self.handoff_chunk_pages,
                    pace_mbps=self.handoff_pace_mbps,
                    logger=self.logger, metrics=self.metrics)
            else:
                self.logger.warn(
                    "ENGINE_ROLE=prefill without HANDOFF_TARGET: prompts "
                    "decode locally (colocated fallback)")

    # -- public API ------------------------------------------------------------

    def warmup(self, len_buckets: list[int] | None = None,
               batch_buckets: list[int] | None = None) -> int:
        """Pre-compile every (prefill len-bucket × batch-bucket) signature
        plus the decode program, so no XLA compile lands inside the serving
        window (compiles cost seconds; over a tunneled device they dominate
        early-traffic latency). Safe for cache contents: prefill warmup rows
        use out-of-bounds slot ids / block tables, whose scatter writes XLA
        drops; decode warmup writes are below any live slot's attention
        length mask. Call before serving traffic, not concurrently with it.
        Returns the number of programs compiled."""
        from gofr_tpu.ops.pallas import platform_hint

        lbs = sorted(len_buckets) if len_buckets else self.prefill_buckets
        bbs = sorted(batch_buckets) if batch_buckets else _pow2_buckets(1, self.max_prefill_batch)
        # same platform pin as the device thread (_run): without it, warmup
        # traces on the caller thread could resolve kernels for the wrong
        # backend (e.g. Pallas for a CPU test mesh under an attached TPU),
        # and jit would cache that mis-resolved program per shape
        with platform_hint(getattr(self.tpu, "platform", None)):
            # backend autotune runs BEFORE the programs trace: the pins it
            # produces are what _trace_scope makes the traces below see
            self._autotune_backends()
            with self._trace_scope():
                return self._warmup_traced(lbs, bbs)

    def _warmup_traced(self, lbs: list[int], bbs: list[int]) -> int:
        # the compile body lives in the executor layer (tpu/executor.py,
        # warmup_compile) and is ROLE-scoped there: a prefill worker
        # skips the decode/spec compiles, a decode worker skips the
        # batched-prefill ladder — most of a role spare's warmup win
        return executor.warmup_compile(self, lbs, bbs)

    def _autotune_backends(self) -> None:
        """Measure Pallas vs XLA for this engine's decode attention op on
        its REAL serving shapes and pin the winner for every trace
        (ops/autotune.py; ROADMAP O3). Replaces the static GOFR_PALLAS
        gate with a per-(op, shape, kv dtype, device_kind) decision, cached
        across restarts via GOFR_AUTOTUNE_CACHE. Stands down when the
        autotuner is disabled (GOFR_AUTOTUNE=0 / explicit GOFR_PALLAS /
        interpreter mode) and under lockstep — a leader-only pin would make
        leader and follower trace DIFFERENT decode programs, and the
        announce protocol has no way to reproduce a timing on the
        follower's behalf."""
        from gofr_tpu.ops import autotune

        if self.lockstep_role or self._autotune_pins or not autotune.enabled():
            return
        if self.role == "prefill":
            # every op the tuner races is decode attention; a prefill-role
            # worker never traces one. Pins stay role-scoped regardless via
            # autotune.entry_key(..., role), so a colocated engine's cache
            # entries are untouched either way.
            self._autotune = {"skipped": "prefill role: no decode ops to tune"}
            return
        from gofr_tpu.ops import attention as attn_ops
        from gofr_tpu.ops.pallas import kernel_platform

        cfg = self.cfg
        hq = getattr(cfg, "num_heads", 0)
        hkv = getattr(cfg, "num_kv_heads", hq)
        d = getattr(cfg, "head_size", None) or getattr(cfg, "head_dim", 0)
        if not (hq and hkv and d):  # family exposes no GQA geometry
            return
        qdtype = getattr(cfg, "dtype", jnp.bfloat16)
        devices = getattr(self.tpu, "devices", None)
        kind = (getattr(devices[0], "device_kind", None) if devices
                else None) or getattr(self.tpu, "platform", "cpu")
        tuner = autotune.Autotuner(
            device_kind=str(kind), cache_file=autotune.cache_path(),
            timer=self._autotune_timer, logger=self.logger, role=self.role,
            sharding=(f"tp{self.kv_shards}"
                      if getattr(self, "kv_shards", 1) > 1 else ""))
        pallas_ok = kernel_platform()
        t0 = time.monotonic()
        n = self.num_slots

        if self.kv_layout == "paged":
            # Candidate inputs reuse the engine's own layer-0 pool planes
            # (right per-shard shape AND dtype, no second pool in HBM) with
            # a full-occupancy block table and full lengths — the worst-case
            # stream each serving decode step pays.
            maxp, page = self.pages_per_slot, self.page_size
            pool = self.total_pages
            rng = np.random.RandomState(0)
            table = jnp.asarray(
                rng.permutation(n * maxp)[: n * maxp] % max(pool, 1),
                jnp.int32).reshape(n, maxp)
            lengths = jnp.full((n,), maxp * page, jnp.int32)
            q = jnp.asarray(rng.standard_normal((n, hq, d)), qdtype)
            skey = autotune.shape_key(n, hq, hkv, d, page, maxp, pool)
            kv = self.kv_cache  # spec mode wraps the pool in (kv, hist)
            if self.kv_quantize == "int4":
                kq, vq = kv.k[0], kv.v[0]  # packed uint8, last dim d//2
                ks, vs = kv.ks[0], kv.vs[0]
                cands = {"xla": self._at_fn(
                    attn_ops.paged_decode_attention_q4, "xla",
                    q, kq, vq, ks, vs, table, lengths)}
                if pallas_ok and page % 8 == 0:
                    cands["pallas"] = self._at_fn(
                        attn_ops.paged_decode_attention_q4, "pallas",
                        q, kq, vq, ks, vs, table, lengths)
                tuner.measure("paged_decode_q4", skey, "int4", cands)
            elif self.kv_quantize:
                kq, vq = kv.k[0], kv.v[0]
                ks, vs = kv.ks[0], kv.vs[0]
                cands = {"xla": self._at_fn(
                    attn_ops.paged_decode_attention_q, "xla",
                    q, kq, vq, ks, vs, table, lengths)}
                if pallas_ok and page % 8 == 0:
                    cands["pallas"] = self._at_fn(
                        attn_ops.paged_decode_attention_q, "pallas",
                        q, kq, vq, ks, vs, table, lengths)
                tuner.measure("paged_decode_q", skey, "int8", cands)
            else:
                kp, vp = kv.k[0], kv.v[0]
                cands = {"xla": self._at_fn(
                    attn_ops.paged_decode_attention, "xla",
                    q, kp, vp, table, lengths)}
                if pallas_ok and page % 8 == 0:
                    cands["pallas"] = self._at_fn(
                        attn_ops.paged_decode_attention, "pallas",
                        q, kp, vp, table, lengths)
                tuner.measure("paged_decode", skey, str(kp.dtype), cands)
        elif not self.kv_quantize:
            # slot layout, dense cache (the int8 slot path has no kernel
            # variant to race). With spec on the cache is (kv, aux).
            kv = self.cache[0] if isinstance(self.cache, tuple) else self.cache
            kc, vc = kv.k[0], kv.v[0]
            smax = kc.shape[2]
            rng = np.random.RandomState(0)
            q = jnp.asarray(rng.standard_normal((n, hq, d)), qdtype)
            lengths = jnp.full((n,), smax, jnp.int32)
            cands = {"xla": self._at_fn(
                attn_ops.decode_attention, "xla", q, kc, vc, lengths)}
            if pallas_ok:
                # a block-ineligible Smax makes this candidate raise (the
                # explicit-pallas contract) — the tuner records the error
                # and XLA wins by disqualification
                cands["pallas"] = self._at_fn(
                    attn_ops.decode_attention, "pallas", q, kc, vc, lengths)
            tuner.measure("decode", autotune.shape_key(n, hq, hkv, d, smax),
                          str(kc.dtype), cands)

        self._autotune_pins = tuner.pins()
        self._autotune = {"elapsed_s": round(time.monotonic() - t0, 3),
                          **tuner.report()}
        autotune.set_last_report(self._autotune)
        for op, rec in tuner.decisions.items():
            # info-style gauge: 1 on the pinned (op, backend) pair, 0 on
            # the loser so a re-tune never leaves both labels asserted.
            # kv_dtype rides as a label so a kv-dtype A/B (bf16/int8/int4
            # arms pin DIFFERENT ops) stays distinguishable in one scrape.
            for b in ("pallas", "xla"):
                self.metrics.set_gauge(
                    "app_tpu_kernel_backend",
                    1.0 if b == rec["backend"] else 0.0, op=op, backend=b,
                    kv_dtype=str(rec.get("kv_dtype", "")))
            self.logger.infof(
                "autotune: %s -> %s (%s, shapes %s, %s)", op, rec["backend"],
                rec["source"], rec["shape"], rec.get("timings_ms") or "untimed")

    def _at_fn(self, op_fn, backend: str, *arrays):
        """A timed autotune candidate: the op jitted over REAL device-shaped
        array arguments (arguments, not closure constants — XLA must not
        fold the benchmark away) with the backend bound explicitly. On a
        tp-sharded pool the candidate traces under the engine's KVShardCtx
        so the timing races the per-shard program the serving traces will
        actually run — that is what the sharding-scoped cache key pins."""
        jf = jax.jit(partial(op_fn, backend=backend))
        ctx = self._kv_shard_ctx()
        if ctx is None:
            return lambda: jf(*arrays)
        from gofr_tpu.ops.paged import kv_shard_scope

        def run():
            with kv_shard_scope(ctx):
                return jf(*arrays)

        return run

    def autotune_report(self) -> dict | None:
        """The warmup autotuner's decision table (None until warmup ran or
        when autotune is disabled) — surfaced at /debug/engine and recorded
        in the bench JSON."""
        return self._autotune

    def _quality_backend(self) -> str:
        """Backend label for quality telemetry: the distinct autotune-pinned
        kernel backends serving this engine ("xla" before warmup pins)."""
        pins = self._autotune_pins
        return "+".join(sorted(set(pins.values()))) if pins else "xla"

    def spec_accept_totals(self) -> dict[str, tuple[float, float]]:
        """Lifetime per-adapter (accepted, proposed) speculative-decode
        token totals ("base" = no adapter). Raw summable numerators — the
        container divides at scrape time, federation sums across engines."""
        with self._obs_lock:
            return {k: (v[0], v[1]) for k, v in self._spec_totals.items()}

    def quality_snapshot(self) -> dict | None:
        """The /debug/quality + capture-bundle join: plane totals and recent
        divergence reports, keyed by the serving state that produced them —
        autotune pins, weights epoch, kv dtype — plus the replay config
        scripts/replay_bundle.py needs to re-execute samples offline."""
        if self._quality is None:
            return None
        snap = self._quality.snapshot()
        snap["autotune_pins"] = dict(self._autotune_pins)
        snap["weights_epoch"] = self.weights_epoch
        snap["backend"] = self._quality_backend()
        snap["replay"] = self.replay_config()
        return snap

    def replay_config(self) -> dict:
        """Everything scripts/replay_bundle.py needs to rebuild THIS engine
        offline: model family/config, sampler seed, the engine knobs that
        shape compiled programs, adapter digest, weights epoch, fingerprint,
        and the chaos spec that was armed (corruption is part of the repro)."""
        import dataclasses

        cfg = self.cfg
        cfg_d = None
        if dataclasses.is_dataclass(cfg):
            cfg_d = dataclasses.asdict(cfg)
            dt = cfg_d.get("dtype")
            if dt is not None:
                cfg_d["dtype"] = jnp.dtype(dt).name
        return {
            "family": getattr(self.family, "__name__",
                              type(self.family).__name__).rsplit(".", 1)[-1],
            "config": cfg_d,
            "seed": self._seed,
            "engine": {
                "slots": self.num_slots,
                "max_len": self.max_len,
                "decode_chunk": self.decode_chunk,
                "kv_layout": self.kv_layout,
                "page_size": self.page_size if self.kv_layout == "paged" else 0,
                "total_pages": getattr(self, "total_pages", 0),
                "spec_tokens": self.spec_tokens,
                "kv_quantize": self.kv_quantize,
                "kv_shards": getattr(self, "kv_shards", 1),
                "top_k": self.top_k,
                "top_p": self.top_p,
            },
            "weights_epoch": self.weights_epoch,
            "adapter_digest": self.adapters_digest(),
            "fingerprint": self.fleet_fingerprint(),
            # the LIVE armed spec (env or test override), not the env var:
            # an armed corruption is part of the deterministic repro
            "chaos": chaos.active_spec(),
        }

    def page_pool_stats(self) -> dict | None:
        """Paged-pool waste view for the perf plane: occupancy (allocated
        fraction of usable pages) and fragmentation (claimed page positions
        no live sequence has written yet — trailing partial pages plus
        spec over-claim not yet trimmed). None on the slot layout."""
        if self.kv_layout != "paged":
            return None
        with self._state_lock:
            free = len(self._free_pages)
            held = sum(len(p) for p in self._slot_pages)
            live = sum(s.pos for s in self.slots if s is not None)
        usable = max(1, self.total_pages - self._page_sink)
        covered = held * self.page_size
        # Byte fields are SHARD-LOCAL (per-device): on a tp-sharded pool
        # each device holds 1/kv_shards of every plane, and a fleet rollup
        # that sums parts must see parts, not the logical-global footprint
        # multiplied per engine. Occupancy/fragmentation are ratios over
        # page COUNTS (replicated bookkeeping) and are shard-invariant.
        shards = max(1, getattr(self, "kv_shards", 1))
        return {
            "total_pages": self.total_pages,
            "free_pages": free,
            "slot_pages": held,
            "kv_shards": shards,
            "page_bytes_device": getattr(self, "_page_bytes", 0) // shards,
            "pool_bytes_device": getattr(self, "_pool_bytes", 0) // shards,
            "occupancy": round(1.0 - free / usable, 4),
            "fragmentation": round(1.0 - min(1.0, live / covered), 4)
            if covered else 0.0,
        }

    def submit(
        self,
        prompt: Any,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout: float | None = None,
        **kw: Any,
    ) -> Request:
        """Non-blocking enqueue: returns the Request future (``.result()``
        blocks; ``.cancel()`` frees the slot). One caller thread can keep
        hundreds of generations in flight — the shape async transports use."""
        return self._submit(
            prompt, timeout,
            max_new_tokens=max_new_tokens, temperature=temperature, **kw,
        )

    def generate(
        self,
        prompt: Any,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        timeout: float | None = None,
        stream: bool = False,
        **kw: Any,
    ):
        """Generate a completion. ``prompt`` is a string (needs a
        tokenizer) or a sequence of token ids. Greedy when temperature=0.
        ``stream=True`` returns an iterator of tokens (strings when a
        tokenizer is attached) instead of blocking for the full result."""
        req = self._submit(
            prompt, timeout, stream=stream,
            max_new_tokens=max_new_tokens, temperature=temperature, **kw,
        )
        if stream:
            return self._stream_iter(req, timeout)
        return req.result(timeout if timeout is not None else self.default_timeout)

    def infer(self, inputs: Any, **kw: Any):
        return self.generate(inputs, **kw)

    def _stream_iter(self, req: Request, timeout: float | None) -> "_StreamIterator":
        per_token_timeout = timeout if timeout is not None else self.default_timeout

        def it():
            while True:
                try:
                    item = req.stream_q.get(timeout=per_token_timeout or 3600.0)
                except queue.Empty:
                    req.cancel()
                    raise RequestTimeout() from None
                if item is None:
                    # surface a terminal error (engine death) if any
                    if req._error is not None:
                        raise req._error
                    return
                yield item

        return _StreamIterator(req, it())

    def _announce(self, tag: int, a: int, b: int, packed) -> None:
        if self._ls is not None:
            self._ls.announce(tag, a, b, packed)

    def stop(self) -> None:
        super().stop()
        if self._handoff_exporter is not None:
            self._handoff_exporter.close()
        if self._handoff_server is not None:
            self._handoff_server.close()
        if self._ls is not None and not self._poisoned:
            # after a CLEAN device-thread join no concurrent collective can
            # interleave with the terminal broadcast. A wedged thread may
            # still be inside one — broadcasting would corrupt the stream;
            # followers must be torn down externally then (lockstep.py).
            self._ls.stop()

    def serve_follower(self) -> None:
        """Run this process as a lockstep FOLLOWER (multi-host serving,
        tpu/lockstep.py): blocks executing the leader's announced programs
        until the leader stops. Do not call start(). With
        LOCKSTEP_DEADLINE_S set, a liveness watchdog hard-exits this
        process if the leader goes silent (kill -9/OOM — lockstep.py).

        Under a fleet config (FLEET_LEADER) the announce stream rides the
        host-side channel instead of the device collective: this dials the
        leader (retrying for FLEET_CONNECT_TIMEOUT_S), replays its epochs,
        and on leader loss REDIALS for FLEET_REJOIN_S before declaring the
        leader dead — the epoch-based warm rejoin (docs/parallelism.md)."""
        if self.lockstep_role != "follower":
            raise RuntimeError("engine was not built with lockstep_role='follower'")
        from gofr_tpu.tpu.lockstep import LockstepFollower

        deadline = self.container.config.get_float("LOCKSTEP_DEADLINE_S", 0.0)
        if self._fleet is not None:
            from gofr_tpu.fleet import FleetFollowerChannel

            channel = FleetFollowerChannel(
                self._fleet.leader, fingerprint=self.fleet_fingerprint(),
                connect_timeout_s=self._fleet.connect_timeout_s,
                rejoin_timeout_s=self._fleet.rejoin_timeout_s,
                logger=self.logger)
            channel.connect()
            try:
                LockstepFollower(self, deadline_s=deadline, channel=channel).run()
            finally:
                channel.close()
            return
        LockstepFollower(self, deadline_s=deadline).run()

    # -- multi-LoRA adapters (gofr_tpu.adapters; docs/serving.md) --------------

    def register_adapter(self, spec) -> None:
        """Install (or replace) a named LoRA adapter for serving. Host-tier
        registration only — the device upload happens lazily at the first
        admission that names it (AdapterPool.acquire). Replacing an adapter
        whose pool slot is referenced by a live lane raises: weights must
        never change under an in-flight request (drain first)."""
        if not self._adapters_enabled:
            raise RuntimeError(
                "engine built without the adapter plane; set ADAPTER_SLOTS "
                "or ADAPTER_POOL_MB")
        if spec.rank > self._adapter_pool.rank:
            raise ValueError(
                f"adapter {spec.name!r} rank {spec.rank} exceeds the pool "
                f"rank {self._adapter_pool.rank} (ADAPTER_RANK)")
        with self._state_lock:
            self.adapters.register(spec, pool=self._adapter_pool)
        self.metrics.set_gauge(
            "app_tpu_adapters_registered", len(self.adapters.names()))

    def unregister_adapter(self, name: str) -> None:
        """Remove an adapter from both tiers. Raises while lanes still
        reference its pool slot (same discipline as register-replace)."""
        if not self._adapters_enabled:
            return
        with self._state_lock:
            self.adapters.unregister(name, pool=self._adapter_pool)
        self.metrics.set_gauge(
            "app_tpu_adapters_registered", len(self.adapters.names()))

    def adapter_stats(self) -> dict[str, Any]:
        """Both tiers' occupancy + the weights epoch, for /debug/engine."""
        if not self._adapters_enabled:
            return {"enabled": False, "weights_epoch": self.weights_epoch}
        with self._state_lock:
            pool = self._adapter_pool.stats()
        out = {"enabled": True, "registry": self.adapters.stats(),
               "pool": pool, "weights_epoch": self.weights_epoch}
        return out

    def adapters_digest(self) -> str:
        """Adapter-set fingerprint for the handoff JOIN gate (empty when
        the plane is disabled — pre-adapter peers send/expect nothing)."""
        return self.adapters.digest() if self._adapters_enabled else ""

    def _adapter_args(self) -> tuple:
        """The device pool triple threaded into every adapter-enabled
        program call as trailing DYNAMIC jit args (tpu/programs.py) —
        uploads and hot-swaps never recompile."""
        p = self._adapter_pool
        return (p.a, p.b, p.scale)

    def _acquire_adapter(self, req: Request):
        """Resolve ``req``'s adapter to a device pool slot at admission
        (caller holds the state lock). Returns ``(adapter_id, pool_slot)``
        when bound — base requests bind ``(None, 0)`` — the string
        ``"wait"`` when every pool slot is referenced by a live lane (the
        caller requeues, exactly like KV page exhaustion), or ``None``
        when the adapter vanished since submission (the request was failed
        here)."""
        name = req.kw.get("_adapter")
        if not name or not self._adapters_enabled:
            return (None, 0)
        try:
            spec = self.adapters.get(name)
        except KeyError as e:
            req.complete(error=ValueError(
                str(e.args[0]) if e.args else str(e)))
            return None
        aslot = self._adapter_pool.acquire(spec)
        if aslot is None:
            return "wait"
        return (name, aslot)

    # -- live weight hot-swap (zero-drop; docs/serving.md) ---------------------

    def adopt_weights(self, new_params, *, timeout_s: float | None = 30.0) -> int:
        """Adopt a full replacement weight tree with no restart and no
        dropped requests: the device loop drains the in-flight queue,
        requeues slot-resident work whole (preemption-by-recompute — a
        request either finished on the old weights or re-enters the queue
        as a fresh prefill; tokens from the two epochs never mix inside
        one decode step), resets per-epoch device state (the prefix cache
        and KV pages carry old-weight K/V), swaps ``params`` and bumps
        ``weights_epoch`` — which feeds fleet.epoch_of, so router gossip
        sees a strict epoch bump. Returns the new epoch. Blocks up to
        ``timeout_s`` for the adoption (None = stage and return)."""
        if self.lockstep_role:
            raise RuntimeError(
                "live weight hot-swap is not supported under lockstep "
                "(weight adoption cannot be announced to followers)")
        new_params = self._match_weights(new_params)
        done = threading.Event()
        with self._swap_lock:
            self._pending_weights = (new_params, done)
        if self._thread is None or not self._thread.is_alive():
            # not serving yet (tests, pre-start swap): adopt inline
            self._apply_pending_weights()
            return self.weights_epoch
        if timeout_s is not None and not done.wait(timeout_s):
            raise TimeoutError(
                f"weight hot-swap not adopted within {timeout_s:.1f}s")
        return self.weights_epoch

    def adopt_checkpoint(self, directory: str, *,
                         timeout_s: float | None = 30.0) -> int:
        """Adopt the latest orbax checkpoint under ``directory``
        (train/checkpoint.py layout) as the serving weights — the scripted
        train→serve hot-swap path. The raw tree is resolved through the
        same post-processing the ctor weights got (mesh sharding, weight
        quantization when the serving tree is quantized)."""
        from gofr_tpu.train.checkpoint import load_params

        like = jax.eval_shape(
            lambda: self.family.init(self.cfg, jax.random.key(0)))
        raw = load_params(directory, like)
        return self.adopt_weights(self._prepare_weights(raw),
                                  timeout_s=timeout_s)

    def _match_weights(self, new_params):
        """Validate a replacement tree against the serving tree: identical
        structure, shapes, and dtypes — anything else would recompile
        every program (or garble decode) mid-serving. A draft-spec engine
        may pass just the target tree; the live draft is grafted in."""
        if (self._draft is not None and isinstance(self.params, dict)
                and not (isinstance(new_params, dict) and "t" in new_params)):
            new_params = {"t": new_params, "d": self.params["d"]}
        if jax.tree.structure(new_params) != jax.tree.structure(self.params):
            raise ValueError(
                "adopt_weights: replacement tree structure does not match "
                "the serving tree (same family/config/quantization required)")
        for new, old in zip(jax.tree.leaves(new_params),
                            jax.tree.leaves(self.params)):
            if (tuple(new.shape) != tuple(old.shape)
                    or jnp.asarray(new).dtype != jnp.asarray(old).dtype):
                raise ValueError(
                    f"adopt_weights: leaf {tuple(new.shape)}/{new.dtype} != "
                    f"serving {tuple(old.shape)}/{old.dtype}")
        return new_params

    def _prepare_weights(self, raw):
        """Run a raw (checkpoint) tree through the ctor weights' post-
        processing: shard over the mesh by the family's logical axes, then
        weight-only quantization when the serving tree is quantized."""
        rules = getattr(self.tpu, "rules", None)
        mesh = getattr(self.tpu, "mesh", None)
        if rules is not None:
            raw = shard_pytree(raw, self.family.param_axes(self.cfg),
                               rules, mesh)
        target = (self.params["t"] if self._draft is not None
                  else self.params)
        if jax.tree.structure(raw) != jax.tree.structure(target):
            from gofr_tpu.ops.quant import quantize_tree

            raw = jax.jit(quantize_tree)(raw)
        return raw

    def _apply_pending_weights(self) -> bool:
        """Device-loop half of the hot-swap (also run inline pre-start):
        the zero-drop drain. Mirrors ``_fleet_admit``'s epoch bump — fold
        every in-flight device call, requeue slot-resident work whole via
        preemption-by-recompute, reset per-epoch device state OUTSIDE the
        lock, then swap the tree and bump the epoch."""
        with self._swap_lock:
            pending, self._pending_weights = self._pending_weights, None
        if pending is None:
            return False
        new_params, done = pending
        while self._dq:
            process_decode(self)
        with self._state_lock:
            while self._preempt_newest():
                pass
        # outside the lock — _reset_device_state blocks on still-executing
        # device work first (_drain_device_state), and that wait must never
        # run under _state_lock (the _fleet_admit discipline)
        self._reset_device_state()
        self.params = new_params
        self.weights_epoch += 1
        self.metrics.set_gauge("app_tpu_weights_epoch", self.weights_epoch)
        self.metrics.increment_counter("app_tpu_weight_swaps_total", 1)
        self.logger.warn(
            f"live weight hot-swap adopted (weights epoch "
            f"{self.weights_epoch}); slot-resident work requeued")
        done.set()
        return True

    def _scan_hotswap_steps(self) -> int | None:
        """Newest checkpoint step under ADAPTER_HOTSWAP_DIR, by a light
        directory scan — orbax step dirs are bare integers and appear
        atomically (saves land in a tmp dir and rename), so this never
        opens a CheckpointManager on the device thread's poll path."""
        try:
            steps = [int(d) for d in os.listdir(self._hotswap_dir)
                     if d.isdigit()]
        except OSError:
            return None
        return max(steps) if steps else None

    def _poll_hotswap(self) -> None:
        """Device-loop tick: adopt any checkpoint step newer than the last
        one seen (throttled to ADAPTER_HOTSWAP_POLL_S)."""
        now = time.monotonic()
        if now - self._hotswap_last < self._hotswap_poll_s:
            return
        self._hotswap_last = now
        step = self._scan_hotswap_steps()
        if step is None or (self._hotswap_seen is not None
                            and step <= self._hotswap_seen):
            return
        self._hotswap_seen = step
        try:
            from gofr_tpu.train.checkpoint import load_params

            like = jax.eval_shape(
                lambda: self.family.init(self.cfg, jax.random.key(0)))
            raw = load_params(self._hotswap_dir, like)
            with self._swap_lock:
                self._pending_weights = (
                    self._match_weights(self._prepare_weights(raw)),
                    threading.Event())
            self._apply_pending_weights()
        except Exception as e:  # noqa: BLE001 - a bad checkpoint must not kill serving
            self.logger.log_exception(e, "hot-swap checkpoint adoption")

    # -- device loop -----------------------------------------------------------

    def _encode_prompt(self, prompt: Any) -> np.ndarray:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompt but engine has no tokenizer; pass token ids")
            return np.asarray(self.tokenizer.encode(prompt), np.int32)
        return np.asarray(prompt, np.int32)

    def _fail_all(self, error: Exception) -> None:
        """Slot-resident requests must fail too — without this, a caller of a
        request already admitted into a slot would block forever when the
        engine stops with a wedged device thread."""
        super()._fail_all(error)
        with self._state_lock:
            for i, s in enumerate(self.slots):
                if s is not None:
                    self._free_slot(i)
                    s.request.complete(error=error)

    def _crash_recover(self, error: Exception) -> None:
        """Slot-resident requests rode the crashed device state — fail them
        and reset slot/page bookkeeping; queued/pending prompts survive and
        re-plan after the restart."""
        super()._crash_recover(error)
        with self._state_lock:
            for i, s in enumerate(self.slots):
                if s is not None:
                    self._free_slot(i)
                    s.request.complete(error=error)
        # The crashed call may have DONATED the cache buffer before
        # dying — self.cache can reference a deleted array, and every
        # post-restart step would fail on it, burning the whole restart
        # budget on one fault. Rebuild it (all slots are empty now);
        # _reset_device_state first SETTLES still-executing dispatches, so
        # the rebuild cannot reuse memory a stale program is writing into.
        self._reset_device_state()

    def _drain_device_state(self) -> None:
        """Settle every possibly-still-executing device computation of the
        dying epoch BEFORE its buffers are dropped. A host-side crash (or
        an epoch bump) can leave dispatched calls running: rebinding
        ``self.cache``/clearing ``_dq`` frees their output buffers, and the
        allocator may hand that memory to the NEXT epoch's fresh cache
        while the stale program is still writing into it — scribbling the
        new state (observed as deterministic-under-load token corruption in
        the fleet chaos drill). Blocking here bounds recovery by the last
        step's runtime. A crashed program raising out of the wait is
        expected — its buffers are settled either way. NEVER call this
        holding the state lock: a truly wedged program would then deadlock
        ``stop()``'s ``_fail_all`` behind the lock forever (the wedged path
        must stay poison-and-abandon, lockstep.py semantics)."""
        for entry in list(self._dq):
            try:
                jax.block_until_ready(entry[1])
            except Exception:  # noqa: BLE001 - crashed call: settled anyway
                pass
        self._dq.clear()
        for ref in (self.cache, self._prev_last, self._spec_carry):
            if ref is not None:
                try:
                    jax.block_until_ready(ref)
                except Exception:  # noqa: BLE001
                    pass

    def _place_cache(self, cache):
        """Cache placement shared by the ctor and every rebuild site: under
        lockstep the (process-local) cache must be placed as a GLOBAL array
        on the engine's mesh, or the first rebuilt-cache program would
        re-place it differently from the other processes. A tp-sharded pool
        keeps its plane sharding (head axis split, everything else — spec
        history — replicated); unsharded engines place replicated as
        before."""
        if not self.lockstep_role:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as _P

        if getattr(self, "kv_shards", 1) > 1:
            from gofr_tpu.ops.paged import plane_partition_spec

            def place(leaf):
                spec = plane_partition_spec(leaf.ndim) if leaf.ndim >= 4 else _P()
                return jax.device_put(leaf, NamedSharding(self.tpu.mesh, spec))

            return jax.tree.map(place, cache)
        return jax.device_put(cache, NamedSharding(self.tpu.mesh, _P()))

    def _reset_device_state(self) -> None:
        """Reset every piece of per-epoch device state to its virgin value:
        fresh cache (the crashed call may have donated the old buffer; a
        fleet epoch bump needs leader and followers on identical state),
        empty page pool/tables, no decode or spec carries. Slots must
        already be empty (failed by _crash_recover or requeued by
        _fleet_admit); weights and compiled programs are untouched — this
        is the warm part of warm-rejoin. Safe on followers (their slot
        bookkeeping is never populated) and re-entrant under the state
        lock."""
        self._drain_device_state()  # before the lock — see its docstring
        with self._state_lock:
            if self.kv_layout == "paged":
                self.cache = self._place_cache(self._build_paged_cache())
                self._free_pages = list(range(self._page_sink, self.total_pages))
                self._slot_pages = [[] for _ in range(self.num_slots)]
                self._table = np.full(
                    (self.num_slots, self.pages_per_slot), self.total_pages, np.int32
                )
                self._page_refs[:] = 0
                self._pending_swapins = []
                self._pending_spills = []
                if self._prefix is not None:
                    # cached pages (both tiers) rode the dead epoch's device
                    # state; the gauges must say so (a stale cached_pages /
                    # host_pages reading after a reset would misreport
                    # capacity until the next eviction touched them)
                    self._prefix.clear()
                    self._set_prefix_gauges()
            else:
                self.cache = self._place_cache(self._build_slot_cache())
            self._prev_last = None
            self._spec_carry = None  # rode the same dead-epoch device state

    def fleet_fingerprint(self) -> str:
        """Engine-config fingerprint for the fleet handshake: two processes
        form a fleet only when everything that determines the compiled
        programs and the replayed state transitions is identical
        (fleet/channel.py rejects mismatches at the door)."""
        from gofr_tpu.fleet import fingerprint_of

        return fingerprint_of(
            getattr(self.family, "__name__", type(self.family).__name__),
            self.cfg, self._seed, self.num_slots, self.max_len,
            self.decode_chunk, self.prefill_buckets, self.max_prefill_batch,
            self.kv_layout, self.page_size if self.kv_layout == "paged" else 0,
            getattr(self, "total_pages", 0), self.spec_tokens,
            self.kv_quantize, self.top_k, self.top_p,
            getattr(self, "kv_shards", 1),
        )

    def _fleet_admit(self) -> bool:
        """Step-boundary membership change (device thread, loop top): when
        followers are parked in the channel's pending set — fresh joins,
        rejoins after a leader or follower death — bump the fleet epoch and
        bring EVERYONE onto identical virgin per-epoch state. Slot-resident
        work is REQUEUED by recompute (the preemption machinery), not
        failed: the leader's device state is healthy here, so nothing is
        lost — requests re-prefill under the new epoch and their replay is
        announced to the whole (new) fleet."""
        ls = self._ls
        if ls is None or not ls.has_pending():
            return False
        # drain in-flight device work first: queued folds reference the
        # pre-bump cache and slot objects
        while self._dq:
            process_decode(self)
        with self._state_lock:
            while self._preempt_newest():
                pass
        # outside the lock: _reset_device_state blocks on still-executing
        # device work first (_drain_device_state), and that wait must never
        # run under _state_lock — a wedged program would deadlock stop()'s
        # _fail_all behind the lock. Slots cannot repopulate in the gap:
        # admission runs on this (device) thread only.
        self._reset_device_state()
        n = ls.admit_pending()
        self.metrics.set_gauge("app_fleet_epoch", ls.epoch)
        self.metrics.set_gauge("app_fleet_followers", ls.follower_count())
        self.metrics.increment_counter("app_fleet_rejoins_total", n)
        self.logger.warn(
            f"fleet: admitted {n} follower(s) at epoch {ls.epoch} "
            f"({ls.follower_count()} active); slot-resident work requeued"
        )
        return True

    # -- scale-in drain (fleet/autoscaler.py; docs/resilience.md) --------------

    def begin_drain(self) -> None:
        """Flip the replica into draining: _submit sheds new arrivals with a
        retryable 503 and _admit_prefill stops claiming slots for queued
        work. In-flight slot work is untouched — streams keep streaming."""
        self._draining = True
        self.metrics.set_gauge("app_tpu_draining", 1)

    def abort_drain(self) -> None:
        """Drain abort (autoscaler re-admit after death-mid-drain chaos or a
        failed scale-in): back to serving — admission resumes on the very
        next loop iteration; nothing was torn down."""
        self._draining = False
        self.metrics.set_gauge("app_tpu_draining", 0)

    def drain_queued(self) -> list[Request]:
        """Pull every queued-but-unadmitted request off this replica for
        requeue onto a peer (fleet.autoscaler.requeue). Must run AFTER
        begin_drain: _admit_prefill holds the state lock across its whole
        queue→pending→slot move and returns early while draining, so under
        the same lock nothing can be half-moved here."""
        out: list[Request] = []
        with self._state_lock:
            while True:
                try:
                    out.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            out.extend(r for r, _ in self._pending)
            self._pending = []
            out.extend(r for r, _ in self._pending_long)
            self._pending_long = []
        self.metrics.set_gauge("app_tpu_queue_depth", self._backlog())
        return out

    def drained(self) -> bool:
        """True once every slot is empty and no device work is in flight —
        the point where retiring the process drops zero streams."""
        with self._state_lock:
            return all(s is None for s in self.slots) and not self._dq

    def drain(self, *, timeout_s: float = 30.0) -> list[Request]:
        """The scale-in drain entrypoint: stop admitting, hand back queued
        work for peer requeue, and wait for in-flight streams to finish.
        Past ``timeout_s`` the stragglers are cooperatively cancelled (the
        PR10 lifetime plane frees their slots and KV pages) with a bounded
        grace for the reclaim. Returns the requests the caller must requeue;
        the chaos point ``replica.drain`` fires after the flag flips, so an
        injected fault leaves the engine draining — exactly the state a
        replica that died mid-drain is in — for the autoscaler's
        abort→re-admit path to undo."""
        self.begin_drain()
        chaos.fire("replica.drain")
        pending = self.drain_queued()
        deadline = time.monotonic() + max(0.0, timeout_s)
        cancelled = False
        while not self.drained():
            if time.monotonic() >= deadline:
                if cancelled:
                    break
                with self._state_lock:
                    for s in self.slots:
                        if s is not None:
                            s.request.cancel("drain_timeout")
                cancelled = True
                deadline = time.monotonic() + 5.0  # reclaim grace
            time.sleep(0.01)
        return pending

    # -- slot/page bookkeeping -------------------------------------------------

    def _build_slot_cache(self):
        """One construction site for ctor AND crash-restart rebuild. With
        speculative decoding on, the cache is a 2-tuple pytree: (kv, hist)
        for prompt-lookup — the device-resident token history the
        prefill/spec programs maintain (tpu/programs.py), so the host never
        ships history — or (kv, draft_kv) with a draft model."""
        kv = (self.family.make_cache_q(self.cfg, self.num_slots, self._cache_len)
              if self.kv_quantize
              else self.family.make_cache(self.cfg, self.num_slots, self._cache_len))
        if self._draft is not None:
            dfam, dcfg = self._draft
            return (kv, dfam.make_cache(dcfg, self.num_slots, self._cache_len))
        if self.spec_tokens:
            return (kv, jnp.zeros((self.num_slots, self._cache_len), jnp.int32))
        return kv

    @property
    def kv_cache(self):
        """The KV pool alone, regardless of whether the live cache is the
        bare pool or the (kv, hist) 2-tuple spec decoding wraps around it.
        Page-granular plumbing (page-byte accounting, gather_page eviction
        and handoff export, swap-in protos) targets the pool only — the
        history plane is slot-indexed, not page-indexed."""
        return self.cache[0] if isinstance(self.cache, tuple) else self.cache

    def _paged_make_fn(self):
        if self.kv_quantize == "int4":
            return self.family.make_paged_cache_q4
        if self.kv_quantize:
            return self.family.make_paged_cache_q
        return self.family.make_paged_cache

    def _resolve_kv_shard(self, kv_shard: str):
        """(shards, pool NamedSharding) for ENGINE_KV_SHARD: 'off'/'0' → 1
        (unsharded, today's placement bit-for-bit); 'auto' → the mesh's tp
        size when the geometry can split (tp > 1, head counts divide, the
        family's cache constructor takes a sharding); explicit 'tp' raises
        when it can't — an operator who asked for sharding must not get a
        silently replicated pool."""
        mode = str(kv_shard or "auto").strip().lower()
        if mode in ("", "0", "off", "none", "no"):
            return 1, None
        if mode not in ("auto", "1", "tp"):
            raise ValueError(
                f"unknown ENGINE_KV_SHARD {kv_shard!r}; use 'auto', 'tp' or 'off'")
        import inspect

        axis = "tp"
        mesh = getattr(self.tpu, "mesh", None)
        tp = 0
        if mesh is not None and axis in getattr(mesh, "axis_names", ()):
            tp = int(mesh.shape[axis])
        hkv = int(getattr(self.cfg, "num_kv_heads", 0) or 0)
        hq = int(getattr(self.cfg, "num_heads", 0) or 0)
        try:
            supports = "sharding" in inspect.signature(self._paged_make_fn()).parameters
        except (TypeError, ValueError):
            supports = False
        why = None
        if tp <= 1:
            why = "mesh has no tp axis with more than one device"
        elif not supports:
            why = "family cache constructor takes no sharding"
        elif hkv <= 0 or hkv % tp or hq <= 0 or hq % tp:
            why = (f"head counts (num_heads={hq}, num_kv_heads={hkv}) do not "
                   f"divide by tp={tp}")
        if why is not None:
            if mode == "tp":
                raise ValueError(f"ENGINE_KV_SHARD=tp impossible: {why}")
            return 1, None
        from gofr_tpu.ops.paged import pool_sharding

        return tp, pool_sharding(mesh, axis)

    def _kv_shard_ctx(self):
        """The paged.KVShardCtx this engine pins for its traces, or None."""
        if getattr(self, "kv_shards", 1) <= 1:
            return None
        from gofr_tpu.ops.paged import KVShardCtx

        return KVShardCtx(self.tpu.mesh, "tp", self.kv_shards)

    def _build_paged_cache(self):
        """One construction site for ctor AND crash-restart rebuild: the
        two must always agree on the cache kind (int4 vs int8 vs dense).
        With speculative decoding on, the paged cache is the same 2-tuple
        pytree the slot layout uses — (kv, hist), hist [num_slots, Hcap]
        int32 with Hcap = pages_per_slot * page_size — so the device keeps
        the prompt-lookup history and spec rounds ride the pipeline without
        the host shipping history rows every dispatch (tpu/programs.py).
        A sharded pool is allocated DIRECTLY under its NamedSharding (no
        replicated transient); the hist plane is slot-indexed, not
        head-indexed, so it stays replicated on the same mesh."""
        make = self._paged_make_fn()
        if self._kv_pool_sharding is not None:
            kv = make(self.cfg, self.total_pages, self.page_size,
                      sharding=self._kv_pool_sharding)
        else:
            kv = make(self.cfg, self.total_pages, self.page_size)
        if self.spec_tokens:
            hcap = self.pages_per_slot * self.page_size
            hist = jnp.zeros((self.num_slots, hcap), jnp.int32)
            if self._kv_pool_sharding is not None:
                from jax.sharding import NamedSharding, PartitionSpec as _P

                hist = jax.device_put(hist, NamedSharding(self.tpu.mesh, _P()))
            return (kv, hist)
        return kv

    def _ref_page(self, p: int) -> None:
        self._page_refs[p] += 1

    def _unref_page(self, p: int) -> None:
        self._page_refs[p] -= 1
        if self._page_refs[p] == 0:
            self._free_pages.append(p)

    # staging buffers per (kind, shape) rotate through a ring this long
    # before reuse. One shared buffer is NOT safe: the host→device fetch of
    # a dispatched call's packed input is asynchronous PER DEVICE REPLICA
    # (jnp.asarray does not copy for every device before dispatch returns),
    # so rewriting the buffer for the next same-kind dispatch can corrupt
    # what a lagging replica reads — divergent per-device KV writes, then
    # garbage collectives (found by the fleet chaos drill: deterministic
    # wrong tokens after a crash-restart under load). A device cannot lag a
    # full ring behind the newest dispatch: every program carries a
    # collective, so all replicas advance together within the bounded
    # in-flight window (pipeline depth ≤ 4, plus abandoned crash-path
    # dispatches) — 8 is comfortably past both.
    STAGING_RING = 8

    def _staging(self, kind: str, shape: tuple[int, ...]) -> np.ndarray:
        """A zeroed int32 staging buffer for one packed dispatch, drawn
        from a per-(kind, shape) ring so allocation is amortized without
        ever rewriting a buffer a still-fetching replica may read.
        Device-thread only."""
        key = (kind, shape)
        ring = self._staging_bufs.get(key)
        if ring is None:
            ring = ([np.zeros(shape, np.int32) for _ in range(self.STAGING_RING)], [0])
            self._staging_bufs[key] = ring
        bufs, idx = ring
        buf = bufs[idx[0]]
        idx[0] = (idx[0] + 1) % len(bufs)
        buf.fill(0)
        return buf

    def _claim_slot(self, idx: int, slot: _Slot) -> None:
        """Occupy lane ``idx`` (caller holds the state lock). The lane is
        reserved from this moment — admission skips it, decode masks it,
        and its pages stay held — until _free_slot or the prefill fold
        moves it to the decode stage."""
        self.slots[idx] = slot
        self._free_lanes.discard(idx)
        if slot.last_token is None:
            self._prefill_lanes.add(idx)
        else:
            self._decode_lanes.add(idx)

    def _lane_to_decode(self, idx: int) -> None:
        """Prefill fold completed: the lane starts decoding next dispatch."""
        self._prefill_lanes.discard(idx)
        self._decode_lanes.add(idx)

    def _free_slot(self, idx: int) -> None:
        """Vacate a slot; in the paged layout its share of each page is
        released (pages also held by the prefix cache or other slots stay
        allocated — refcount zero is what returns a page to the pool).
        The slot's adapter pool reference drops with it."""
        s = self.slots[idx]
        self.slots[idx] = None
        self._decode_lanes.discard(idx)
        self._prefill_lanes.discard(idx)
        self._free_lanes.add(idx)
        if self.kv_layout == "paged":
            pages = self._slot_pages[idx]
            if pages:
                self._slot_pages[idx] = []
                self._table[idx, :] = self.total_pages
                for p in pages:
                    self._unref_page(p)
            self.metrics.set_gauge("app_tpu_kv_pages_free", len(self._free_pages))
        if s is not None and s.adapter_slot and self._adapter_pool is not None:
            self._adapter_pool.release(s.adapter_slot)
        if s is not None and s.handoff is not None:
            # a mid-prefill streaming transfer whose slot died (preemption,
            # cancel, deadline): tear down the WIRE state only — the
            # request itself is settled by whoever freed the slot, and a
            # preempted prompt re-prefills and re-streams from page 0
            # (the importer touch-skips pages it already holds)
            t, s.handoff = s.handoff, None
            self._handoff_exporter.abort(t)

    def _set_prefix_gauges(self) -> None:
        """One authoritative write of every prefix-cache occupancy gauge —
        eviction, insertion, swap-in, clear(), and crash-restart all funnel
        here so no path can leave a stale reading behind."""
        if self._prefix is None:
            return
        self.metrics.set_gauge("app_tpu_prefix_cached_pages", len(self._prefix))
        self.metrics.set_gauge("app_tpu_prefix_host_pages", self._prefix.host_pages)
        self.metrics.set_gauge("app_tpu_prefix_host_bytes", self._prefix.host_bytes)

    def _evict_prefix_page(self) -> bool:
        """Release LRU prefix-cache leaves until a page actually lands in
        the free pool (an evicted page still shared with a live slot frees
        nothing — keep going). With the host tier enabled the page's K/V is
        spilled instead of dropped: the per-page gather is DISPATCHED here
        (asynchronous — no device round trip ever blocks under the state
        lock, or a wedged device call would deadlock stop()'s _fail_all
        behind it) and the node temporarily holds the small gathered device
        buffers; _materialize_spills completes the device→host read outside
        the lock on the next loop iteration. False when the cache has
        nothing left."""
        if self._prefix is None:
            return False
        freed = False
        while not self._free_pages:
            if self._prefix.host_budget:
                ent = self._prefix.spill_lru()
                if ent is None:
                    break
                key, p = ent
                from gofr_tpu.ops.paged import gather_page

                payload = tuple(
                    jax.tree.leaves(gather_page(self.kv_cache, jnp.int32(p)))
                )
                dropped = self._prefix.commit_spill(key, payload, self._page_bytes)
                self._pending_spills.append((key, payload))
                if dropped:
                    self.metrics.increment_counter(
                        "app_tpu_prefix_evicted_pages_total", dropped, tier="host")
            else:
                p = self._prefix.evict_lru()
                if p is None:
                    break
            self.metrics.increment_counter(
                "app_tpu_prefix_evicted_pages_total", 1, tier="hbm")
            self._unref_page(p)
            freed = True
        if freed:
            self._set_prefix_gauges()
        return bool(self._free_pages)

    def _ensure_pages(self, slot_idx: int, upto_pos: int) -> bool:
        """Grow slot_idx's block table until it covers logical position
        ``upto_pos``; False when the pool is exhausted. Failure rolls back
        the pages allocated by THIS call: a partial allocation on a slot
        that stays unoccupied (the admission path) would be invisible to
        preemption and strand pool capacity forever (ADVICE.md round 2)."""
        need = upto_pos // self.page_size + 1
        cur = self._slot_pages[slot_idx]
        added = 0
        while len(cur) < need:
            if not self._free_pages and not self._evict_prefix_page():
                for _ in range(added):
                    p = cur.pop()
                    self._table[slot_idx, len(cur)] = self.total_pages
                    self._unref_page(p)
                return False
            p = self._free_pages.pop()
            self._page_refs[p] = 1
            self._table[slot_idx, len(cur)] = p
            cur.append(p)
            added += 1
        return True

    def _usable_hit(self, toks: np.ndarray) -> list:
        """``(key, node)`` chain entries (tpu/prefix.py, both tiers)
        covering a prefix of ``toks``, capped below the prompt length so
        the final prompt token's logits — and therefore the first sampled
        token — are always recomputed. The single source of truth for both
        admission routing and slot claim. Touches cache LRU clocks; takes
        no references. Deliberately NOT the lookup/miss counting point:
        admission planning may re-run for a request bounced by pool
        exhaustion, and per-round counting would drown the hit-rate ratio
        in retry noise — counting happens once per claim/admission
        (_prefix_hit and the batched-path admission loop)."""
        if self._prefix is None:
            return []
        chain = self._prefix.lookup_tiered(toks)
        n_hit = min(len(chain), (int(toks.shape[0]) - 1) // self.page_size)
        return chain[:n_hit]

    def _prefix_hit(self, idx: int, slot: _Slot, toks: np.ndarray,
                    chain: list | None = None) -> None:
        """Splice the longest cached full-page prefix of ``toks`` into a
        freshly claimed slot's block table (caller holds the state lock;
        the slot owns no pages yet); chunked prefill then starts at
        ``slot.written``. Device-resident chain nodes splice directly;
        host-resident nodes claim a FREE device page each (stopping the
        chain where none is available — table rows must stay contiguous),
        are promoted back to the device tier, and their payload upload is
        staged on ``_pending_swapins`` — ``_admit`` dispatches it onto the
        unified in-flight queue right after releasing the lock, before any
        chunk of this prompt's tail can dispatch, so the cache data
        dependency orders the upload ahead of every read of those pages."""
        if self._prefix is None:
            return
        # lookup/miss accounting at CLAIM time, once per request — never in
        # _usable_hit, whose planning caller can re-run for a pool-bounced
        # request (hit rate = 1 - miss_total / lookup_total)
        self.metrics.increment_counter("app_tpu_prefix_lookup_total", 1)
        if chain is None:
            chain = self._usable_hit(toks)
        if not chain:
            self.metrics.increment_counter("app_tpu_prefix_miss_total", 1)
            return
        pages: list[int] = []
        swap_keys: list[int] = []
        swap_pids: list[int] = []
        swap_payloads: list = []
        hbm_toks = host_toks = 0
        for key, node in chain:
            if node.page_id >= 0:
                p = node.page_id
                self._ref_page(p)
                hbm_toks += self.page_size
            else:
                if not self._free_pages:
                    break  # no device page for the swap-in: tail recomputes
                p = self._free_pages.pop()
                # two shares at once: this slot's and the cache's (the node
                # is promoted below — never double-freed across tiers)
                self._page_refs[p] = 2
                swap_keys.append(key)
                swap_pids.append(p)
                swap_payloads.append(node.host)
                self._prefix.promote(key, p)
                host_toks += self.page_size
            pages.append(p)
        if not pages:
            # a chain whose first node is host-resident with no free device
            # page serves NOTHING from cache — that is a miss for hit-rate
            # purposes, or pool-pressure episodes would over-report hits
            self.metrics.increment_counter("app_tpu_prefix_miss_total", 1)
            return
        self._slot_pages[idx] = list(pages)
        self._table[idx, :len(pages)] = pages
        slot.written = len(pages) * self.page_size
        slot.dispatched = slot.written  # cached tokens need no prefill write
        if hbm_toks:
            self.metrics.increment_counter(
                "app_tpu_prefix_hit_tokens", hbm_toks, tier="hbm")
        if host_toks:
            self.metrics.increment_counter(
                "app_tpu_prefix_hit_tokens", host_toks, tier="host")
        slot.request.kw["_prefix"] = {
            "hbm_tokens": hbm_toks, "host_tokens": host_toks,
            "swapin_pages": len(swap_pids),
        }
        if swap_pids:
            self._pending_swapins.append(
                (idx, slot, swap_keys, swap_pids, swap_payloads))
            self._set_prefix_gauges()  # host bytes shrank at promotion

    def _prefix_insert(self, idx: int) -> None:
        """Retain the full prompt pages of a slot whose prefill just
        completed (caller holds the state lock). The cache takes one pool
        reference per newly registered page; pages already cached at their
        chain position are skipped — identical tokens produce identical
        K/V, so the existing page serves both chains."""
        s = self.slots[idx]
        if self._prefix is None or s is None:
            return
        n_full = s.prompt_len // self.page_size
        if n_full == 0:
            return
        new = self._prefix.insert(
            np.asarray(s.prompt_tokens), self._slot_pages[idx][:n_full]
        )
        for p in new:
            self._ref_page(p)
        if new:
            self._set_prefix_gauges()

    def _alloc_lane_pages(self, i: int, s: "_Slot", upto_pos: int) -> None:
        """Grow lane i's block table to cover ``upto_pos``, preempting the
        newest-admitted OTHER slot under pool pressure (LIFO, recompute on
        return). Caller holds the state lock and must re-check lane
        identity afterwards — preemption may have evicted lanes, including
        this one via another lane's pressure."""
        if self.slots[i] is not s:
            return  # evicted by an earlier lane's pool pressure
        while not self._ensure_pages(i, upto_pos):
            if not self._preempt_newest(except_slot=i):
                # alone and still short — can't happen when
                # total_pages >= pages_per_slot (ctor guard)
                self._free_slot(i)
                s.request.complete(error=RuntimeError(
                    "KV page pool exhausted for a single request"))
                break

    def _trim_lane_pages(self, i: int, s: "_Slot", keep_pos: int) -> int:
        """Release lane i's TRAILING pages beyond the page holding logical
        position ``keep_pos`` (caller holds the state lock). Only valid
        with no round in flight for the lane — an in-flight dispatch's
        table snapshot may write any page claimed at its dispatch time.
        This is the fold-side release of the over-claim
        ``decode.dispatch_spec_paged`` makes for the worst-case accepted
        span; rejected drafts' surplus pages return to the pool here.
        Pages also held by the prefix cache or other slots stay allocated
        (refcount discipline). Returns the number of shares released."""
        keep = keep_pos // self.page_size + 1
        cur = self._slot_pages[i]
        released = 0
        while len(cur) > keep:
            p = cur.pop()
            self._table[i, len(cur)] = self.total_pages
            self._unref_page(p)
            released += 1
        if released:
            self.metrics.set_gauge(
                "app_tpu_kv_pages_free", len(self._free_pages))
        return released

    def _masked_table(self, live: set) -> np.ndarray:
        """Block-table snapshot with NON-decoding rows forced all-OOB: a
        chunk-prefilling slot owns real pages, and a uniform decode write
        would corrupt its position 0 otherwise; empty slots are already
        all-OOB via _free_slot. Caller holds the state lock."""
        snapshot = self._table.copy()
        for i in range(self.num_slots):
            if i not in live:
                snapshot[i, :] = self.total_pages
        return snapshot

    def _preempt_newest(self, except_slot: int | None = None) -> bool:
        """Pool pressure valve: evict the MOST RECENTLY admitted active slot
        (LIFO keeps almost-done requests running), fold its generated tokens
        into its prompt, and requeue it for re-prefill — preemption by
        recompute. Greedy decode continues bit-identically; sampled decode
        resumes from a fresh RNG fold (documented engine semantics)."""
        candidates = [
            (self.slots[i].admit_seq, i)
            for i in self._decode_lanes | self._prefill_lanes
            if i != except_slot
        ]
        if not candidates:
            return False
        _, idx = max(candidates)
        s = self.slots[idx]
        self._free_slot(idx)
        req = s.request
        req.kw["_preemptions"] = req.kw.get("_preemptions", 0) + 1
        rt = req.kw.get("_rt")
        if rt is not None:
            # whichever phase the slot was in ends here (a slot still mid-
            # chunked-prefill has no decode span yet; end() no-ops on the
            # other); re-admission opens a fresh engine.prefill span, so the
            # trace shows the recompute round-trip
            rt.end("engine.prefill", preempted=True)
            rt.end("engine.decode", preempted=True)
        req.kw["_prior_tokens"] = list(req.kw.get("_prior_tokens", [])) + list(s.generated)
        req.kw["max_new_tokens"] = max(
            1, int(req.kw.get("max_new_tokens", 64)) - len(s.generated)
        )
        new_prompt = np.concatenate(
            [np.asarray(s.prompt_tokens, np.int32), np.asarray(s.generated, np.int32)]
        ).astype(np.int32)
        if new_prompt.shape[0] > self.prefill_buckets[-1]:
            # the regrown prompt outgrew the bucket ladder: it re-enters
            # through the chunked-prefill path rather than being expired
            # (ADVICE.md round 2 medium)
            self._pending_long.append((req, new_prompt))
        else:
            self._pending.append((req, new_prompt))
        self.metrics.increment_counter("app_tpu_preemptions", 1)
        return True

    # The accessors sort for determinism (lowest-lane-first claiming, and
    # lockstep leaders must pack lanes identically run-to-run); membership
    # itself is maintained incrementally, never by rescanning self.slots.

    def _free_slots(self) -> list[int]:
        return sorted(self._free_lanes)

    def _active(self) -> list[int]:
        """Slots in the decode stage (prefill-stage slots excluded)."""
        return sorted(self._decode_lanes)

    def _activate_lane(self, idx: int, s: _Slot, tok: int, now: float) -> None:
        """Shared tail of both prefill folds: give the slot its sampled
        first token and move it into the decode stage (caller holds the
        state lock and has already verified slot identity/liveness)."""
        self._mark_first_token(s.request)
        s.written = s.prompt_len
        s.generated = [tok]
        s.last_token = tok
        s.pos = s.prompt_len
        s.first_token_at = now
        self._lane_to_decode(idx)
        self._prefix_insert(idx)
        if self.role == "prefill" and self._export_handoff(idx, s, tok, now):
            return
        self._emit(s, tok)
        self._maybe_finish(idx)

    def _stream_handoff_chunk(self, idx: int, s: _Slot) -> None:
        """Streaming handoff, mid-prefill half (caller holds the state
        lock, the slot just folded a NON-final chunk): stage every newly
        full page's gather on the slot's StreamTransfer and kick the
        exporter thread. The gathers are dispatched HERE, under the lock,
        so they capture the page contents before preemption or eviction
        could recycle a page (the `_evict_prefix_page` discipline); the
        exporter blocks on them — device→host readback — outside every
        engine lock, overlapped with the prompt's next chunk's compute."""
        exp = self._handoff_exporter
        if (exp is None or self.handoff_streams <= 0 or self._prefix is None
                or self.kv_layout != "paged" or exp.known_blob()):
            return  # blob peer or blob config: pages ship at activation
        n_full = min(s.written, s.prompt_len) // self.page_size
        t = s.handoff
        if t is None:
            if n_full == 0:
                return  # no full page yet; nothing to ship
            t = s.handoff = exp.begin_stream(
                s.request, np.asarray(s.prompt_tokens), self._page_bytes,
                time.monotonic())
        ready = min(n_full, len(self._slot_pages[idx]))
        if ready > t.staged_pages:
            t.add(executor.gather_pages(
                self, self._slot_pages[idx][t.staged_pages:ready]))
            exp.kick(t)

    def _export_handoff(self, idx: int, s: _Slot, tok: int, now: float) -> bool:
        """Prefill-role terminal: ship the slot's full KV pages to the decode
        pool and complete the request with just its first token
        (finish_reason="handoff"). Returns False → colocated fallback (no
        exporter wired, unpaged prompt shorter than one page, lane state
        already torn down).

        The pages survive `_free_slot` because `_prefix_insert` one line
        earlier retained them in the prefix cache; the per-page gathers are
        dispatched HERE, under the state lock, so they capture the cache
        value before any later step can recycle a page (the
        `_evict_prefix_page` discipline — JAX's functional updates make the
        gathered payload immune to subsequent pool writes).

        With streaming negotiated (GOFR-HANDOFF2) most pages already left
        during the chunk folds (`_stream_handoff_chunk`); this terminal
        stages only the tail, detaches the transfer from the slot (so
        `_free_slot` doesn't abort it) and hands the exporter the first
        token to close the stream with."""
        exp = self._handoff_exporter
        if exp is None or self._prefix is None:
            return False
        n_full = s.prompt_len // self.page_size
        if n_full == 0 or len(self._slot_pages[idx]) < n_full:
            if s.handoff is not None:
                t, s.handoff = s.handoff, None
                exp.abort(t)  # partial stream of a slot that fell back
            return False
        pages = self._slot_pages[idx][:n_full]
        rt = s.request.kw.get("_rt")
        if rt is not None:
            rt.end("engine.decode")
            rt.begin("engine.handoff", **{"pages": n_full})
        if self.handoff_streams > 0 and not exp.known_blob():
            # streaming path (also carries the negotiated-down blob case:
            # the exporter accumulates and ships one frame at finish)
            t = s.handoff
            if t is None:
                t = exp.begin_stream(
                    s.request, np.asarray(s.prompt_tokens),
                    self._page_bytes, now)
            else:
                s.handoff = None  # detach BEFORE _free_slot's abort hook
            if n_full > t.staged_pages:
                t.add(executor.gather_pages(self, pages[t.staged_pages:]))
            self._free_slot(idx)
            exp.finish(t, tok, now)
            return True
        payloads = executor.gather_pages(self, pages)
        self._free_slot(idx)
        from gofr_tpu.tpu.handoff import HandoffJob

        exp.submit(HandoffJob(
            request=s.request, prompt_tokens=np.asarray(s.prompt_tokens),
            first_token=tok, payloads=payloads,
            nbytes_page=self._page_bytes, t0=now))
        return True

    def handoff_import(self, toks, payloads, nbytes_page: int) -> int:
        """Decode-role ingest (called from the HandoffServer thread): park
        the shipped pages as HOST-tier prefix nodes for `toks`' chain. The
        next admission of that prompt claims them through `_usable_hit` and
        re-uploads via the ordinary swap-in path, so the upload overlaps
        live decode on the `_dq` exactly like any other host-tier hit.
        Returns the number of chain positions newly registered."""
        if self.kv_layout != "paged" or self._prefix is None:
            raise ValueError("handoff import needs the paged prefix cache")
        if not self._prefix.host_budget:
            raise ValueError("handoff import needs a host-tier budget")
        want = [((leaf.shape[0],) + tuple(leaf.shape[2:]), leaf.dtype)
                for leaf in jax.tree.leaves(self.kv_cache)]
        for planes in payloads:
            if len(planes) != len(want):
                raise ValueError(
                    f"handoff page has {len(planes)} planes, pool has {len(want)}")
            for plane, (shape, dtype) in zip(planes, want):
                if tuple(plane.shape) != shape or plane.dtype != dtype:
                    raise ValueError(
                        f"handoff plane {plane.dtype}{tuple(plane.shape)} != "
                        f"pool {dtype}{shape}")
        with self._state_lock:
            # the engine's OWN page-byte size, not the wire value: both sides
            # must agree on geometry for the planes to validate above, and
            # budget accounting must match this pool's arithmetic
            added = self._prefix.insert_host(
                np.asarray(toks), payloads, self._page_bytes)
            self._set_prefix_gauges()
        return added

    def handoff_stats(self) -> dict:
        """Role + transfer counters for /debug/fleet."""
        out: dict[str, Any] = {"role": self.role}
        if self._handoff_exporter is not None:
            out["export"] = self._handoff_exporter.stats()
        if self._handoff_server is not None:
            out["import"] = self._handoff_server.stats()
            out["addr"] = self.handoff_addr
        return out

    # -- online knob actuation (gofr_tpu.control) ------------------------------

    def _build_controller(self, container):
        """Wire a StepController to this engine's knob seams. Each KnobSpec
        APPLY enqueues through request_knobs — the controller ticks on the
        device thread, so the change lands at the very next loop top, but
        routing through the queue keeps one audited mutation path for
        controller, debug endpoints, and bench drills alike."""
        from gofr_tpu.control.controller import (ControlPolicy, KnobSpec,
                                                 StepController)

        policy = ControlPolicy.from_config(container.config)
        specs = [
            KnobSpec("pipeline_depth",
                     tuple(range(1, self._boot_pipeline_depth + 1)),
                     lambda: self.pipeline_depth,
                     lambda v: self.request_knobs(pipeline_depth=v)),
            KnobSpec("prefill_chunk", tuple(self.prefill_buckets),
                     lambda: self.prefill_chunk,
                     lambda v: self.request_knobs(prefill_chunk=v)),
            KnobSpec("prefill_batch",
                     tuple(range(1, self._boot_prefill_batch + 1)),
                     lambda: self.max_prefill_batch,
                     lambda v: self.request_knobs(prefill_batch=v)),
        ]
        if self._boot_spec_tokens:
            # g=0 <-> g>0 is not a knob move (the spec carry changes the
            # cache pytree and the dispatch path): explore [1 .. boot g]
            specs.append(KnobSpec(
                "spec_tokens", tuple(range(1, self._boot_spec_tokens + 1)),
                lambda: self.spec_tokens,
                lambda v: self.request_knobs(spec_tokens=v)))

        def on_decision(d):
            if self.flight is not None:
                self.flight.record_control(d.to_dict())
            self.metrics.increment_counter(
                "app_tpu_control_decisions_total", 1, verdict=d.verdict)

        return StepController(
            policy, specs,
            kv_dtype=self.perf.model.kv_dtype,
            device_kind=self.perf.device_kind,
            shard=f"tp{max(1, getattr(self, 'kv_shards', 1))}",
            window_fn=self.perf.band_totals,
            standdown_fn=lambda: "lockstep" if self.lockstep_role else None,
            on_decision=on_decision,
            logger=self.logger)

    def _spec_fn_for(self, g: int):
        """The compiled spec-round handle for round length ``g`` (g is a
        static arg of the jitted program); builds and caches on first use."""
        fn = self._spec_fns.get(g)
        if fn is None:
            progs = build_programs(self.family, self.cfg, spec_tokens=g,
                                   **self._progs_kw)
            fn = self._spec_fns[g] = progs.spec_chunk
        return fn

    def request_knobs(self, **knobs) -> None:
        """Thread-safe: enqueue knob changes for the device loop to apply
        at its loop-top safe seam (_apply_pending_knobs) — no dispatch is
        in flight-construction there, so every dispatch snapshots a
        consistent knob vector."""
        self._knob_requests.append(dict(knobs))

    def _apply_pending_knobs(self) -> None:
        while self._knob_requests:
            req = self._knob_requests.popleft()
            for name, value in req.items():
                try:
                    self._apply_knob_now(name, value)
                except Exception as e:  # a bad knob must never kill the loop
                    self.logger.warn(f"knob {name}={value!r} rejected: {e}")

    def _apply_knob_now(self, name: str, value) -> None:
        """Device-thread only. Clamps every move to the boot ceiling (the
        operator's provisioned envelope) and, for prefill_chunk, snaps to a
        bucket member so next_bucket stays exact and the compiled-signature
        population never grows past the boot set."""
        v = int(value)
        if name == "pipeline_depth":
            self.pipeline_depth = max(1, min(v, self._boot_pipeline_depth))
            self.decode_pipeline = self.pipeline_depth  # keep the alias true
        elif name == "prefill_chunk":
            allowed = [b for b in self.prefill_buckets if b <= v]
            self.prefill_chunk = (allowed[-1] if allowed
                                  else self.prefill_buckets[0])
        elif name == "prefill_batch":
            self.max_prefill_batch = max(1, min(v, self._boot_prefill_batch))
        elif name == "spec_tokens":
            if not self._boot_spec_tokens:
                raise ValueError(
                    "spec is off at boot; g=0<->g>0 changes the cache pytree")
            g = max(1, min(v, self._boot_spec_tokens))
            if g != self.spec_tokens:
                # swap the compiled handle FIRST: a failed (re)build leaves
                # the old g fully consistent. In-flight rounds fold with
                # their dispatch-time g (decode._fold_spec reads sig), and
                # _chunk_span stays at the boot worst case, so masking and
                # paged over-claim remain conservative.
                self._spec_chunk_fn = self._spec_fn_for(g)
                self.spec_tokens = g
        else:
            raise ValueError(f"unknown knob {name!r}")

    def knob_vector(self) -> dict[str, int]:
        """Live knob values — stamped on flight-recorder steps, gossiped in
        the fleet digest, and compared by the bench's exactness drill."""
        out = {"pipeline_depth": self.pipeline_depth,
               "prefill_chunk": self.prefill_chunk,
               "prefill_batch": self.max_prefill_batch}
        if self._boot_spec_tokens:
            out["spec_tokens"] = self.spec_tokens
        return out

    def control_report(self) -> dict[str, Any]:
        """/debug/control payload (app.py)."""
        if self._control is None:
            return {"enabled": False, "knobs": self.knob_vector()}
        return self._control.report()

    def _loop(self) -> None:
        self._dq.clear()  # a restarted loop must not read a dead life's futures
        self._prev_last = None
        self._spec_carry = None
        if getattr(self, "_pending_swapins", None):
            self._pending_swapins = []  # staged by a dead life; never dispatch
        if getattr(self, "_pending_spills", None):
            self._pending_spills = []
        while not self._stop.is_set() and not self._poisoned:
            # loop-top safe seam: no dispatch is being constructed here, so
            # queued knob changes (controller commits/reverts, debug pokes)
            # land before anything snapshots them; the controller itself
            # ticks right after, ON this thread, so its applies take effect
            # at the very next iteration. ``depth`` is re-read every
            # iteration — a live pipeline_depth move simply changes how far
            # the drain below lets the queue refill.
            self._apply_pending_knobs()
            if self._control is not None:
                self._control.maybe_tick(time.monotonic())
            depth = self.pipeline_depth
            # One bounded in-flight device queue (self._dq): batched
            # prefill, chunked prefill, and decode/spec chunks all DISPATCH
            # here (enqueueing their device futures) and are read back +
            # folded into slot state at DEQUEUE below — so every readback's
            # device→host round trip and host bookkeeping overlap the
            # compute of whatever was dispatched after it. Spec rounds ride
            # the queue on BOTH layouts: the paged dispatcher over-claims
            # pages for the worst-case accepted span at dispatch time and
            # the fold releases the surplus, so page allocation never waits
            # on readback (decode.dispatch_spec_paged).
            if self._chaos_step is not None:
                self._chaos_step(step=self._step_count)
            if self._ls is not None and self._ls.has_pending():
                # fleet membership change: admit (re)joining followers at
                # this step boundary via an epoch bump (requeue + reset)
                self._fleet_admit()
            if self._pending_weights is not None:
                # live hot-swap staged by adopt_weights: drain + requeue +
                # epoch bump at this step boundary (zero-drop)
                self._apply_pending_weights()
            if self._hotswap_dir is not None:
                self._poll_hotswap()
            processed = False
            admitted = self._admit()
            if depth == 1:
                # TRULY synchronous at depth 1: each dispatch is read back
                # before the next phase dispatches (the pre-unification
                # behavior, and what "fully synchronous" promises operators
                # debugging with ENGINE_PIPELINE=1 — also the honest "off"
                # arm of the bench's overlap A/B)
                while self._dq:
                    processed = process_decode(self) or processed
            # one chunk of ONE long prompt per iteration, so decode of the
            # other slots keeps stepping between chunks (TTFT fairness)
            chunked = self._advance_chunked()
            if depth == 1:
                while self._dq:
                    processed = process_decode(self) or processed
            if not self.spec_tokens:
                dispatched = dispatch_decode(self)
            elif self.kv_layout == "slot":
                dispatched = dispatch_spec(self)
            else:
                dispatched = dispatch_spec_paged(self)
            busy = admitted or chunked or dispatched
            # drain to depth-1 in-flight entries while work keeps arriving
            # (each blocking readback overlaps every younger dispatch);
            # drain fully when the engine goes quiet so no future lingers
            while len(self._dq) > (depth - 1 if busy else 0):
                processed = process_decode(self) or processed
            if not busy and not processed:
                if self._ls is not None and self._hb_interval:
                    # idle leader: heartbeat so follower watchdogs see
                    # liveness between announcements (LOCKSTEP_DEADLINE_S)
                    self._ls.maybe_heartbeat(self._hb_interval)
                if self._quality is not None and self._quality.step():
                    # quality plane: ONE shadow-scoring arm per idle
                    # iteration, then straight back to the top of the loop —
                    # interactive work that arrived during the forward is
                    # picked up before the next arm runs, and shadow work
                    # claims no slots or pages (it is a standalone
                    # teacher-forced forward), so preemption is free
                    continue
                # idle: block briefly for work without consuming (a get/put
                # round trip would skew QoS wait metrics and fair credits,
                # and could reorder same-class FIFO arrivals)
                self._queue.wait_nonempty(0.2)
                if self.perf is not None:
                    # nothing queued, nothing in flight: advance the bubble
                    # floor so true idleness never counts as pipeline bubble
                    self.perf.mark_no_work(time.monotonic())

    # -- admission / prefill ---------------------------------------------------

    def _drain_pending(self) -> None:
        """Move queued requests into the encoded pending list (invalid ones
        complete with their error immediately). With QoS on, at most a
        couple of admission rounds' worth is drained per iteration — a full
        drain would freeze class priorities at arrival order inside the
        FIFO ``_pending`` list, while a bounded one keeps late-arriving
        interactive traffic able to overtake queued batch work."""
        budget = (2 * self.num_slots + self.max_prefill_batch
                  if self.qos is not None else -1)
        while budget != 0:
            budget -= 1
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            try:
                toks = self._encode_prompt(req.inputs)
                if toks.ndim != 1 or toks.shape[0] == 0:
                    raise ValueError(f"prompt must be a non-empty 1-D token sequence, got shape {toks.shape}")
                if toks.shape[0] >= self.max_len:
                    raise ValueError(f"prompt length {toks.shape[0]} ≥ engine max_len {self.max_len}")
                if toks.shape[0] > self.prefill_buckets[-1]:
                    if not self._chunked_ok:
                        raise ValueError(
                            f"prompt length {toks.shape[0]} exceeds the largest prefill "
                            f"bucket {self.prefill_buckets[-1]} (chunked prefill needs "
                            f"the paged layout or a family with SLOT_CHUNKED_PREFILL)"
                        )
                    self._pending_long.append((req, toks))
                else:
                    self._pending.append((req, toks))
            except Exception as e:  # noqa: BLE001
                req.complete(error=e)

    def _admit_long(self) -> None:
        """Claim a free slot for each waiting long prompt (paged layout).
        No device work here — _advance_chunked streams the prompt into the
        cache one bucket-sized chunk per loop iteration. Caller holds the
        state lock."""
        while self._pending_long and self._free_slots():
            req, toks = self._pending_long.pop(0)
            if req.cancelled or req.expired(time.monotonic()):
                req.complete(error=RequestTimeout())
                continue
            ad = self._acquire_adapter(req)
            if ad is None:
                continue  # adapter vanished since submit; request failed
            if ad == "wait":
                # every adapter pool slot is referenced by a live lane:
                # requeue at the head, exactly like KV page exhaustion
                self._pending_long.insert(0, (req, toks))
                break
            idx = self._free_slots()[0]
            slot = _Slot(
                req,
                prompt_len=int(toks.shape[0]),
                max_total=min(int(toks.shape[0]) + int(req.kw.get("max_new_tokens", 64)),
                              self.max_len),
                eos=req.kw.get("eos_token_id", self.eos_token_id),
                first_token=None,
                admit_seq=self._admit_seq,
                prompt_tokens=toks,
                adapter_id=ad[0],
                adapter_slot=ad[1],
            )
            self._admit_seq += 1
            self._claim_slot(idx, slot)
            self._mark_admitted(req, time.monotonic())
            req.kw["_slot"] = idx
            req.kw["_prompt_len"] = slot.prompt_len
            rt = req.kw.get("_rt")
            if rt is not None:
                rt.begin("engine.prefill",
                         **{"slot": idx, "prompt.tokens": slot.prompt_len,
                            "prefill.chunked": True})
            self._prefix_hit(idx, slot, toks)

    def _advance_chunked(self) -> bool:
        """DISPATCH the next chunk of the OLDEST-admitted prefilling slot
        onto the in-flight queue; readback + slot bookkeeping happen at
        dequeue (_fold_chunk), overlapped with later dispatches — the final
        chunk's dequeue samples the request's first token and flips the
        slot to the decode stage. One chunk dispatched per loop iteration
        keeps decode stepping between chunks; successive iterations can
        keep several chunks of one prompt in flight (``dispatched`` tracks
        the frontier). Returns True when device work was dispatched."""
        if not self._chunked_ok:
            return False
        with self._state_lock:
            pre = [i for i in self._prefill_lanes
                   if self.slots[i].dispatched < self.slots[i].prompt_len]
            if not pre:
                return False
            idx = min(pre, key=lambda i: self.slots[i].admit_seq)
            s = self.slots[idx]
            if s.request.cancelled or s.request.expired(time.monotonic()):
                self._free_slot(idx)
                s.request.complete(error=RequestTimeout())
                return True  # state changed; re-loop without idling
            offset = s.dispatched
            # prefill_chunk is the controller's chunked-prefill knob: a
            # bucket member <= buckets[-1], so smaller values trade TTFT of
            # the long prompt for tighter decode interleave without ever
            # minting a new compiled signature
            chunk = min(s.prompt_len - offset, self.prefill_chunk)
            lb = next_bucket(chunk, self.prefill_buckets)
            table_row = None
            if self.kv_layout == "paged":
                # pages must cover this chunk's writes before the table
                # snapshot; they stay reserved until the fold (or _free_slot)
                while not self._ensure_pages(idx, offset + chunk - 1):
                    if not self._preempt_newest(except_slot=idx):
                        self._free_slot(idx)
                        s.request.complete(error=RuntimeError(
                            "KV page pool exhausted for a single request"))
                        return True  # state changed; re-loop without idling
                if self.slots[idx] is None:  # preemption pressure evicted US
                    return True
                table_row = self._table[idx].copy()
            last = offset + chunk == s.prompt_len
            s.dispatched = offset + chunk
            self._step_count += 1
            step = self._step_count
            temp = float(s.request.kw.get("temperature", 0.0))
            t0 = time.monotonic()

        # device dispatch OUTSIDE the state lock: everything in the plan is
        # immutable (prompt_tokens) or snapshotted above (table row, step)
        executor.dispatch_chunk(self, executor.ChunkPlan(
            idx, s, chunk, offset, last, lb, table_row, temp, step, t0))
        return True

    def _fold_chunk(self, first: np.ndarray, meta, t0: float,
                    occupancy: float, sig: tuple, pstep=None) -> None:
        """Dequeue side of one prefill chunk (called by process_decode with
        the tokens already read back). Lanes freed/preempted since dispatch
        are discarded by identity — the same discipline decode uses."""
        idx, s, chunk, offset, last = meta
        lb = sig[1]
        with self._state_lock:
            dev_s = self._record_step(
                "prefill_chunk", time.monotonic() - t0, occupancy, sig, pstep,
                adapter_ids=([s.adapter_id or "base"]
                             if self._adapters_enabled else None))
            if self.slots[idx] is not s:
                return  # stop()/preemption/cancel took over while in flight
            if s.request.cancelled or s.request.expired(time.monotonic()):
                self._free_slot(idx)
                s.request.complete(error=RequestTimeout())
                return
            if dev_s:
                kw = s.request.kw
                kw["_dev_prefill_s"] = kw.get("_dev_prefill_s", 0.0) + dev_s
            self.metrics.increment_counter("app_tpu_tokens_total", chunk)
            s.written += chunk
            rt = s.request.kw.get("_rt")
            if rt is not None:
                rt.event("engine.prefill", "chunk",
                         offset=offset, tokens=chunk, bucket=lb)
            if last:
                if rt is not None:
                    rt.end("engine.prefill")
                    rt.begin("engine.decode", **{"slot": idx})
                self._activate_lane(idx, s, int(first[0]), time.monotonic())
            elif self.role == "prefill":
                # streaming handoff (GOFR-HANDOFF2): pages this fold just
                # made durable start shipping NOW, overlapped with the
                # prompt's remaining prefill chunks still on the device
                self._stream_handoff_chunk(idx, s)

    def _admit(self) -> bool:
        """Admission round: plan/claim/dispatch prefills, then dispatch any
        host-tier swap-ins the claims staged. The swap-in dispatch MUST
        happen before this device thread can dispatch a tail chunk for the
        claimed slot (_advance_chunked runs next in the loop): all device
        calls thread ``self.cache``, so issue order is data-dependency
        order and the upload lands before any read of those pages."""
        busy = self._admit_prefill()
        if getattr(self, "_pending_spills", None):
            self._materialize_spills()
        if getattr(self, "_pending_swapins", None):
            busy = self._dispatch_swapins() or busy
        return busy

    def _materialize_spills(self) -> None:
        """Complete staged spill copies OUTSIDE the state lock: eviction
        dispatched each page's gather asynchronously (so pool pressure
        never blocks the lock on a device round trip) and left the node
        holding the small gathered device buffers; this step — device
        thread, once per loop iteration — blocks on those buffers, copies
        them to host memory, and swaps the node payload. Nodes dropped or
        promoted in between simply skip the replacement. Body lives in
        the executor layer (tpu/executor.py)."""
        executor.materialize_spills(self)

    def _dispatch_swapins(self) -> bool:
        """Dispatch one async host→device page upload per staged hit onto
        the unified in-flight queue. Body lives in the executor layer
        (tpu/executor.py, dispatch_swapins) — see its docstring for the
        locking/fold contract."""
        return executor.dispatch_swapins(self)

    def _fold_swapin(self, meta, t0: float, occupancy: float, sig: tuple,
                     pstep=None) -> None:
        """Dequeue side of one swap-in (process_decode already blocked on
        the upload's completion marker). Settles the promoted nodes — they
        become spillable again — whatever happened to the slot; per-slot
        bookkeeping is discarded by identity (preemption/cancel/stop while
        in flight): the upload still landed in cache-owned pages holding
        exactly the content their chain nodes advertise, so nothing needs
        undoing."""
        idx, s, keys, n_pages, nbytes = meta
        now = time.monotonic()
        with self._state_lock:
            dev_s = self._record_step("swapin", now - t0, occupancy, sig, pstep)
            if self._prefix is not None:
                for key in keys:
                    self._prefix.settle(key)
            self.metrics.increment_counter(
                "app_tpu_prefix_swapin_pages_total", n_pages)
            self.metrics.record_histogram(
                "app_tpu_prefix_swapin_seconds", now - t0)
            self.metrics.record_histogram(
                "app_tpu_prefix_swapin_bytes", nbytes)
            if self.slots[idx] is not s:
                return  # freed/preempted/cancelled mid-swap-in
            if dev_s:
                kw = s.request.kw
                kw["_dev_swapin_s"] = kw.get("_dev_swapin_s", 0.0) + dev_s
            rt = s.request.kw.get("_rt")
            if rt is not None:
                rt.event("engine.prefill", "swapin",
                         pages=n_pages, bytes=nbytes)

    def _admit_prefill(self) -> bool:
        # Plan + claim under the state lock; token packing and the device
        # call OUTSIDE it (a wedged device call must never hold the lock,
        # or stop()'s _fail_all would deadlock behind it — and the pure-
        # numpy packing doesn't need it either). The dispatched prefill's
        # future rides the in-flight queue; readback + slot activation
        # happen at dequeue (_fold_prefill), overlapped with later
        # dispatches. Slots (and their pages) are CLAIMED here at dispatch
        # so the lane stays reserved until the matching dequeue — visible
        # to preemption, _fail_all, and crash recovery like any other
        # occupied lane.
        with self._state_lock:
            if self._draining:
                # scale-in drain: no new slot claims; queued work stays put
                # for drain_queued() to requeue onto a peer. Under the same
                # lock drain_queued takes, so a request can never be mid-move
                # from queue to slot when it runs.
                return False
            self._drain_pending()
            self.metrics.set_gauge("app_tpu_queue_depth", self._backlog())
            self._admit_long()
            free = self._free_slots()
            if not self._pending:
                return False
            still = []
            for r, t in self._pending:
                if r.cancelled:
                    r.complete(error=RequestTimeout())
                else:
                    still.append((r, t))
            self._pending = still

            # EDF + bucket-affinity packing (native planner when available):
            # the most urgent request leads and sets the length bucket; only
            # prompts fitting that bucket join, so one long prompt never
            # inflates the whole batch's padding.
            now_us = int(time.monotonic() * 1e6)
            plan = plan_prefill(
                [t.shape[0] for _, t in self._pending],
                [int(r.deadline * 1e6) if r.deadline else 0 for r, _ in self._pending],
                now_us, len(free), self.max_prefill_batch, self.prefill_buckets,
            )
            for i in plan.expired:
                self._pending[i][0].complete(error=RequestTimeout())
            ready = [self._pending[i] for i in plan.chosen]
            taken = set(plan.chosen) | set(plan.expired)
            self._pending = [p for i, p in enumerate(self._pending) if i not in taken]

            ad_of: dict[int, tuple] | None = None
            if self._adapters_enabled:
                # bind each chosen request's adapter to a device pool slot
                # BEFORE any slot/page claims below — dropping a request
                # after its pages were ensured would misalign the
                # row↔pages mapping of the batched dispatch
                ad_of = {}
                bound = []
                ad_wait = False
                for req, toks in ready:
                    ad = None if ad_wait else self._acquire_adapter(req)
                    if ad is None and not ad_wait:
                        continue  # adapter vanished since submit; failed
                    if ad_wait or ad == "wait":
                        # pool fully referenced by live lanes: requeue
                        # (order preserved — later picks wait behind it,
                        # exactly like the KV page-exhaustion gate)
                        ad_wait = True
                        self._pending.append((req, toks))
                        continue
                    ad_of[id(req)] = ad
                    bound.append((req, toks))
                ready = bound

            chunk_claimed = False
            if self.kv_layout == "paged" and self._prefix is not None:
                # EDF-chosen prompts whose cached prefix covers ≥ HALF their
                # tokens claim a slot on the CHUNKED path: its offset prefill
                # computes only the uncached remainder (the batched prefill
                # program has no offset support). Below the threshold the
                # recompute is cheap relative to losing prefill batching, so
                # the request stays on the EDF batch. Routing happens here —
                # for requests the plan already chose — so the lookup cost is
                # bounded by free slots per admission, not backlog size per
                # loop iteration, and EDF ordering is preserved.
                still = []
                for req, toks in ready:
                    chain = self._usable_hit(toks)
                    if 2 * len(chain) * self.page_size >= int(toks.shape[0]):
                        idx = self._free_slots()[0]
                        ad = (ad_of.get(id(req), (None, 0))
                              if ad_of is not None else (None, 0))
                        slot = _Slot(
                            req,
                            prompt_len=int(toks.shape[0]),
                            max_total=min(
                                int(toks.shape[0]) + int(req.kw.get("max_new_tokens", 64)),
                                self.max_len,
                            ),
                            eos=req.kw.get("eos_token_id", self.eos_token_id),
                            first_token=None,
                            admit_seq=self._admit_seq,
                            prompt_tokens=toks,
                            adapter_id=ad[0],
                            adapter_slot=ad[1],
                        )
                        self._admit_seq += 1
                        self._claim_slot(idx, slot)
                        self._mark_admitted(req, time.monotonic())
                        req.kw["_slot"] = idx
                        req.kw["_prompt_len"] = slot.prompt_len
                        self._prefix_hit(idx, slot, toks, chain=chain)
                        rt = req.kw.get("_rt")
                        if rt is not None:
                            # hit_pages is what was actually SPLICED — the
                            # chain can stop short of the planning-time
                            # length when a host node finds no free page
                            rt.begin("engine.prefill",
                                     **{"slot": idx, "prompt.tokens": slot.prompt_len,
                                        "prefill.chunked": True,
                                        "prefix.hit_pages": len(self._slot_pages[idx])})
                        chunk_claimed = True
                    else:
                        still.append((req, toks))
                ready = still
                free = self._free_slots()

            if self.kv_layout == "paged":
                # admission gate: each admitted prompt needs pages covering its
                # prefill writes NOW. On pool exhaustion the leader (most urgent)
                # stops admission entirely — later arrivals must not starve it.
                admitted: list[tuple[Request, np.ndarray]] = []
                exhausted = False
                for req, toks in ready:
                    if not exhausted and self._ensure_pages(free[len(admitted)], int(toks.shape[0]) - 1):
                        admitted.append((req, toks))
                    else:
                        exhausted = True
                        if ad_of is not None:
                            # bounced back to pending: drop the adapter
                            # pool reference taken above (re-acquired at
                            # the next admission attempt)
                            a = ad_of.pop(id(req), None)
                            if a and a[1]:
                                self._adapter_pool.release(a[1])
                        self._pending.append((req, toks))
                ready = admitted
            if not ready:
                return chunk_claimed
            if self.kv_layout == "paged" and self._prefix is not None:
                # cache-consultation accounting at ADMISSION, not per
                # planning round (a pool-bounced request must not recount):
                # batched-path admissions serve nothing from cache — a
                # below-threshold hit goes unused — so each counts one
                # lookup and one miss
                self.metrics.increment_counter(
                    "app_tpu_prefix_lookup_total", len(ready))
                self.metrics.increment_counter(
                    "app_tpu_prefix_miss_total", len(ready))

            # one prefill call, padded to (len_bucket, batch_bucket), shipped
            # as ONE packed array (layout documented at the jit definitions).
            # Padding rows point at slot index == num_slots, which is out of
            # bounds for the cache's slot dimension — XLA scatter DROPS
            # out-of-bounds updates, so they write nowhere (verified in
            # tests). Paged rows use the same trick through all-OOB
            # block-table rows (ops.paged).
            n = len(ready)
            nb = plan.batch_bucket
            lb = plan.len_bucket
            w = executor.prefill_cols(self)
            rows = free[:n]
            table_rows = (self._table[rows].copy()
                          if self.kv_layout == "paged" else None)
            t0 = time.monotonic()
            meta: list[tuple[int, _Slot]] = []
            for i, (req, toks) in enumerate(ready):
                self._mark_admitted(req, t0)
                req.kw["_slot"] = rows[i]
                req.kw["_prompt_len"] = int(toks.shape[0])
                rt = req.kw.get("_rt")
                if rt is not None:
                    rt.begin("engine.prefill",
                             **{"prefill.len_bucket": lb, "prefill.batch": nb})
                ad = (ad_of.get(id(req), (None, 0))
                      if ad_of is not None else (None, 0))
                slot = _Slot(
                    req,
                    prompt_len=int(toks.shape[0]),
                    max_total=min(int(toks.shape[0]) + int(req.kw.get("max_new_tokens", 64)),
                                  self.max_len),
                    eos=req.kw.get("eos_token_id", self.eos_token_id),
                    first_token=None,
                    admit_seq=self._admit_seq,
                    prompt_tokens=toks,
                    adapter_id=ad[0],
                    adapter_slot=ad[1],
                )
                slot.dispatched = slot.prompt_len  # whole prompt in this call
                self._admit_seq += 1
                self._claim_slot(rows[i], slot)
                meta.append((rows[i], slot))
            self._step_count += 1
            step = self._step_count

        # device dispatch OUTSIDE the state lock (executor layer): token/
        # temp data rides the immutable `ready` list, lanes and table rows
        # were snapshotted under the lock above
        executor.dispatch_prefill(self, executor.PrefillPlan(
            ready, meta, nb, lb, w, rows, table_rows, step, t0))
        return True

    def _fold_prefill(self, first: np.ndarray, meta, t0: float,
                      occupancy: float, sig: tuple, pstep=None) -> None:
        """Dequeue side of a batched prefill: activate each slot claimed at
        dispatch with its sampled first token. Lanes whose slot object
        changed since dispatch (stop()'s _fail_all, preemption, cancel)
        are discarded by identity — their requests were already completed
        and their pages returned by _free_slot."""
        with self._state_lock:
            dev_s = self._record_step(
                "prefill", time.monotonic() - t0, occupancy, sig, pstep,
                adapter_ids=([s.adapter_id or "base" for _, s in meta]
                             if self._adapters_enabled else None))
            now = time.monotonic()
            tokens = 0
            for row, (idx, s) in enumerate(meta):
                if self.slots[idx] is not s:
                    continue  # freed/preempted/failed while in flight
                if s.request.cancelled or s.request.expired(now):
                    self._free_slot(idx)
                    s.request.complete(error=RequestTimeout())
                    continue
                if dev_s:
                    kw = s.request.kw
                    kw["_dev_prefill_s"] = kw.get("_dev_prefill_s", 0.0) + dev_s
                tokens += s.prompt_len + 1
                rt = s.request.kw.get("_rt")
                if rt is not None:
                    rt.end("engine.prefill",
                           **{"slot": idx, "batch.occupancy": occupancy})
                    rt.begin("engine.decode", **{"slot": idx})
                self._activate_lane(idx, s, int(first[row]), now)
            self.metrics.increment_counter("app_tpu_tokens_total", tokens)

    # -- completion ------------------------------------------------------------

    # stream detokenizer bounds: ctx anchors in-context decoding (a few
    # tokens suffice for space-marker/merge effects); tail max bounds
    # worst-case hold latency and per-token re-decode cost
    STREAM_CTX_TOKENS = 8
    STREAM_TAIL_MAX = 32

    def _stream_diff(self, kw: dict, tail: list) -> str:
        """decode(ctx + tail) minus decode(ctx) — the next stream piece."""
        ctx = kw.get("_stream_ctx", [])
        if not ctx:
            return self.tokenizer.decode(tail)
        return self.tokenizer.decode(ctx + tail)[len(self.tokenizer.decode(ctx)):]

    def _emit(self, slot: _Slot, tok: int) -> None:
        if slot.request.stream_q is None or tok == slot.eos:
            return
        if self.tokenizer is None:
            slot.request.stream_q.put(tok)
            return
        # Incremental detokenization: unflushed token ids accumulate in a
        # TAIL and are emitted as the decode DIFF against a short context
        # of already-flushed ids — piece = decode(ctx + tail) minus
        # decode(ctx). The diff keeps tokenizers whose per-group decode
        # differs from in-context decode exact (SentencePiece strips a
        # leading space marker per decode call; the shared ctx prefix makes
        # any such artifact identical in both decodes and cancel). A piece
        # ending in U+FFFD holds a split multi-byte character until the
        # next token completes it, but the tail never grows past
        # STREAM_TAIL_MAX tokens — a model stuck on undecodable or
        # empty-decoding ids must not stall the stream or grow an O(n)
        # re-decode. State lives on the REQUEST so it survives preemption-
        # by-recompute; _maybe_finish flushes the remainder so the joined
        # stream equals the final result text.
        tail = slot.request.kw.setdefault("_stream_tail", [])
        tail.append(tok)
        piece = self._stream_diff(slot.request.kw, tail)
        if (piece and not piece.endswith("�")) or len(tail) > self.STREAM_TAIL_MAX:
            if piece:
                slot.request.stream_q.put(piece)
            slot.request.kw["_stream_ctx"] = (
                slot.request.kw.get("_stream_ctx", []) + tail)[-self.STREAM_CTX_TOKENS:]
            tail.clear()

    def _maybe_finish(self, slot_idx: int) -> None:
        s = self.slots[slot_idx]
        if s.eos is not None and s.generated[-1] == s.eos:
            finish = "stop"
        elif s.prompt_len + len(s.generated) >= s.max_total:
            finish = "length"
        else:
            return
        # tokens generated before any preemption round-trips lead the result
        prior = list(s.request.kw.get("_prior_tokens", []))
        tokens = prior + (s.generated[:-1] if finish == "stop" else list(s.generated))
        tail = s.request.kw.get("_stream_tail")
        if tail and s.request.stream_q is not None and self.tokenizer is not None:
            # flush any held (possibly incomplete) trailing characters so
            # the joined stream equals the result text exactly — without
            # this, a generation cut mid-character would silently drop its
            # tail from the stream
            text = self._stream_diff(s.request.kw, tail)
            if text:
                s.request.stream_q.put(text)
            tail.clear()
        now = time.monotonic()
        ft = s.request.kw.get("_first_token_at", s.first_token_at)
        if len(tokens) > 1:
            # steady-state decode pace: first token excluded (that's TTFT's
            # job), so tpot isolates the per-token device-loop cost
            self.metrics.record_histogram(
                "app_tpu_tpot_seconds", (now - ft) / (len(tokens) - 1))
            if self.slo is not None:
                self.slo.observe(s.request.kw.get("_qos_class"), "tpot",
                                 (now - ft) / (len(tokens) - 1))
        rt = s.request.kw.get("_rt")
        if rt is not None:
            attrs: dict[str, Any] = {"tokens": len(tokens), "finish.reason": finish}
            proposed = s.request.kw.get("_spec_proposed", 0)
            if proposed:
                attrs["spec.accept_rate"] = round(
                    s.request.kw.get("_spec_accepted", 0) / proposed, 4)
            rt.end("engine.decode", **attrs)
            # covers detokenization + completion bookkeeping; closed by the
            # done callback's close_all right after complete() below
            rt.begin("engine.finish")
        result = {
            "tokens": tokens,
            "text": self.tokenizer.decode(tokens) if self.tokenizer is not None else None,
            "finish_reason": finish,
            "ttft_s": ft - s.request.enqueued_at,
        }
        if self._quality is not None:
            # shadow-sampling dice roll (host-cheap; scoring happens later
            # on idle loop iterations). Captured BEFORE the slot is freed so
            # prompt/emitted are read from live state, keyed by exactly what
            # served the request: adapter, qos class, weights epoch. Uses
            # THIS life's prompt/emitted split (after a preemption the slot
            # prompt already contains the prior tokens — `tokens` above
            # would double-count them).
            self._quality.maybe_capture(
                [int(t) for t in np.asarray(s.prompt_tokens).reshape(-1)],
                s.generated[:-1] if finish == "stop" else list(s.generated),
                adapter=s.adapter_id,
                qos_class=s.request.kw.get("_qos_class"),
                weights_epoch=s.request.kw.get("_weights_epoch",
                                               self.weights_epoch) or 0,
                request_id=s.request.id,
            )
        self._free_slot(slot_idx)
        s.request.complete(result=result)


# -- factory (app.serve_model → here) ------------------------------------------


def _resolve_config(family_name: str, config: Any):
    if config is not None and not isinstance(config, dict):
        return config
    from gofr_tpu.models import BertConfig, GPT2Config, LlamaConfig, ViTConfig

    defaults = {"llama": LlamaConfig, "gpt2": GPT2Config, "bert": BertConfig, "vit": ViTConfig}
    cls = defaults.get(family_name)
    if cls is None:
        raise ValueError(f"no default config for family {family_name!r}; pass spec.config")
    return cls(**config) if isinstance(config, dict) else cls()


def _resolve_weights(spec, family, container, *, seed, rules, mesh, what=None):
    """One weights-to-(cfg, params) resolution path for the target AND the
    speculative draft: orbax checkpoint dir, HF converter, or random init
    (dev/bench), then shard over the mesh by the family's logical axes."""
    name = what or f"model {spec.family}"
    if spec.weights:
        from gofr_tpu.train.checkpoint import is_checkpoint_dir, load_params

        if is_checkpoint_dir(spec.weights):
            # orbax checkpoint dir (train/checkpoint.py): config must be given
            cfg = _resolve_config(spec.family, spec.config)
            like = jax.eval_shape(lambda: family.init(cfg, jax.random.key(0)))
            params = load_params(spec.weights, like)
        else:
            from gofr_tpu.models import convert

            converter = getattr(convert, f"{spec.family}_from_hf", None)
            if converter is None:
                raise ValueError(f"no weight converter for family {spec.family!r}")
            cfg, params = converter(spec.weights, dtype=spec.dtype)
    else:
        cfg = _resolve_config(spec.family, spec.config)
        params = family.init(cfg, jax.random.key(seed))
        container.logger.warn(
            f"{name}: no weights given — randomly initialized (dev/bench mode)"
        )
    return cfg, shard_pytree(params, family.param_axes(cfg), rules, mesh)


def _load_tokenizer(path_or_id):
    if not path_or_id:
        return None
    if hasattr(path_or_id, "encode") and hasattr(path_or_id, "decode") \
            and not isinstance(path_or_id, str):
        return path_or_id  # already a tokenizer object (e.g. utils.ByteTokenizer)
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(path_or_id)


def build_engine(spec: ModelSpec, container, **kw: Any):
    """Materialize an engine from a ModelSpec: resolve config, load or init
    weights, cast + shard onto the container's TPU mesh, pick the engine
    for the task. Engine knobs come from config (ENGINE_*) overridden by
    ``kw`` — the reference's "config decides, code composes" rule
    (`container/container.go:91-122`)."""
    family = get_family(spec.family)
    tpu = container.tpu
    conf = container.config

    rules = tpu.rules
    # the PRE-pp-override rules: the speculative draft shards with these —
    # it is replicated/tp-sharded, never pipeline-layer-sharded (a 2-layer
    # draft's stacked blocks cannot divide a pp axis, and sharding it over
    # pp would contradict the draft's replicated-everywhere contract)
    base_rules = rules
    mesh = tpu.mesh
    # popped unconditionally: the knob must be ignorable on non-pp meshes,
    # not crash GenerateEngine with an unexpected-keyword TypeError
    pp_microbatches = int(kw.pop("pp_microbatches",
                                 conf.get_int("ENGINE_PP_MICROBATCHES", 0)))
    if (spec.task == "generate" and mesh is not None
            and "pp" in getattr(mesh, "axis_names", ()) and mesh.shape["pp"] > 1):
        # pipeline-parallel serving: blocks + slot KV cache shard over pp on
        # the layer dim; engine device calls run the GPipe schedule
        # (models/llama_pp.py). The 70B-on-v5e-64 weight-fit path.
        if spec.family != "llama":
            raise ValueError(
                f"pp-mesh serving is implemented for the llama family only "
                f"(got {spec.family!r}); drop the pp axis or use llama"
            )
        from gofr_tpu.models.llama_pp import PPLlamaFamily

        rules = rules.with_overrides(layers="pp")
        family = PPLlamaFamily(mesh, microbatches=pp_microbatches or None, rules=rules)

    prefill_attn = kw.pop("prefill_attn_fn", None)
    sp_size = (int(mesh.shape["sp"])
               if mesh is not None and "sp" in getattr(mesh, "axis_names", ()) else 1)

    # resolved ONCE: the same seed feeds random weight init AND the engine's
    # sampling RNG — with checkpoint/HF weights a caller-supplied seed was
    # previously popped here and silently dropped before it could reach
    # GenerateEngine's _base_key (ADVICE r5)
    seed = int(kw.pop("seed", 0))
    cfg, params = _resolve_weights(
        spec, family, container, seed=seed, rules=rules, mesh=mesh)

    quantize_kw = kw.pop("quantize", None)
    quantize = str(quantize_kw if quantize_kw is not None else conf.get_or_default("ENGINE_QUANTIZE", ""))
    if quantize == "int8":
        # weight-only int8 AFTER sharding (logical-axis rules apply to the
        # original tree; quantized arrays inherit shardings). Halves the
        # per-step weight reads decode is bound by — measured 1.33x decode
        # throughput on v5e (ops/quant.py). Families whose forwards don't
        # route linears through ops.quant.qdot can't serve QTensors: an
        # explicit per-model request errors, while the process-wide
        # ENGINE_QUANTIZE config only warns (it may legitimately target a
        # different engine in the same app).
        if getattr(family, "QUANTIZABLE", False):
            from gofr_tpu.ops.quant import quantize_tree

            params = jax.jit(quantize_tree)(params)
        elif quantize_kw is not None:
            raise ValueError(
                f"family {spec.family!r} does not support weight-only quantization"
            )
        else:
            container.logger.warn(
                f"ENGINE_QUANTIZE=int8 ignored for family {spec.family!r} (no qdot support)"
            )
    elif quantize:
        raise ValueError(f"ENGINE_QUANTIZE={quantize!r}: only 'int8' is supported")

    tokenizer = _load_tokenizer(spec.tokenizer)
    default_timeout = conf.get_float("ENGINE_TIMEOUT", 0.0) or None
    kw.setdefault("max_restarts", conf.get_int("ENGINE_MAX_RESTARTS", 3))

    if spec.task == "generate":
        eos = kw.pop("eos_token_id", None)
        if eos is None and tokenizer is not None:
            eos = tokenizer.eos_token_id
        default_layout = "paged" if hasattr(family, "make_paged_cache") else "slot"
        kv_layout = str(kw.pop("kv_layout", conf.get_or_default("ENGINE_KV_LAYOUT", default_layout)))
        # spec_tokens follows the quantize precedent (above): an explicit
        # per-model request errors on an incompatible setup, while the
        # process-wide ENGINE_SPEC_TOKENS config only warns — it may
        # legitimately target a different engine in the same app.
        spec_kw = kw.pop("spec_tokens", None)
        spec_tokens = int(spec_kw if spec_kw is not None else conf.get_int("ENGINE_SPEC_TOKENS", 0))
        spec_attr = "verify_step" if kv_layout == "slot" else "verify_step_paged"
        if spec_tokens and not hasattr(family, spec_attr):
            if spec_kw is not None:
                raise ValueError(
                    f"spec_tokens: family {getattr(family, '__name__', family)!r} "
                    f"has no {spec_attr} (speculative verification for the "
                    f"{kv_layout} layout)"
                )
            container.logger.warn(
                f"ENGINE_SPEC_TOKENS ignored for family "
                f"{getattr(family, '__name__', family)!r} (no {spec_attr})"
            )
            spec_tokens = 0
        # draft model for speculative decoding: a ModelSpec (resolved and
        # sharded through the same _resolve_weights path as the target) or
        # a prebuilt (family, cfg, params) triple. Engine-level validation
        # covers layout/protocol fit. Deliberately NOT routed through the
        # target-only extras: pp-family wrapping (the draft is replicated,
        # never pipeline-sharded) and ENGINE_QUANTIZE (a tiny draft's
        # weight reads are noise; quantize the target instead).
        draft_kw = kw.pop("spec_draft", None)
        if isinstance(draft_kw, ModelSpec):
            dfamily = get_family(draft_kw.family)
            dcfg, dparams = _resolve_weights(
                draft_kw, dfamily, container, seed=1, rules=base_rules,
                mesh=mesh, what=f"spec_draft {draft_kw.family}")
            draft_kw = (dfamily, dcfg, dparams)
        elif draft_kw is not None:
            # prebuilt (family, cfg, params) triple: shard the draft over
            # the mesh like everything else the programs close over
            # (base_rules: never the pp layer override — see above)
            dfamily, dcfg, dparams = draft_kw
            draft_kw = (dfamily, dcfg,
                        shard_pytree(dparams, dfamily.param_axes(dcfg), base_rules, mesh))
        if draft_kw is not None:
            kw["spec_draft"] = draft_kw
        # multi-host: every process must issue identical global programs;
        # the leader (process 0) serves, followers run serve_follower()
        # (tpu/lockstep.py). A crash-restart would desynchronize followers,
        # so lockstep engines don't restart.
        lockstep_role = kw.pop("lockstep_role", None)
        # elastic fleet (gofr_tpu.fleet; FLEET_LISTEN / FLEET_LEADER): the
        # announce stream rides the host-side channel with epoch-based
        # rejoin, so the restart budget STAYS available — a leader device-
        # loop restart is an epoch bump, not fleet death
        fleet = kw.pop("fleet", None)
        if fleet is None:
            from gofr_tpu.fleet import FleetConfig

            fleet = FleetConfig.from_config(conf)
        if fleet is not None:
            if lockstep_role not in (None, fleet.role):
                raise ValueError(
                    f"lockstep_role {lockstep_role!r} contradicts the FLEET_* "
                    f"config (role {fleet.role!r})")
            lockstep_role = fleet.role
        elif (lockstep_role is None and getattr(tpu, "distributed", False)
                and jax.process_count() > 1):
            lockstep_role = "leader" if jax.process_index() == 0 else "follower"
        if lockstep_role and fleet is None:
            kw["max_restarts"] = 0
        if fleet is not None:
            kw["fleet"] = fleet

        prefix_cache = bool(kw.pop("prefix_cache", conf.get_bool("ENGINE_PREFIX_CACHE", True)))
        if prefill_attn is None and sp_size > 1 and spec.task == "generate":
            # sequence-parallel PREFILL: whole-prompt attention shards the
            # sequence over sp (ring online-softmax, parallel/ring.py) —
            # the long-context lever for prompt-heavy serving. Batch stays
            # replicated inside the region (prefill batches are small).
            # NOT wired when it would break a contract, with a loud warn:
            # - prefix cache on (paged): a cache hit replays the remainder
            #   through gathered-view attention, whose reduction order
            #   differs from ring's — cold/hit bit-identity would be lost;
            # - non-llama families / the pp family: no attn_fn hook.
            supported = (spec.family == "llama"
                         and getattr(family, "__name__", "") != "llama_pp")
            if not supported:
                container.logger.warn(
                    f"mesh has sp:{sp_size} but sequence-parallel prefill is "
                    f"not wired for family {getattr(family, '__name__', family)!r}"
                )
            elif kv_layout == "paged" and prefix_cache:
                container.logger.warn(
                    f"mesh has sp:{sp_size} but sequence-parallel prefill is "
                    "disabled while the prefix cache is on (ring vs gathered-"
                    "view reduction order would break cold/hit bit-identity); "
                    "set ENGINE_PREFIX_CACHE=false to enable it"
                )
            else:
                from gofr_tpu.parallel.ring import make_seq_parallel_attn

                strategy = conf.get_or_default("ENGINE_SP_STRATEGY", "ring")
                if strategy == "ulysses":
                    # ulysses all-to-alls heads across sp — per-device query
                    # heads must divide (ring.py ulysses check). Fail at
                    # BUILD time like the bucket guard, not mid-serving.
                    tp_size = int(mesh.shape.get("tp", 1))
                    local_heads = cfg.num_heads // max(1, tp_size)
                    if local_heads % sp_size:
                        raise ValueError(
                            f"ENGINE_SP_STRATEGY=ulysses needs per-device query "
                            f"heads ({cfg.num_heads}/tp:{tp_size} = {local_heads}) "
                            f"divisible by sp:{sp_size}"
                        )
                prefill_attn = make_seq_parallel_attn(
                    mesh, batch_axes=(), strategy=strategy)
        # same precedent for the quantized-KV knob. ENGINE_KV_DTYPE is the
        # canonical spelling (bf16 | int8 | int4 — the bench A/B axis);
        # ENGINE_KV_QUANTIZE ("" | int8 | int4) stays as the legacy alias.
        kvq_kw = kw.pop("kv_quantize", None)
        kvd_env = str(conf.get_or_default("ENGINE_KV_DTYPE", "")).lower()
        if kvd_env in ("bf16", "bfloat16"):
            kvd_env = "dense"  # sentinel: explicit request for the dense pool
        if kvq_kw is not None:
            kv_quantize = str(kvq_kw)
        elif kvd_env:
            if kvd_env not in ("dense", "int8", "int4"):
                raise ValueError(
                    f"ENGINE_KV_DTYPE={kvd_env!r}: use bf16, int8 or int4")
            kv_quantize = "" if kvd_env == "dense" else kvd_env
        else:
            kv_quantize = str(conf.get_or_default("ENGINE_KV_QUANTIZE", ""))
        if kv_quantize == "int4":
            kvq_attr = "make_paged_cache_q4"
        else:
            kvq_attr = ("make_cache_q" if kv_layout == "slot"
                        else "make_paged_cache_q")
        if kv_quantize and not hasattr(family, kvq_attr):
            if kvq_kw is not None or kvd_env:
                raise ValueError(
                    f"kv_quantize: family {getattr(family, '__name__', family)!r} "
                    f"has no {kvq_attr} (quantized KV support for the "
                    f"{kv_layout} layout)"
                )
            container.logger.warn(
                f"ENGINE_KV_QUANTIZE ignored for family "
                f"{getattr(family, '__name__', family)!r} (no {kvq_attr})"
            )
            kv_quantize = ""
        # disaggregated serving (ENGINE_ROLE, docs/serving.md): a prefill
        # worker ships finished prompts' KV pages to a decode worker over
        # the handoff channel; "both" (the default) is colocated serving,
        # byte-identical to the pre-role engine.
        role = str(kw.pop("role", conf.get_or_default("ENGINE_ROLE", "both")) or "both")
        handoff_target = kw.pop(
            "handoff_target", conf.get_or_default("HANDOFF_TARGET", "")) or None
        handoff_listen = kw.pop(
            "handoff_listen", conf.get_or_default("HANDOFF_LISTEN", "")) or None
        handoff_timeout = float(kw.pop(
            "handoff_timeout_s", conf.get_float("HANDOFF_TIMEOUT_S", 5.0)))
        # GOFR-HANDOFF2 streaming pipeline knobs (docs/serving.md):
        # HANDOFF_STREAMS=0 pins the exporter to HANDOFF1 blob framing
        handoff_streams = int(kw.pop(
            "handoff_streams", conf.get_int("HANDOFF_STREAMS", 2)))
        handoff_chunk_pages = int(kw.pop(
            "handoff_chunk_pages", conf.get_int("HANDOFF_CHUNK_PAGES", 4)))
        handoff_pace = float(kw.pop(
            "handoff_pace_mbps", conf.get_float("HANDOFF_PACE_MBPS", 0.0)))
        return GenerateEngine(
            family, cfg, params, container,
            slots=int(kw.pop("slots", conf.get_int("ENGINE_SLOTS", 8))),
            max_len=int(kw.pop("max_len", conf.get_int("ENGINE_MAX_LEN", 2048))),
            decode_chunk=int(kw.pop("decode_chunk", conf.get_int("ENGINE_DECODE_CHUNK", 8))),
            max_prefill_batch=int(kw.pop("max_prefill_batch", conf.get_int("ENGINE_PREFILL_BATCH", 4))),
            kv_layout=kv_layout,
            page_size=int(kw.pop("page_size", conf.get_int("ENGINE_PAGE_SIZE", 128))),
            total_pages=int(kw.pop("total_pages", conf.get_int("ENGINE_TOTAL_PAGES", 0))) or None,
            paged_kv_write=str(kw.pop("paged_kv_write",
                                      conf.get_or_default("ENGINE_PAGED_KV_WRITE", ""))),
            seed=seed,
            prefix_cache=prefix_cache,
            prefix_host_mb=float(kw.pop("prefix_host_mb",
                                        conf.get_float("ENGINE_PREFIX_HOST_MB", 0.0))),
            spec_tokens=spec_tokens,
            kv_quantize=kv_quantize,
            kv_shard=str(kw.pop("kv_shard",
                                conf.get_or_default("ENGINE_KV_SHARD", "auto"))),
            prefill_attn_fn=prefill_attn,
            prefill_attn_divisor=sp_size if prefill_attn is not None else 1,
            lockstep_role=lockstep_role,
            # unified pipeline depth: ENGINE_PIPELINE is canonical; the
            # pre-unification ENGINE_DECODE_PIPELINE spelling (and the
            # decode_pipeline kwarg) keep working as aliases
            pipeline_depth=int(kw.pop("pipeline_depth", kw.pop(
                "decode_pipeline",
                conf.get_int("ENGINE_PIPELINE", 0)
                or conf.get_int("ENGINE_DECODE_PIPELINE", 2)))),
            eos_token_id=eos,
            tokenizer=tokenizer,
            default_timeout=default_timeout,
            role=role,
            handoff_target=handoff_target,
            handoff_listen=handoff_listen,
            handoff_timeout_s=handoff_timeout,
            handoff_streams=handoff_streams,
            handoff_chunk_pages=handoff_chunk_pages,
            handoff_pace_mbps=handoff_pace,
            # multi-LoRA adapter plane (gofr_tpu.adapters, docs/serving.md):
            # off by default — both spellings disabled keeps the engine
            # byte-identical to the pre-adapter build
            adapter_slots=int(kw.pop("adapter_slots",
                                     conf.get_int("ADAPTER_SLOTS", 0))),
            adapter_rank=int(kw.pop("adapter_rank",
                                    conf.get_int("ADAPTER_RANK", 16))),
            adapter_pool_mb=float(kw.pop("adapter_pool_mb",
                                         conf.get_float("ADAPTER_POOL_MB", 0.0))),
            adapter_host_mb=float(kw.pop("adapter_host_mb",
                                         conf.get_float("ADAPTER_HOST_MB", 256.0))),
            adapter_hotswap_dir=kw.pop(
                "adapter_hotswap_dir",
                conf.get_or_default("ADAPTER_HOTSWAP_DIR", "")) or None,
            adapter_hotswap_poll_s=float(kw.pop(
                "adapter_hotswap_poll_s",
                conf.get_float("ADAPTER_HOTSWAP_POLL_S", 5.0))),
            # quality plane (metrics/quality.py): rate 0 (the default)
            # never constructs the plane — bit-identical off path
            quality_shadow_rate=float(kw.pop(
                "quality_shadow_rate",
                conf.get_float("QUALITY_SHADOW_RATE", 0.0))),
            quality_seed=kw.pop(
                "quality_seed",
                conf.get_int("QUALITY_SEED", -1)),
            quality_max_pending=int(kw.pop(
                "quality_max_pending",
                conf.get_int("QUALITY_MAX_PENDING", 16))),
            quality_max_tokens=int(kw.pop(
                "quality_max_tokens",
                conf.get_int("QUALITY_MAX_TOKENS", 64))),
            quality_top1_min=float(kw.pop(
                "quality_top1_min",
                conf.get_float("QUALITY_TOP1_MIN", 0.9))),
            quality_kl_max=float(kw.pop(
                "quality_kl_max",
                conf.get_float("QUALITY_KL_MAX", 1.0))),
            quality_recent=int(kw.pop(
                "quality_recent",
                conf.get_int("QUALITY_RECENT", 32))),
            # online step controller (gofr_tpu.control): off by default —
            # CONTROL_ENABLE=0 never constructs it (bit-identical off path)
            control_enable=bool(kw.pop(
                "control_enable", conf.get_int("CONTROL_ENABLE", 0))),
            **kw,
        )

    max_batch = int(kw.pop("max_batch", conf.get_int("ENGINE_MAX_BATCH", 32)))
    wait_ms = float(kw.pop("max_wait_ms", conf.get_float("ENGINE_MAX_WAIT_MS", 2.0)))

    if spec.task == "embed":
        def encode(inputs):
            if isinstance(inputs, str):
                if tokenizer is None:
                    raise ValueError("string input but no tokenizer on the embed engine")
                return np.asarray(tokenizer.encode(inputs), np.int32)
            return np.asarray(inputs, np.int32)

        def apply(tokens, lengths):
            return family.embed_pooled(cfg, params, tokens, lengths)

        return BatchEngine(
            apply, container, encode_fn=encode, max_batch=max_batch,
            max_wait_ms=wait_ms, default_timeout=default_timeout, **kw,
        )

    if spec.task == "classify":
        def apply_images(images):
            return family.forward(cfg, params, images)

        return BatchEngine(
            apply_images, container,
            encode_fn=lambda x: np.asarray(x, np.float32),
            max_batch=max_batch, max_wait_ms=wait_ms,
            default_timeout=default_timeout, **kw,
        )

    raise ValueError(f"unknown task {spec.task!r}; use generate|embed|classify")
