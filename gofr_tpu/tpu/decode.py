"""Decode dispatch + unified pipeline processing for ``GenerateEngine``.

Split out of tpu/engine.py (the engine's device thread calls these once
per loop iteration). The interface to the engine is its documented state:
slot table + page bookkeeping under ``eng._state_lock``, the compiled
program handles from tpu/programs.py, the UNIFIED in-flight device queue
``eng._dq`` with the device-resident carries (``eng._prev_last`` for
plain decode, ``eng._spec_carry`` for speculative rounds), and the
emit/finish callbacks.

Every asynchronous device call rides ``eng._dq``: plain decode chunks and
speculative rounds on BOTH layouts (dispatched here), plus batched and
chunked prefills (dispatched by ``engine._admit``/``_advance_chunked``).
``process_decode`` dequeues the OLDEST entry, blocks on its readback —
overlapping every younger dispatch's compute — and folds the result into
slot state. Decode can pipeline because the data-dependent state (token,
hlen, token history) is device-resident — the host never needs chunk
t-1's output to assemble chunk t; prefill can because the prompt is
host-known. Paged spec used to be the one synchronous discipline left
(page allocation depended on acceptance counts the host only learned at
readback); ``dispatch_spec_paged`` breaks that dependency by OVER-
CLAIMING pages for the worst-case accepted span at dispatch time and
releasing the rejected surplus at fold time (``_fold_spec`` →
``engine._trim_lane_pages``), so paged spec rounds overlap prefill
chunks and other in-flight work exactly like the slot layout's.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from gofr_tpu.http.errors import RequestTimeout
from gofr_tpu.tpu.lockstep import TAG_DECODE, TAG_SPEC


def _fold_spec(eng, toks, accs, meta, k, g, dev_s: float = 0.0) -> None:
    """Replay one spec round's device acceptance into slot state. Caller
    holds the state lock. ``toks`` [k, n, g+1], ``accs`` [k, n]. ``g`` is
    the round length AT DISPATCH (from the entry's signature): the step
    controller may move ``eng.spec_tokens`` between dispatch and fold,
    and this round's proposal accounting belongs to the g that priced
    and shaped it."""
    now = time.monotonic()
    emitted = accepted = folded = trimmed = 0
    for i, s in meta:
        if eng.slots[i] is not s:
            continue  # freed/preempted/reassigned while in flight
        s.inflight = max(0, s.inflight - 1)
        if s.request.cancelled or s.request.expired(now):
            eng._free_slot(i)
            s.request.complete(error=RequestTimeout())
            continue
        folded += 1
        # per-request acceptance, mirroring the aggregate convention
        # (full-round proposed even when EOS cuts the fold short; accepted
        # credited per round BEFORE its tokens emit, so _maybe_finish —
        # which may complete the request mid-loop — reads counters that
        # include the finishing round). Surfaces as the spec.accept_rate
        # span attribute and flight-recorder field.
        kw = s.request.kw
        if dev_s:
            kw["_dev_decode_s"] = kw.get("_dev_decode_s", 0.0) + dev_s
        kw["_spec_proposed"] = kw.get("_spec_proposed", 0) + k * g
        for kk in range(k):
            a = int(accs[kk, i])
            accepted += a
            kw["_spec_accepted"] = kw.get("_spec_accepted", 0) + a
            for j in range(a + 1):
                tok = int(toks[kk, i, j])
                s.pos += 1
                s.last_token = tok
                s.generated.append(tok)
                emitted += 1
                eng._emit(s, tok)
                eng._maybe_finish(i)
                if eng.slots[i] is not s:  # EOS/budget: rest discarded
                    break
            if eng.slots[i] is not s:
                break
        if (eng.kv_layout == "paged" and eng.slots[i] is s
                and s.inflight == 0):
            # release the over-claim's rejected surplus — safe only with
            # no round in flight for this lane: an in-flight dispatch's
            # table snapshot may write to any page claimed at its
            # dispatch (dispatch_spec_paged over-claims for the
            # worst-case accepted span)
            trimmed += eng._trim_lane_pages(i, s, max(s.pos - 1, 0))
    eng.metrics.increment_counter("app_tpu_tokens_total", emitted)
    # proposed counts only lanes whose acceptance was folded — a lane
    # discarded mid-flight (freed/preempted/cancelled) contributes to
    # neither side, keeping accepted/proposed a true acceptance rate
    eng.metrics.increment_counter(
        "app_tpu_spec_proposed", k * g * folded)
    eng.metrics.increment_counter("app_tpu_spec_accepted", accepted)
    # over-claim policy waste, metered where it happens: pages claimed at
    # dispatch for drafts the fold rejected, and the rejected tokens
    # themselves — target flops spent without tokens emitted
    if trimmed:
        eng.metrics.increment_counter(
            "app_tpu_spec_pages_trimmed_total", trimmed)
    rejected = k * g * folded - accepted
    if rejected > 0:
        eng.metrics.increment_counter(
            "app_tpu_spec_tokens_rejected_total", rejected)


def dispatch_spec_paged(eng) -> bool:
    """Assemble and asynchronously dispatch one PAGED-layout speculative
    round onto the unified in-flight queue — the paged twin of
    ``dispatch_spec``, with the same ``[token, hlen, use_host, temps,
    step]`` carry arbitration plus the block-table rows (packed
    ``[5 + Wp, n]``; tpu/programs.py docstring). Token history lives in
    the cache pytree (kv, hist); prefill seeded it, the spec program
    maintains it — the old synchronous round shipped O(Hcap) history per
    lane per round.

    What used to force paged spec synchronous was page allocation: the
    host only learns acceptance counts at readback. This dispatcher
    breaks the dependency by OVER-CLAIMING — every dispatch grows the
    lane's table to cover its worst case, ``pos + chunk_span *
    (inflight + 1) - 1`` (each un-folded in-flight round may advance pos
    by a full chunk_span) — and the fold releases the rejected surplus
    once the lane has no round in flight (``_fold_spec`` →
    ``engine._trim_lane_pages``). Lanes whose worst-case position
    reaches max_total are masked until their in-flight rounds process,
    the same single-chunk_span cache-slack bound plain pipelined decode
    relies on."""
    with eng._state_lock:
        n = eng.num_slots
        k = eng.decode_chunk
        span = eng._chunk_span
        Wp = eng.pages_per_slot
        Hcap = Wp * eng.page_size
        lanes = []
        for i in eng._active():
            s = eng.slots[i]
            if s.pos + span * s.inflight >= s.max_total:
                continue  # masked until in-flight rounds process
            lanes.append((i, s))
        if not lanes:
            return False
        # claim pages covering the full worst case NOW (the device
        # cannot allocate mid-chunk, and the fold that would refine the
        # estimate hasn't happened yet — that's the point)
        for i, s in list(lanes):
            eng._alloc_lane_pages(i, s, s.pos + span * (s.inflight + 1) - 1)
        lanes = [(i, s) for i, s in lanes if eng.slots[i] is s]
        if not lanes:
            return True  # preemption work happened
        # ae: one extra packed row carrying each lane's adapter pool slot
        # (row 5; zero = base). OFF keeps the layout byte-identical to the
        # pre-adapter engine (tpu/programs.py documents both).
        ae = 1 if eng._adapters_enabled else 0
        packed = eng._staging("spec", (5 + ae + Wp, n))
        packed[1, :] = Hcap + 1  # inactive: every hist/cache write lands OOB
        packed[2, :] = 1         # inactive lanes are host-arbitrated
        temps = np.zeros((n,), np.float32)
        packed[5 + ae:] = eng._masked_table({i for i, _ in lanes}).T
        for i, s in lanes:
            if s.inflight == 0:
                # host knows this lane's exact (token, hlen) — it just
                # (re)joined from prefill or a fully-processed round
                packed[0, i] = s.last_token
                packed[1, i] = s.pos + 1
            else:
                packed[2, i] = 0  # device carry owns (token, hlen)
            if ae:
                packed[5, i] = s.adapter_slot
            temps[i] = float(s.request.kw.get("temperature", 0.0))
        packed[3] = temps.view(np.int32)
        eng._step_count += 1
        packed[4, 0] = eng._step_count
        for _, s in lanes:
            s.inflight += 1
        occupancy = len(lanes) / n
        # perf-plane history floor: pages the attention stream can touch
        # this round (the tables snapshotted above), in positions
        hist = sum(len(eng._slot_pages[i]) for i, _ in lanes) * eng.page_size
        t0 = time.monotonic()

    eng._announce(TAG_SPEC, packed.shape[0], 1, packed)  # b=1: live, carry applies
    carry = eng._spec_carry
    if carry is None:
        carry = (jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
    toks_dev, accs_dev, eng.cache, eng._spec_carry = eng._spec_chunk_fn(
        eng.params, eng._base_key, eng.cache, k, jnp.asarray(packed), carry,
        *((eng._adapter_args(),) if ae else ()))
    pstep = (eng.perf.step_spec(len(lanes), k, eng.spec_tokens, hist, t0)
             if eng.perf is not None else None)
    eng._dq.append(("spec", (toks_dev, accs_dev), [(i, s) for i, s in lanes],
                    t0, occupancy, ("decode_spec", n, k, eng.spec_tokens),
                    pstep))
    return True


def dispatch_spec(eng) -> bool:
    """Assemble and asynchronously dispatch one SLOT-layout speculative
    round. The host ships only [5, n]: per-lane (token, hlen, use_host,
    temperature) plus the rng step — never history, never logits.
    A lane with a round already in flight is driven by the device-
    resident spec carry (use_host=0); its worst-case advance is
    chunk_span per in-flight round, so lanes whose worst-case position
    reaches max_total are masked until their in-flight rounds process —
    which bounds any round's writes to max_total + chunk_span, the same
    single-chunk_span cache slack plain decode uses (engine ctor
    comment). Token history lives in the cache pytree
    (kv, hist); prefill seeded it, the spec program maintains it."""
    with eng._state_lock:
        n = eng.num_slots
        k = eng.decode_chunk
        span = eng._chunk_span
        lanes = []
        for i in eng._active():
            s = eng.slots[i]
            if s.pos + span * s.inflight >= s.max_total:
                continue  # masked until in-flight rounds process
            lanes.append((i, s))
        if not lanes:
            return False
        ae = 1 if eng._adapters_enabled else 0  # row 5: adapter pool slots
        packed = eng._staging("spec", (5 + ae, n))
        packed[1, :] = eng._cache_len + 1  # inactive: every write lands OOB
        packed[2, :] = 1                   # inactive lanes are host-arbitrated
        temps = np.zeros((n,), np.float32)
        for i, s in lanes:
            if s.inflight == 0:
                # host knows this lane's exact (token, hlen) — it just
                # (re)joined from prefill or a fully-processed round
                packed[0, i] = s.last_token
                packed[1, i] = s.pos + 1
            else:
                packed[2, i] = 0  # device carry owns (token, hlen)
            if ae:
                packed[5, i] = s.adapter_slot
            temps[i] = float(s.request.kw.get("temperature", 0.0))
        packed[3] = temps.view(np.int32)
        eng._step_count += 1
        packed[4, 0] = eng._step_count
        for _, s in lanes:
            s.inflight += 1
        occupancy = len(lanes) / n
        # perf-plane history floor: worst-case positions this round's
        # attention streams per lane (device carry may be ahead of pos)
        hist = sum(min(s.pos + span * s.inflight + 1, s.max_total)
                   for _, s in lanes)
        t0 = time.monotonic()

    eng._announce(TAG_SPEC, packed.shape[0], 1, packed)  # b=1: live, carry applies
    carry = eng._spec_carry
    if carry is None:
        carry = (jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))
    toks_dev, accs_dev, eng.cache, eng._spec_carry = eng._spec_chunk_fn(
        eng.params, eng._base_key, eng.cache, k, jnp.asarray(packed), carry,
        *((eng._adapter_args(),) if ae else ()))
    pstep = (eng.perf.step_spec(len(lanes), k, eng.spec_tokens, hist, t0)
             if eng.perf is not None else None)
    eng._dq.append(("spec", (toks_dev, accs_dev), [(i, s) for i, s in lanes],
                    t0, occupancy, ("decode_spec", n, k, eng.spec_tokens),
                    pstep))
    return True


def dispatch_decode(eng) -> bool:
    """Assemble and asynchronously dispatch one decode chunk. Positions
    are SPECULATIVE: a lane with a chunk already in flight decodes from
    ``pos + k*inflight`` and takes its input token from the on-device
    ``prev_last`` carry rather than the host (which hasn't read that
    chunk back yet). Lanes guaranteed dead once their in-flight chunk is
    processed (speculative pos >= max_total) are masked out, so writes
    never exceed the existing decode_chunk cache slack. Returns True when
    a chunk was dispatched."""
    with eng._state_lock:
        n = eng.num_slots
        k = eng.decode_chunk

        # (slot index, slot, speculative position) for lanes that decode
        lanes = []
        for i in eng._active():
            s = eng.slots[i]
            p = s.pos + k * s.inflight
            if p >= s.max_total:
                continue  # will be freed when its in-flight chunk processes
            lanes.append((i, s, p))
        if not lanes:
            return False

        if eng.kv_layout == "paged":
            # every decoding lane must own pages covering this chunk's
            # writes (p .. p+k-1) BEFORE the table snapshot
            for i, s, p in list(lanes):
                eng._alloc_lane_pages(i, s, p + k - 1)
            lanes = [(i, s, p) for i, s, p in lanes if eng.slots[i] is s]
            if not lanes:
                return False

        # always the FULL chunk — one compiled decode program for the whole
        # serving lifetime. A slot that hits its budget/EOS mid-chunk simply
        # has its surplus tokens discarded (the cache carries decode_chunk
        # slack past max_len, so overshoot writes stay in bounds; paged
        # slots' tables carry the same slack via pages_per_slot). All host
        # inputs ride ONE packed array (layout at the jit definitions).
        wt = eng.pages_per_slot if eng.kv_layout == "paged" else 0
        ae = 1 if eng._adapters_enabled else 0  # row 5: adapter pool slots
        packed = eng._staging("decode", (5 + ae + wt, n))
        temps = np.zeros((n,), np.float32)
        if eng.kv_layout != "paged":
            # non-decoding rows (empty, chunk-prefilling, or dead-lane-
            # masked) write at an out-of-bounds position so the masked-
            # select append drops them — a position-0 write would corrupt
            # a prefilling slot's first token (paged masks via OOB table
            # rows instead)
            packed[1, :] = eng._cache_len
        for i, s, p in lanes:
            if s.inflight == 0:
                # host knows this lane's exact last token (from prefill or
                # its last processed chunk); otherwise the device carry
                # from the in-flight chunk supplies it (use_host stays 0)
                packed[0, i] = s.last_token
                packed[4, i] = 1
            packed[1, i] = p
            if ae:
                packed[5, i] = s.adapter_slot
            temps[i] = float(s.request.kw.get("temperature", 0.0))
        packed[2] = temps.view(np.int32)
        eng._step_count += 1
        packed[3, 0] = eng._step_count
        if eng.kv_layout == "paged":
            packed[5 + ae:] = eng._masked_table({i for i, _, _ in lanes}).T

        for _, s, _ in lanes:
            s.inflight += 1
        occupancy = len(lanes) / n
        # perf-plane history floor: positions (slot) / pages-touched
        # (paged) this chunk's attention streams, from dispatch shapes
        if eng.kv_layout == "paged":
            hist = sum(len(eng._slot_pages[i])
                       for i, _, _ in lanes) * eng.page_size
        else:
            hist = sum(p + 1 for _, _, p in lanes)
        t0 = time.monotonic()

    eng._announce(TAG_DECODE, 1, 0, packed)  # a=1: live, carry applies
    prev = eng._prev_last
    if prev is None:
        prev = jnp.zeros((n,), jnp.int32)
    chunk_dev, last_dev, eng.cache = eng._decode_chunk(
        eng.params, eng._base_key, eng.cache, k, jnp.asarray(packed), prev,
        *((eng._adapter_args(),) if ae else ())
    )
    eng._prev_last = last_dev
    pstep = (eng.perf.step_decode(len(lanes), k, hist, t0)
             if eng.perf is not None else None)
    eng._dq.append(("plain", chunk_dev, [(i, s) for i, s, _ in lanes],
                    t0, occupancy, ("decode", n, k), pstep))
    return True


def process_decode(eng) -> bool:
    """Block on the OLDEST dispatched entry's readback (overlapping any
    younger dispatch's compute) and fold it into slot state. Lanes whose
    slot object changed since dispatch (freed, preempted, reassigned)
    have their results discarded — the identity check is what makes
    dispatch-time claiming safe. Handles every entry kind on ``eng._dq``:
    plain decode, spec rounds, batched prefill, prefill chunks, and
    prefix-cache host→device page swap-ins."""
    if not eng._dq:
        return False
    kind, dev, meta, t0, occupancy, sig, pstep = eng._dq.popleft()
    if kind == "spec":
        toks = np.asarray(dev[0])  # [k, n, g+1] int32 — tokens, never logits
        accs = np.asarray(dev[1])  # [k, n]
    else:
        chunk = np.asarray(dev)  # int32 tokens, never logits
    if pstep is not None:
        # the result just landed on the host: everything from here on is
        # fold time, not device time (perf plane separates the two)
        pstep.t_ready = time.monotonic()
    if eng._poisoned:
        # stop() declared this thread wedged and already failed/cleared
        # everything; the slot/page state now belongs to the caller.
        return False
    if kind == "swapin":
        # chunk is the upload's completion marker (already read back above,
        # i.e. the host→device page copy has landed); fold is bookkeeping
        eng._fold_swapin(meta, t0, occupancy, sig, pstep)
        return True
    if kind == "prefill":
        eng._fold_prefill(chunk, meta, t0, occupancy, sig, pstep)
        return True
    if kind == "chunk":
        eng._fold_chunk(chunk, meta, t0, occupancy, sig, pstep)
        return True
    n, k = sig[1], sig[2]
    with eng._state_lock:
        # per-adapter attribution covers DISPATCHED lanes — a lane freed
        # while in flight still had device time spent on its behalf
        ads = ([s.adapter_id or "base" for _, s in meta]
               if eng._adapters_enabled else None)
        if kind == "spec":
            # sig[3] is the round length g AT DISPATCH — the live
            # eng.spec_tokens may already be a different (controller-
            # moved) value by the time this round folds
            dev_s = eng._record_step(
                "decode_spec", time.monotonic() - t0, occupancy,
                sig, pstep, adapter_ids=ads)
            _fold_spec(eng, toks, accs, meta, k, sig[3], dev_s)
            return True
        dev_s = eng._record_step("decode", time.monotonic() - t0, occupancy,
                                 ("decode", n, k), pstep, adapter_ids=ads)

        now = time.monotonic()
        accepted = 0
        for i, s in meta:
            if eng.slots[i] is not s:
                continue  # freed/preempted/reassigned while in flight
            s.inflight -= 1
            if dev_s:
                kw = s.request.kw
                kw["_dev_decode_s"] = kw.get("_dev_decode_s", 0.0) + dev_s
            if s.request.cancelled or s.request.expired(now):
                # slot invalidation: free the lane; in-flight work is discarded
                eng._free_slot(i)
                s.request.complete(error=RequestTimeout())
                continue
            for j in range(k):
                tok = int(chunk[i, j])
                s.pos += 1
                s.last_token = tok
                s.generated.append(tok)
                accepted += 1
                eng._emit(s, tok)
                eng._maybe_finish(i)
                if eng.slots[i] is not s:  # EOS/length mid-chunk: rest discarded
                    break
        eng.metrics.increment_counter("app_tpu_tokens_total", accepted)
        return True
