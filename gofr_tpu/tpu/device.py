"""TPU device datasource.

Wraps the visible accelerator devices plus the configured mesh the way the
reference wraps a connection pool (SQL: `datasource/sql/sql.go:37-89` —
lazy connect, pushed pool gauges, health check). Config keys:

    TPU_MESH            mesh topology, e.g. "dp:2,tp:4" (default: all on dp)
    TPU_DEVICES         cap the number of devices used (default: all)
    JAX_COORDINATOR     host:port of process 0 → multi-host (DCN) mode:
                        ``jax.distributed.initialize`` runs before any device
                        access and the mesh spans the GLOBAL device set
                        (SURVEY §5.8; the reference's backend-by-config
                        switch, container.go:95-122)
    JAX_NUM_PROCESSES   total processes in the job (with JAX_COORDINATOR)
    JAX_PROCESS_ID      this process's index (with JAX_COORDINATOR)

Everything degrades gracefully on CPU (the virtual test mesh) — memory
stats are best-effort because the CPU PJRT client doesn't report them.
"""

from __future__ import annotations

import threading
from typing import Any

import jax

from gofr_tpu.parallel import ShardingRules, mesh_from_config


def _maybe_init_distributed(config, logger) -> bool:
    """Config-gated multi-host bring-up. Unset coordinator ⇒ single-process
    (the 'unset host ⇒ feature off' rule every datasource follows). Must run
    before the first device touch in the process; `jax.distributed` raises
    if already initialized, which we treat as wired."""
    coordinator = config.get("JAX_COORDINATOR")
    if not coordinator:
        return False
    num_processes = config.get_int("JAX_NUM_PROCESSES", 1)
    process_id = config.get_int("JAX_PROCESS_ID", 0)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        logger.infof(
            "jax.distributed initialized: process %d/%d via %s",
            process_id, num_processes, coordinator,
        )
        return True
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise


class TPUDevices:
    def __init__(self, config, logger, metrics):
        self.config = config
        self.logger = logger
        self.metrics = metrics
        self._lock = threading.Lock()

        self.distributed = _maybe_init_distributed(config, logger)
        limit = config.get_int("TPU_DEVICES", 0)
        # multi-host: the mesh MUST span the global device set so pjit
        # programs agree across processes; local-only work uses local_devices
        devices = jax.devices()
        self.local_devices = jax.local_devices() if self.distributed else devices
        self.devices = devices[:limit] if limit > 0 else devices
        self.platform = self.devices[0].platform if self.devices else "none"
        self.mesh = mesh_from_config(config, devices=self.devices)
        self.rules = ShardingRules()
        self._compiles = 0

        metrics.set_gauge("app_tpu_device_count", len(self.devices))
        self._push_memory_gauges()
        logger.infof(
            "TPU datasource: %d %s device(s), mesh %s",
            len(self.devices), self.platform,
            dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
        )

    # -- stats -----------------------------------------------------------------

    def memory_stats(self) -> dict[str, dict[str, int]]:
        """Per-device HBM stats (empty entries where the backend doesn't
        report them, e.g. CPU). Multi-host: only this process's devices are
        addressable, so gauges cover the local slice."""
        stats: dict[str, dict[str, int]] = {}
        local = [d for d in self.devices if d in self.local_devices] or self.devices
        for d in local:
            try:
                s = d.memory_stats() or {}
            except Exception:  # noqa: BLE001
                s = {}
            stats[str(d.id)] = {
                "bytes_in_use": int(s.get("bytes_in_use", 0)),
                "bytes_limit": int(s.get("bytes_limit", 0)),
            }
        return stats

    def _push_memory_gauges(self) -> None:
        for dev_id, s in self.memory_stats().items():
            self.metrics.set_gauge("app_tpu_hbm_used_bytes", s["bytes_in_use"], device=dev_id)
            self.metrics.set_gauge("app_tpu_hbm_limit_bytes", s["bytes_limit"], device=dev_id)

    def record_compile(self) -> None:
        """Engines call this when a (shape-bucket) program compiles for the
        first time — the compile-cache-miss signal of the north star."""
        with self._lock:
            self._compiles += 1
        self.metrics.increment_counter("app_tpu_compile_total", 1)

    @property
    def compile_count(self) -> int:
        return self._compiles

    # -- health (container/health.go parity) -----------------------------------

    def health_check(self) -> dict[str, Any]:
        try:
            n = len(self.devices)
            if n == 0:
                return {"status": "DOWN", "details": {"error": "no devices visible"}}
            self._push_memory_gauges()
            return {
                "status": "UP",
                "details": {
                    "platform": self.platform,
                    "devices": n,
                    "mesh": {k: int(v) for k, v in zip(self.mesh.axis_names, self.mesh.devices.shape)},
                    "memory": self.memory_stats(),
                },
            }
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"error": str(e)}}
