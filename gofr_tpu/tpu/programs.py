"""Jitted packed-program builders for ``GenerateEngine``.

Every serving step ships its host inputs as ONE packed int32 array (floats
bitcast, RNG step folded in on device from the resident base key). Over a
tunneled device each separate H2D transfer and out-of-jit RNG op costs a
round trip (~70ms measured on the round-3 tunnel); packing turns 4-6 of
them into one. This module holds the compiled-program side of that
contract; the engine (tpu/engine.py) packs the host side.

Packed layouts (W = 1 slot-id column for the slot layout; for paged,
pages_per_slot block-table columns, plus ONE trailing slot-id column when
speculative decoding is on — the prefill programs need the lane index to
seed the device-resident history rows):

- Prefill ``[nb, lb + W + 3]``:
  ``[:, :lb]`` tokens | ``[:, lb]`` lengths | ``[:, lb+1:lb+1+W]`` rows
  | ``[:, lb+1+W]`` temps (f32 bitcast) | ``[0, lb+2+W]`` rng step.
  Chunked prefill adds an offsets column before temps.
- Decode ``[5 + W_t, n]`` (W_t = pages_per_slot table rows for paged, 0
  for slot): ``[0]`` tokens | ``[1]`` positions | ``[2]`` temps | ``[3,0]``
  rng step | ``[4]`` use_host flags | ``[5:]`` table.T. Row 4 arbitrates
  the input token per lane: 1 = take the host's packed token (lane just
  (re)joined decode); 0 = take the on-device ``prev_last`` carry from the
  previous dispatched chunk (lane has a chunk in flight the host hasn't
  read back yet).
- Spec (slot) ``[5, n]``: ``[0]`` input token | ``[1]`` history length
  (the input token is hist[hlen-1], its KV goes to position hlen-1)
  | ``[2]`` use_host flags — same arbitration as decode row 4, against a
  device-resident ``(token, hlen)`` carry, which is what lets spec rounds
  ride the pipelined dispatch queue | ``[3]`` temps (f32 bitcast)
  | ``[4, 0]`` rng step. The token HISTORY itself never leaves the
  device: with spec on, the slot cache is the pytree ``(kv, hist)`` and
  the prefill programs write each admitted prompt (plus its sampled
  first token) into ``hist`` rows on device, so the host never re-ships
  O(pos) history per round. Inactive lanes ship use_host=1 with
  hlen = H + 1: every cache/history write lands out of bounds and drops.
- Spec (paged) ``[5 + Wp, n]``: ``[0]`` input token | ``[1]`` history
  length | ``[2]`` use_host flags | ``[3]`` temps (f32 bitcast)
  | ``[4, 0]`` rng step | ``[5:]`` table.T — the SAME carry arbitration
  and (kv, hist) cache pytree as the slot layout, so paged spec rounds
  ride the pipelined dispatch queue too (pages are over-claimed at
  dispatch for the worst-case accepted span; tpu/decode.py). Inactive
  lanes ship use_host=1, hlen = Hcap + 1 AND an all-OOB table row, so
  every cache/history write drops. History never rides the wire in
  either layout.

With multi-LoRA adapters on (``build_programs(adapters=True)``), every
layout grows the per-lane adapter-pool slot id ``sel``: prefill packs one
extra column between the offsets (if chunked) and temps columns, and the
decode/spec packs insert a ``sel`` row at ``[5]`` (the block table moves
to ``[6:]``). Each program then takes a trailing ``ad = (a, b, scale)``
pool-array argument (dynamic, like ``params`` — uploads and live
hot-swap never recompile). With adapters off, layouts and traces are
byte-identical to the above.

Backend resolution is a TRACE-time property of these programs: the decode
attention ops inside them resolve ``backend="auto"`` when a program first
traces (warmup), consulting the engine's pinned autotune decisions
(ops/autotune.decision_scope, entered via ``engine._trace_scope``). A
compiled program keeps whatever backend its trace resolved for its whole
life — re-tuning means a new process, same as the KV write lowerings.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.ops.sampling import sample_token, truncate_logits


def speculative_sample(key, p_logits, drafts, temps, q_logits=None,
                       top_k=0, top_p=1.0):
    """Distribution-exact speculative sampling for one verify step
    (Leviathan/Chen rejection scheme): accept draft j with probability
    min(1, p_j(d_j)/q_j(d_j)) while the prefix holds, then sample the
    correction from norm((p_acc − q_acc)+) — or, on full acceptance, the
    bonus token from p_g. Each emitted token is distributed EXACTLY as a
    plain sampled decode at the same position; rows with temperature <= 0
    reduce bit-exactly to greedy (p collapses to the argmax one-hot, so
    acceptance == argmax-match and the correction == the argmax).

    ``p_logits`` [n, g+1, V] target logits; ``drafts`` [n, g] proposals;
    ``temps`` [n]; ``q_logits`` [n, g, V] draft-model logits, or None for
    DETERMINISTIC proposals (prompt-lookup: q is the one-hot at the
    proposal, so the accept test is u < p(d) and the residual is p with
    the rejected token zeroed).

    ``top_k``/``top_p`` (static) truncate p AND q with the IDENTICAL mask
    `ops.sampling.truncate_logits` applies in plain decode — making each
    emitted token exact w.r.t. the truncated target distribution (the
    same distribution plain truncated sampling serves). The draft's
    proposals must be sampled with the same truncation (the spec program
    routes them through sample_token with these settings).

    Returns ``(out [n, g+1] int32, acc [n] int32)``: ``out[:, :acc]`` are
    the accepted drafts, ``out[:, acc]`` the correction/bonus; entries
    past ``acc`` are garbage the caller discards. Exposed at module level
    so the distribution guarantee is testable directly (test_spec_decode).
    """
    n, gp1, vocab = p_logits.shape
    g = gp1 - 1
    greedy_rows = (temps <= 0)[:, None, None]
    temp = jnp.maximum(temps, 1e-6)[:, None, None]
    p = jax.nn.softmax(
        truncate_logits(p_logits.astype(jnp.float32) / temp, top_k, top_p),
        axis=-1)
    p = jnp.where(
        greedy_rows,
        jax.nn.one_hot(jnp.argmax(p_logits, -1), vocab, dtype=jnp.float32),
        p,
    )
    if q_logits is None:
        q_d = jnp.ones((n, g), jnp.float32)
    else:
        q = jax.nn.softmax(
            truncate_logits(q_logits.astype(jnp.float32) / temp, top_k, top_p),
            axis=-1)
        q = jnp.where(
            greedy_rows,
            jax.nn.one_hot(jnp.argmax(q_logits, -1), vocab, dtype=jnp.float32),
            q,
        )
        q_d = jnp.take_along_axis(q, drafts[..., None], axis=-1)[..., 0]
    p_d = jnp.take_along_axis(p[:, :g], drafts[..., None], axis=-1)[..., 0]
    ku, kr = jax.random.split(key)
    u = jax.random.uniform(ku, (n, g))
    ok = (u * q_d < p_d).astype(jnp.int32)
    ok = jnp.cumprod(ok, axis=1)
    acc = ok.sum(axis=1)  # leading accepted drafts per lane, 0..g
    p_sel = jnp.take_along_axis(p, acc[:, None, None], axis=1)[:, 0]  # [n, V]
    if q_logits is None:
        d_at = jnp.take_along_axis(
            drafts, jnp.minimum(acc, g - 1)[:, None], axis=1)[:, 0]
        q_sel = jnp.where((acc < g)[:, None],
                          jax.nn.one_hot(d_at, vocab, dtype=jnp.float32), 0.0)
    else:
        q_pad = jnp.concatenate([q, jnp.zeros((n, 1, vocab), q.dtype)], axis=1)
        q_sel = jnp.take_along_axis(q_pad, acc[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(p_sel - q_sel, 0.0)
    rs = resid.sum(-1, keepdims=True)
    # p == q at the rejection point is a zero residual only when the
    # rejection had probability zero — sampling p there is equivalent
    resid = jnp.where(rs > 0, resid, p_sel)
    corr = jax.random.categorical(
        kr, jnp.log(jnp.maximum(resid, 1e-38)), axis=-1).astype(jnp.int32)
    out = jnp.concatenate([drafts, jnp.zeros((n, 1), jnp.int32)], axis=1)
    out = out.at[jnp.arange(n), acc].set(corr)
    return out, acc


def unpack_prefill(packed, w, chunked=False, adapters=False):
    extra = (1 if chunked else 0) + (1 if adapters else 0)
    lb = packed.shape[1] - (w + 3 + extra)
    tokens = packed[:, :lb]
    lengths = packed[:, lb]
    rows = packed[:, lb + 1:lb + 1 + w]
    offsets = packed[:, lb + 1 + w] if chunked else None
    # adapter-pool slot id per row, between offsets and temps (0 = base;
    # padding rows pack 0, whose delta is exactly zero — ops/lora.py)
    sel = (packed[:, lb + 1 + w + (1 if chunked else 0)]
           if adapters else None)
    temps = jax.lax.bitcast_convert_type(
        packed[:, lb + 1 + w + extra], jnp.float32)
    step = packed[0, lb + 2 + w + extra]
    return tokens, lengths, rows, offsets, temps, step, sel


@dataclass
class Programs:
    """Compiled-program handles the engine (and lockstep followers) call.

    ``chunk_prefill`` is None when the layout/family has no chunked-prefill
    support; ``spec_chunk`` is None unless speculative decoding is on.
    """

    prefill_sample: Any
    chunk_prefill: Any | None
    decode_chunk: Any
    spec_chunk: Any | None


def build_programs(
    family: Any,
    cfg: Any,
    *,
    kv_layout: str,
    spec_tokens: int,
    top_k: int,
    top_p: float,
    pages_per_slot: int = 0,
    page_size: int = 0,
    cache_len: int = 0,
    prefill_attn_fn: Any = None,
    draft: Any = None,
    adapters: bool = False,
) -> Programs:
    """``draft`` (slot layout + spec only) is a ``(family, cfg)`` pair for a
    DRAFT MODEL: instead of prompt-lookup, each spec round runs
    ``spec_tokens`` autoregressive draft-model decode steps on device, then
    the one target verify forward. With a draft, ``params`` to every program
    is the pytree ``{"t": target_params, "d": draft_params}``, and the
    engine cache is ``(kv, draft_kv)`` — the draft's slot KV cache replaces
    the token-history buffer (the draft needs no history, killing the
    history writes too). Verification is unchanged, so outputs stay
    bit-identical to plain greedy decode regardless of draft quality — the
    draft only moves the acceptance rate."""
    if adapters and not getattr(family, "SUPPORTS_ADAPTERS", False):
        raise ValueError(
            f"model family {family.__name__!r} has no adapter support "
            "(SUPPORTS_ADAPTERS); disable ADAPTER_* or use a family whose "
            "serving entry points accept the adapters kwarg")

    # With ``adapters`` on, every program takes a trailing ``ad = (a, b,
    # scale)`` — the device adapter-pool arrays (adapters.AdapterPool),
    # DYNAMIC jit args like ``params`` so uploads/evictions/hot-swap never
    # recompile — and the packed layouts grow the per-lane pool slot id
    # ``sel``: one prefill column before temps, and row [5] of the
    # decode/spec packs (the block table moves to [6:]). With it off
    # (default), layouts, signatures, and traces are EXACTLY the
    # pre-adapter ones — the adapter_id=None bit-exactness contract.
    def _akw(sel, ad):
        return {"adapters": (sel, ad[0], ad[1], ad[2])} if adapters else {}

    ts = (top_k, top_p)
    Wp = pages_per_slot
    # paged + spec adds one trailing slot-id column after the block-table
    # columns: hist rows are indexed by LANE, and the paged layout's packed
    # prefill otherwise carries only page ids (module docstring)
    W = (Wp + (1 if spec_tokens else 0)) if kv_layout == "paged" else 1
    # whole-prompt prefill attention override (e.g. ring/Ulysses
    # sequence-parallel attention on an sp mesh — build_engine wires it);
    # chunked prefill keeps the gathered-view attention either way
    pf = {"attn_fn": prefill_attn_fn} if prefill_attn_fn is not None else {}
    chunk_prefill = None
    spec_chunk = None

    if kv_layout == "paged":
        # With spec on, the paged cache is the same 2-tuple pytree the
        # slot layout uses: (kv, hist) — prefill seeds hist rows on
        # device, the spec program maintains them, and no program input
        # ever carries token history (the old paged spec shipped
        # O(Hcap) history rows per round).
        tuple_cache = bool(spec_tokens)

        def _split(cache):
            return cache if tuple_cache else (cache, None)

        def _join(kv, hist):
            return (kv, hist) if tuple_cache else kv

        def _seed_hist(hist, srows, tokens, lengths, toks, offsets=None):
            """Write an admitted prompt chunk (and its sampled token) into
            the device history. OOB lane ids (padding rows) drop. On
            non-final chunks the sampled-token write at offset+length is
            garbage the NEXT chunk overwrites — final state is always
            (prompt .. first sampled token)."""
            lb = tokens.shape[1]
            base = offsets if offsets is not None else jnp.zeros_like(lengths)
            cols = base[:, None] + jnp.arange(lb)[None, :]
            hist = hist.at[srows[:, None], cols].set(tokens, mode="drop")
            return hist.at[srows, base + lengths].set(toks, mode="drop")

        @partial(jax.jit, donate_argnums=(2,))
        def _prefill_sample(params, base_key, cache, packed, ad=None):
            kv, hist = _split(cache)
            tokens, lengths, rows, _, temps, step, sel = unpack_prefill(
                packed, W, adapters=adapters)
            key = jax.random.fold_in(base_key, step)
            logits, kv = family.prefill_paged(
                cfg, params, tokens, lengths, kv, rows[:, :Wp], **pf,
                **_akw(sel, ad))
            toks = sample_token(logits, key, temperature=temps, top_k=ts[0], top_p=ts[1])
            if tuple_cache:
                hist = _seed_hist(hist, rows[:, Wp], tokens, lengths, toks)
            return toks, _join(kv, hist)

        @partial(jax.jit, donate_argnums=(2,))
        def _chunk_prefill(params, base_key, cache, packed, ad=None):
            kv, hist = _split(cache)
            tokens, lengths, rows, offsets, temps, step, sel = unpack_prefill(
                packed, W, chunked=True, adapters=adapters)
            key = jax.random.fold_in(base_key, step)
            logits, kv = family.prefill_paged(
                cfg, params, tokens, lengths, kv, rows[:, :Wp], offsets,
                **_akw(sel, ad)
            )
            toks = sample_token(logits, key, temperature=temps, top_k=ts[0], top_p=ts[1])
            if tuple_cache:
                hist = _seed_hist(hist, rows[:, Wp], tokens, lengths, toks,
                                  offsets)
            return toks, _join(kv, hist)

        chunk_prefill = _chunk_prefill

        @partial(jax.jit, static_argnums=(3,), donate_argnums=(2,))
        def _decode_chunk(params, base_key, cache, steps, packed, prev_last,
                          ad=None):
            kv, hist = _split(cache)
            tokens = jnp.where(packed[4] != 0, packed[0], prev_last)
            positions = packed[1]
            temps = jax.lax.bitcast_convert_type(packed[2], jnp.float32)
            key = jax.random.fold_in(base_key, packed[3, 0])
            sel = packed[5] if adapters else None
            table = packed[6:].T if adapters else packed[5:].T

            def body(carry, _):
                toks, pos, kv, key = carry
                logits, kv = family.decode_step_paged(
                    cfg, params, toks, pos, kv, table, **_akw(sel, ad))
                key, sub = jax.random.split(key)
                nxt = sample_token(logits, sub, temperature=temps, top_k=ts[0], top_p=ts[1])
                return (nxt, pos + 1, kv, key), nxt

            (toks, pos, kv, key), out = jax.lax.scan(
                body, (tokens, positions, kv, key), None, length=steps
            )
            return out.T, toks, _join(kv, hist)  # [slots, K], [slots] carry

        if spec_tokens:
            g = spec_tokens
            Hcap = Wp * page_size  # logical per-slot capacity

            @partial(jax.jit, static_argnums=(3,), donate_argnums=(2, 5))
            def _spec_chunk(params, base_key, cache, steps, packed, carry,
                            ad=None):
                kv, hist0 = cache
                n_l = packed.shape[1]
                use_host = packed[2] != 0
                tok0 = jnp.where(use_host, packed[0], carry[0])
                hlen0 = jnp.where(use_host, packed[1], carry[1])
                temps = jax.lax.bitcast_convert_type(packed[3], jnp.float32)
                key0 = jax.random.fold_in(base_key, packed[4, 0])
                sel = packed[5] if adapters else None
                table = (packed[6:] if adapters else packed[5:]).T  # [n, Wp]
                idx = jnp.arange(Hcap)

                def outer(loop, _):
                    tok, hlen, hist, kv, key = loop
                    key, ks = jax.random.split(key)
                    pos = hlen - 1
                    # prompt-lookup draft: continuation after the most
                    # recent EARLIER occurrence of the current token
                    # (a DETERMINISTIC proposal — one-hot q)
                    match = (hist == tok[:, None]) & (idx[None, :] < pos[:, None])
                    j = jnp.where(match, idx[None, :], -1).max(axis=1)
                    take = jnp.clip(j[:, None] + 1 + jnp.arange(g)[None, :], 0, Hcap - 1)
                    drafts = jnp.take_along_axis(hist, take, axis=1)
                    seq = jnp.concatenate([tok[:, None], drafts], axis=1)
                    logits, kv = family.verify_step_paged(
                        cfg, params, seq, pos, kv, table, **_akw(sel, ad))
                    out, acc = speculative_sample(ks, logits, drafts, temps,
                                                  None, ts[0], ts[1])
                    nxt = jnp.take_along_axis(out, acc[:, None], axis=1)[:, 0]
                    emit = jnp.arange(g + 1)[None, :] <= acc[:, None]
                    wpos = jnp.where(emit, hlen[:, None] + jnp.arange(g + 1)[None, :], Hcap)
                    hist = hist.at[jnp.arange(n_l)[:, None], wpos].set(out, mode="drop")
                    return (nxt, hlen + acc + 1, hist, kv, key), (out, acc)

                (tok_f, hlen_f, hist, kv, _), (toks, accs) = jax.lax.scan(
                    outer, (tok0, hlen0, hist0, kv, key0), None, length=steps
                )
                # [K, n, g+1], [K, n], cache, next-round (token, hlen) carry
                return toks, accs, (kv, hist), (tok_f, hlen_f)

            spec_chunk = _spec_chunk
    else:
        # With spec on, the engine's cache is a 2-tuple pytree: (kv, hist)
        # for prompt-lookup — the prefill programs seed hist rows on device
        # and the spec program maintains them, so no program input ever
        # carries token history — or (kv, draft_kv) with a draft model.
        tuple_cache = bool(spec_tokens)
        dfamily, dcfg = draft if draft is not None else (None, None)

        def _tparams(params):
            return params["t"] if draft is not None else params

        def _split(cache):
            return cache if tuple_cache else (cache, None)

        def _join(kv, aux):
            return (kv, aux) if tuple_cache else kv

        def _seed_hist(hist, rows, tokens, lengths, toks, offsets=None):
            """Write an admitted prompt chunk (and its sampled token) into
            the device history. OOB rows (padding: slot id == num_slots)
            drop. On non-final chunks the sampled-token write at
            offset+length is garbage the NEXT chunk overwrites — final
            state is always (prompt .. first sampled token)."""
            lb = tokens.shape[1]
            base = offsets if offsets is not None else jnp.zeros_like(lengths)
            cols = base[:, None] + jnp.arange(lb)[None, :]
            hist = hist.at[rows[:, None], cols].set(tokens, mode="drop")
            return hist.at[rows, base + lengths].set(toks, mode="drop")

        def _seed_aux(params, aux, rows, tokens, lengths, toks, offsets=None):
            """Bring the spec sidecar state up to date with an admitted
            prompt: prefill the draft model's KV cache over the same
            tokens, or seed the prompt-lookup history rows."""
            if draft is None:
                return _seed_hist(aux, rows, tokens, lengths, toks, offsets)
            if offsets is None:
                _, aux = dfamily.prefill(
                    dcfg, params["d"], tokens, lengths, aux, rows)
            else:
                _, aux = dfamily.prefill(
                    dcfg, params["d"], tokens, lengths, aux, rows, offsets)
            return aux

        @partial(jax.jit, donate_argnums=(2,))
        def _prefill_sample(params, base_key, cache, packed, ad=None):
            kv, aux = _split(cache)
            tokens, lengths, rows, _, temps, step, sel = unpack_prefill(
                packed, W, adapters=adapters)
            key = jax.random.fold_in(base_key, step)
            logits, kv = family.prefill(
                cfg, _tparams(params), tokens, lengths, kv, rows[:, 0], **pf,
                **_akw(sel, ad))
            toks = sample_token(logits, key, temperature=temps, top_k=ts[0], top_p=ts[1])
            if tuple_cache:
                aux = _seed_aux(params, aux, rows[:, 0], tokens, lengths, toks)
            return toks, _join(kv, aux)

        if getattr(family, "SLOT_CHUNKED_PREFILL", False):
            @partial(jax.jit, donate_argnums=(2,))
            def _chunk_prefill(params, base_key, cache, packed, ad=None):
                kv, aux = _split(cache)
                tokens, lengths, rows, offsets, temps, step, sel = unpack_prefill(
                    packed, W, chunked=True, adapters=adapters)
                key = jax.random.fold_in(base_key, step)
                logits, kv = family.prefill(
                    cfg, _tparams(params), tokens, lengths, kv, rows[:, 0],
                    offsets, **_akw(sel, ad)
                )
                toks = sample_token(logits, key, temperature=temps, top_k=ts[0], top_p=ts[1])
                if tuple_cache:
                    aux = _seed_aux(params, aux, rows[:, 0], tokens, lengths,
                                    toks, offsets)
                return toks, _join(kv, aux)

            chunk_prefill = _chunk_prefill

        @partial(jax.jit, static_argnums=(3,), donate_argnums=(2,))
        def _decode_chunk(params, base_key, cache, steps, packed, prev_last,
                          ad=None):
            kv, aux = _split(cache)
            tokens = jnp.where(packed[4] != 0, packed[0], prev_last)
            positions = packed[1]
            temps = jax.lax.bitcast_convert_type(packed[2], jnp.float32)
            key = jax.random.fold_in(base_key, packed[3, 0])
            sel = packed[5] if adapters else None

            def body(carry, _):
                toks, pos, kv, key = carry
                logits, kv = family.decode_step(
                    cfg, _tparams(params), toks, pos, kv, **_akw(sel, ad))
                key, sub = jax.random.split(key)
                nxt = sample_token(logits, sub, temperature=temps, top_k=ts[0], top_p=ts[1])
                return (nxt, pos + 1, kv, key), nxt

            (toks, pos, kv, key), out = jax.lax.scan(
                body, (tokens, positions, kv, key), None, length=steps
            )
            return out.T, toks, _join(kv, aux)  # [slots, K], [slots] carry

        if spec_tokens:
            g = spec_tokens
            H = cache_len

            @partial(jax.jit, static_argnums=(3,), donate_argnums=(2, 5))
            def _spec_chunk(params, base_key, cache, steps, packed, carry,
                            ad=None):
                kv, aux0 = cache
                n_l = packed.shape[1]
                use_host = packed[2] != 0
                tok0 = jnp.where(use_host, packed[0], carry[0])
                hlen0 = jnp.where(use_host, packed[1], carry[1])
                temps = jax.lax.bitcast_convert_type(packed[3], jnp.float32)
                key0 = jax.random.fold_in(base_key, packed[4, 0])
                sel = packed[5] if adapters else None
                idx = jnp.arange(H)

                def outer(loop, _):
                    tok, hlen, aux, kv, key = loop
                    key, kd, ks = jax.random.split(key, 3)
                    pos = hlen - 1
                    q_logits = None
                    if draft is None:
                        # prompt-lookup draft: continuation after the most
                        # recent EARLIER occurrence of the current token
                        # (a DETERMINISTIC proposal — one-hot q)
                        match = (aux == tok[:, None]) & (idx[None, :] < pos[:, None])
                        j = jnp.where(match, idx[None, :], -1).max(axis=1)  # -1 = miss
                        take = jnp.clip(j[:, None] + 1 + jnp.arange(g)[None, :], 0, H - 1)
                        drafts = jnp.take_along_axis(aux, take, axis=1)  # [n, g]
                    else:
                        # draft-model proposal: g+1 autoregressive steps of
                        # the (tiny) draft, its KV cache riding in aux,
                        # SAMPLED at each lane's temperature (greedy rows
                        # decode greedily — sample_token semantics). g+1,
                        # not g: the extra step's OUTPUT is discarded but
                        # its input write puts the g-th draft's KV at
                        # pos+g — without it, a fully-accepted round would
                        # leave a PERMANENT hole there (the next round
                        # starts writing at pos+g+1) and acceptance would
                        # silently decay with generation length, worst in
                        # the high-acceptance regime the draft exists for.
                        def dstep(c, _):
                            dtok, dpos, dkv, dkey = c
                            dlogits, dkv = dfamily.decode_step(
                                dcfg, params["d"], dtok, dpos, dkv)
                            dkey, dsub = jax.random.split(dkey)
                            nxt_d = sample_token(dlogits, dsub, temperature=temps,
                                                 top_k=ts[0], top_p=ts[1])
                            return (nxt_d, dpos + 1, dkv, dkey), (nxt_d, dlogits)

                        (_, _, aux, _), (drafts_t, dlogits_t) = jax.lax.scan(
                            dstep, (tok, pos, aux, kd), None, length=g + 1)
                        drafts = drafts_t[:g].T            # [n, g]
                        q_logits = dlogits_t[:g].swapaxes(0, 1)  # [n, g, V]
                    seq = jnp.concatenate([tok[:, None], drafts], axis=1)
                    logits, kv = family.verify_step(
                        cfg, _tparams(params), seq, pos, kv, **_akw(sel, ad))
                    out, acc = speculative_sample(ks, logits, drafts, temps,
                                                  q_logits, ts[0], ts[1])
                    nxt = jnp.take_along_axis(out, acc[:, None], axis=1)[:, 0]
                    if draft is None:
                        emit = jnp.arange(g + 1)[None, :] <= acc[:, None]
                        wpos = jnp.where(emit, hlen[:, None] + jnp.arange(g + 1)[None, :], H)
                        aux = aux.at[jnp.arange(n_l)[:, None], wpos].set(
                            out, mode="drop")
                    return (nxt, hlen + acc + 1, aux, kv, key), (out, acc)

                (tok_f, hlen_f, aux, kv, _), (toks, accs) = jax.lax.scan(
                    outer, (tok0, hlen0, aux0, kv, key0), None, length=steps
                )
                # [K, n, g+1], [K, n], cache, next-round (token, hlen) carry
                return toks, accs, (kv, aux), (tok_f, hlen_f)

            spec_chunk = _spec_chunk

    return Programs(
        prefill_sample=_prefill_sample,
        chunk_prefill=chunk_prefill,
        decode_chunk=_decode_chunk,
        spec_chunk=spec_chunk,
    )
