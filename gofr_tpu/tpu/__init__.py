"""TPU runtime: device datasource + continuous-batching serving engines.

The device mesh is a *datasource* (``container.tpu``) exactly parallel to
how the reference wraps a Redis pool (`container/container.go:91`):
config-gated, lazily created, health-checked, metered. The engines replace
the reference's goroutine-per-request hot path (SURVEY.md §3.2) with
enqueue → batch → device-step.
"""

from gofr_tpu.tpu.device import TPUDevices

__all__ = ["TPUDevices"]
