"""Prefill→decode paged-KV handoff for disaggregated serving (ISSUE 12;
streaming pipeline ISSUE 18).

Role-split engines (``ENGINE_ROLE`` — tpu/engine.py) separate the two
phases continuous batching otherwise interleaves on one device: a
*prefill* worker runs prompt prefill and ships the resulting full KV
pages here; a *decode* worker imports them as HOST-tier prefix-cache
nodes (tpu/prefix.py ``insert_host``), so the next admission of that
prompt gets a prefix hit and the page upload rides the existing
``swapin`` kind on the unified in-flight queue ``_dq`` — the transfer
overlaps live decode steps instead of stalling them.

Two wire modes share one JOIN (``_MAGIC`` + hello + int32 ACK), and the
ACK **is** the version negotiation:

- **GOFR-HANDOFF1 (blob)**: the original protocol. After ``ACK_OK``,
  each transfer is ONE frame ``<i meta_nbytes><meta JSON><payload>``
  carrying every page of the prompt — sent only after the whole prefill
  finished, so at production prompt lengths transfer serializes behind
  compute on both edges.
- **GOFR-HANDOFF2 (streaming)**: the hello adds ``version: 2`` and
  ``streams: N``; a v2 server answers ``ACK_OK_STREAM`` and the exporter
  opens ``HANDOFF_STREAMS`` parallel connections. Each transfer becomes
  page-granular *chunks* (``begin`` / ``pages`` / ``end`` / ``abort``,
  same ``<i meta_nbytes><meta JSON><payload>`` framing) shipped WHILE
  later chunks of the same prompt are still prefilling: the engine's
  chunk fold stages already-written pages (tpu/engine.py
  ``_stream_handoff_chunk``), the exporter reads them back outside every
  engine lock and writes them as zero-repack scatter-gather memoryviews
  (``fleet.channel.sendmsg_all``) round-robined across the streams.
  Per-stream ordering is TCP's; cross-stream order is reconstructed from
  ``start_page``, and the importer registers each newly *contiguous*
  page prefix incrementally — an in-flight prompt is claimable on the
  decode side up to its landed prefix before the transfer even ends.
  A HANDOFF1 peer answers the same hello with plain ``ACK_OK`` and the
  exporter negotiates DOWN: pages accumulate and ship as one blob frame
  at activation, token-exact across an in-place fleet upgrade.

JOIN gates are identical in both modes: the hello names the exporter's
KV pool dtype (``bf16`` | ``int8`` | ``int4`` — a page payload quantized
for one pool layout is garbage in another), the adapter-set digest, and
the base-weight epoch; a mismatch is rejected at JOIN with a distinct
ACK code before any multi-MB payload moves. Both sides inherit
``MAX_FRAME_BYTES`` so a corrupt length can never silently OOM the
importer.

Failure contract (the PR 10 deadline plane): every chunk send and the
final ACK wait are bounded by ``min(handoff_timeout_s, request deadline
remaining)``; a stuck or severed transfer — at ANY chunk boundary —
completes the request with a 504 (``where="handoff"``). The prefill
side's pages were retained by its own prefix cache BEFORE export and the
decode side registers only refcount-free host payloads (a partial import
is simply a shorter valid prefix chain), so a transfer severed at ANY
byte leaks zero pool pages on either side
(``assert_page_refs_consistent``). Chaos points: ``kv.handoff``
(transfer-granular, both ends), ``kv.handoff.hello`` (JOIN, both ends),
``kv.handoff.chunk`` (chunk-granular, both ends), ``kv.handoff.midchunk``
(export side, tears the vectored write inside one chunk) —
docs/testing.md.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
import time

import numpy as np

from gofr_tpu.fleet import chaos
from gofr_tpu.fleet.channel import MAX_FRAME_BYTES, sendmsg_all
from gofr_tpu.http.errors import DeadlineExceeded

_MAGIC = b"GOFR-HANDOFF1\n"
_I32 = struct.Struct("<i")

# GOFR-HANDOFF2: the version rides the JOIN hello and the ACK picks the
# framing — the magic stays HANDOFF1 so both protocol generations share
# one JOIN code path (and one set of dtype/adapter/epoch gates)
PROTOCOL_VERSION = 2

ACK_OK = 0  # JOIN accepted, HANDOFF1 blob frames on this connection
ACK_REJECTED = 1
ACK_DTYPE_MISMATCH = 2
# adapter-era JOIN gates: the P/D split must agree on WHICH adapters
# exist (a decode worker resolving an adapter the prefill side never
# loaded would serve the wrong weights) and on the base-weight epoch (a
# hot-swap landing on one side only would mix weights across one
# request). Both fields are optional in the hello — absent means a
# pre-adapter peer, which gates on neither (wildcard), preserving
# rolling-upgrade compatibility.
ACK_ADAPTER_MISMATCH = 3
ACK_EPOCH_MISMATCH = 4
ACK_OK_STREAM = 5  # JOIN accepted, HANDOFF2 chunk frames on this connection
# mesh-sharding JOIN gate: exported page payloads are LOGICAL (full-head)
# rows either way, but a tp-degree mismatch means the two sides compiled
# different decode programs over different per-device pool planes — the
# import side's swap-in and byte accounting would silently disagree with
# what the prefill side priced. Optional in the hello like the adapter
# gates: absent means a pre-sharding peer (wildcard, implicitly tp=1).
ACK_SHARD_MISMATCH = 6

# the JOIN hello is a few dozen bytes of JSON; anything bigger is not ours
_MAX_HELLO_BYTES = 4096

# the streaming import keeps per-transfer reassembly state across stream
# connections; bound it so a crashed exporter's orphans can't accumulate
_MAX_SESSIONS = 64


def engine_kv_dtype(engine) -> str:
    """The engine's KV pool dtype as it rides the wire: the canonical
    ENGINE_KV_DTYPE spelling ('' quantize means the dense bf16 pool)."""
    return getattr(engine, "kv_quantize", "") or "bf16"


class HandoffClosed(ConnectionError):
    """The peer went away mid-frame (sever, crash, chaos drop)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes (same discipline as fleet/channel.py — a
    short read mid-frame is a protocol error, not a retry)."""
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise HandoffClosed(f"handoff peer closed mid-read ({len(buf)}/{n} bytes)")
        buf.extend(part)
    return bytes(buf)


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, reaching into ml_dtypes for the accelerator
    dtypes numpy itself doesn't know (bfloat16 — jax always ships it)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_frame(toks: np.ndarray, payloads: list[tuple], nbytes_page: int,
                 kv_dtype: str = "") -> bytes:
    """One HANDOFF1 blob frame: meta-length + meta JSON + concatenated
    plane bytes. ``payloads`` holds one tuple of HOST numpy planes per
    full page, in chain order (the caller already read the device buffers
    back). ``kv_dtype`` tags the pool layout the planes were quantized
    for."""
    planes = [{"dtype": str(a.dtype), "shape": list(a.shape)} for a in payloads[0]]
    meta = json.dumps({
        "toks": np.asarray(toks, np.int64).tolist(),
        "n_pages": len(payloads),
        "nbytes_page": int(nbytes_page),
        "kv_dtype": str(kv_dtype),
        "planes": planes,
    }).encode("utf-8")
    parts = [_I32.pack(len(meta)), meta]
    for page in payloads:
        for a in page:
            parts.append(np.ascontiguousarray(a).tobytes())
    frame = b"".join(parts)
    if len(frame) > MAX_FRAME_BYTES:
        raise ValueError(
            f"handoff: refusing to send a {len(frame)}-byte frame "
            f"(cap {MAX_FRAME_BYTES}); {len(payloads)} pages")
    return frame


def decode_frame(sock: socket.socket) -> tuple[np.ndarray, list[tuple], int, str]:
    """Read one HANDOFF1 blob frame off ``sock``: (prompt tokens, per-page
    plane tuples, nbytes_page, kv_dtype tag — "" from a pre-tag peer).
    Raises HandoffClosed on sever, ValueError on a frame that lies about
    its size."""
    (meta_len,) = _I32.unpack(_recv_exact(sock, _I32.size))
    if not 0 < meta_len <= MAX_FRAME_BYTES:
        raise ValueError(f"handoff: frame advertises {meta_len} meta bytes — corrupt stream")
    meta = json.loads(_recv_exact(sock, meta_len).decode("utf-8"))
    toks = np.asarray(meta["toks"], np.int32)
    n_pages = int(meta["n_pages"])
    payloads = _recv_planes(sock, meta["planes"], n_pages)
    return toks, payloads, int(meta["nbytes_page"]), str(meta.get("kv_dtype", ""))


def _recv_planes(sock: socket.socket, planes: list, n_pages: int) -> list[tuple]:
    """Read ``n_pages`` pages' plane payloads as self-described by the
    frame/chunk meta — shared by the blob and streaming decoders, with
    the same loud size cap."""
    dtypes = [_np_dtype(p["dtype"]) for p in planes]
    shapes = [tuple(int(d) for d in p["shape"]) for p in planes]
    per_page = sum(int(np.prod(sh)) * dt.itemsize for sh, dt in zip(shapes, dtypes))
    if not 0 < n_pages * per_page <= MAX_FRAME_BYTES:
        raise ValueError(
            f"handoff: frame advertises {n_pages} pages x {per_page} bytes "
            f"(cap {MAX_FRAME_BYTES}) — corrupt stream")
    payloads: list[tuple] = []
    for _ in range(n_pages):
        page = []
        for sh, dt in zip(shapes, dtypes):
            raw = _recv_exact(sock, int(np.prod(sh)) * dt.itemsize)
            page.append(np.frombuffer(raw, dtype=dt).reshape(sh).copy())
        payloads.append(tuple(page))
    return payloads


def _byte_view(a: np.ndarray) -> memoryview:
    """A flat uint8 memoryview over an array's bytes WITHOUT copying —
    the accelerator dtypes (ml_dtypes bfloat16 et al) don't speak the
    buffer protocol directly, but a uint8 reinterpret of the same memory
    does."""
    a = np.ascontiguousarray(a)
    return memoryview(a.view(np.uint8).reshape(-1))


def chunk_parts(meta: dict, payload_parts=()) -> list:
    """One HANDOFF2 chunk as a scatter-gather buffer list —
    ``<i meta_nbytes> <meta JSON> <payload>`` where the payload rides as
    memoryviews over the original arrays (``sendmsg_all`` writes them
    without a repack copy). ``meta["kind"]`` is begin|pages|end|abort;
    ``pages`` metas are self-describing (``planes``) so a chunk is
    parseable on any stream before its transfer's ``begin`` arrived."""
    meta_b = json.dumps(meta).encode("utf-8")
    return [_I32.pack(len(meta_b)), meta_b, *payload_parts]


def read_chunk(sock: socket.socket) -> tuple[dict, list[tuple], int]:
    """Read one HANDOFF2 chunk: (meta, page payloads — empty unless
    ``kind == "pages"`` —, payload byte count)."""
    (meta_len,) = _I32.unpack(_recv_exact(sock, _I32.size))
    if not 0 < meta_len <= MAX_FRAME_BYTES:
        raise ValueError(
            f"handoff: chunk advertises {meta_len} meta bytes — corrupt stream")
    meta = json.loads(_recv_exact(sock, meta_len).decode("utf-8"))
    payloads: list[tuple] = []
    nbytes = 0
    if meta.get("kind") == "pages":
        n_pages = int(meta["n_pages"])
        payloads = _recv_planes(sock, meta["planes"], n_pages)
        nbytes = sum(a.nbytes for page in payloads for a in page)
    return meta, payloads, nbytes


def _register_handoff_metrics(metrics) -> None:
    """The registry's record-by-name API drops writes to unregistered
    names, so both endpoints declare the transfer metrics up front
    (idempotent: _register returns the existing metric)."""
    metrics.new_counter("app_tpu_kv_handoff_pages_total",
                        "KV pages shipped between role-split workers")
    metrics.new_counter("app_tpu_kv_handoff_bytes_total",
                        "KV handoff wire bytes (frame size, export side)")
    metrics.new_counter("app_tpu_kv_handoff_overlap_bytes_total",
                        "KV handoff bytes shipped while the slot was still "
                        "prefilling (the streaming pipeline's overlap)")
    metrics.new_gauge("app_tpu_kv_handoff_overlap_ratio",
                      "overlap bytes / total export bytes since boot "
                      "(1.0 = every byte hid behind prefill compute)")
    metrics.new_gauge("app_tpu_kv_handoff_streams",
                      "negotiated parallel handoff streams "
                      "(0 = HANDOFF1 blob mode)")
    metrics.new_histogram("app_tpu_kv_handoff_seconds",
                          "prefill-side handoff latency: activation to ACK")


class HandoffJob:
    """One staged BLOB export (HANDOFF1 / ``handoff_streams=0``):
    everything the exporter thread needs to ship a slot's prompt pages
    and settle the request, captured under the engine state lock at
    activation time. ``payloads`` are DEVICE buffers — the gathers were
    dispatched under the lock (the _evict_prefix_page discipline); the
    exporter blocks on them outside it."""

    __slots__ = ("request", "prompt_tokens", "first_token", "payloads",
                 "nbytes_page", "t0")

    def __init__(self, request, prompt_tokens, first_token, payloads,
                 nbytes_page, t0):
        self.request = request
        self.prompt_tokens = prompt_tokens
        self.first_token = first_token
        self.payloads = payloads
        self.nbytes_page = nbytes_page
        self.t0 = t0


class StreamTransfer:
    """One STREAMING export (HANDOFF2): created at the first full page of
    a still-prefilling slot (``engine._stream_handoff_chunk``) or at
    activation for batched prefills. The engine thread appends
    device-buffer page payloads in chain order (``add``, under its state
    lock — append-only, so the exporter thread reads a stable prefix
    without a lock) and flips ``finished`` at activation; the exporter
    thread owns every other field."""

    __slots__ = ("request", "prompt_tokens", "nbytes_page", "t0", "xfer",
                 "staged", "sent_pages", "sent_bytes", "overlap_bytes",
                 "first_token", "finished", "t_activate", "begun", "seq",
                 "failed", "settled")

    def __init__(self, request, prompt_tokens, nbytes_page, t0, xfer):
        self.request = request
        self.prompt_tokens = prompt_tokens
        self.nbytes_page = int(nbytes_page)
        self.t0 = t0
        self.xfer = xfer
        self.staged: list[tuple] = []  # device payloads, chain order
        self.sent_pages = 0
        self.sent_bytes = 0
        self.overlap_bytes = 0
        self.first_token: int | None = None
        self.finished = False
        self.t_activate: float | None = None
        self.begun = False
        self.seq = 0
        self.failed = False
        self.settled = False

    @property
    def staged_pages(self) -> int:
        return len(self.staged)

    def add(self, payloads: list[tuple]) -> None:
        self.staged.extend(payloads)


class HandoffExporter:
    """Prefill-side export thread: ships staged transfers to the decode
    worker's HandoffServer, lazily (re)dialing and negotiating the wire
    mode at JOIN. Transfers are strictly serial on this thread — the
    decode side imports under its state lock — but each streaming
    transfer's chunks overlap the EXPORTING engine's remaining prefill
    compute: the engine stages pages per chunk fold, this thread drains
    them while the next chunk is still on the device."""

    def __init__(self, target: str, *, engine=None, timeout_s: float = 5.0,
                 streams: int = 2, chunk_pages: int = 4,
                 pace_mbps: float = 0.0, logger=None, metrics=None):
        host, _, port = target.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout_s = max(0.1, float(timeout_s))
        self.streams = max(0, int(streams))
        self.chunk_pages = max(1, int(chunk_pages))
        # emulated egress bandwidth cap (HANDOFF_PACE_MBPS): sleep
        # nbytes/rate after each wire write. 0 = off. A bench/testing
        # knob first (it makes transfer time deterministic on loopback),
        # but also a legitimate production rate limit when the P/D pair
        # shares NICs with training traffic.
        self.pace_mbps = max(0.0, float(pace_mbps))
        self.engine = engine
        self.logger = logger
        self.metrics = metrics
        if metrics is not None:
            _register_handoff_metrics(metrics)
        self._sock: socket.socket | None = None  # blob-mode connection
        self._socks: list[socket.socket] = []    # stream-mode connections
        self._mode: str | None = None            # None until first JOIN
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._stop = threading.Event()
        self._stats = {"exported": 0, "failed": 0, "pages": 0, "bytes": 0,
                       "overlap_bytes": 0}
        self._stream_bytes: list[int] = []
        self._stream_seconds: list[float] = []
        self._xfer_seq = 0
        self._xfer_tag = f"{os.getpid():x}.{id(self) & 0xFFFFFF:x}"
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="kv-handoff-export", daemon=True)
        self._thread.start()

    # -- connection / negotiation ----------------------------------------------

    def _hello(self) -> bytes:
        """JOIN hello: kv dtype, adapter-set digest, base-weight epoch —
        plus the HANDOFF2 version/stream announcement when streaming is
        configured (a HANDOFF1 server ignores the extra keys and ACKs
        plain OK: that ACK *is* the down-negotiation)."""
        hello = {
            "kv_dtype": engine_kv_dtype(self.engine),
            "adapters": str(getattr(self.engine, "adapters_digest",
                                    lambda: "")()),
            "weights_epoch": int(getattr(self.engine, "weights_epoch", 0) or 0),
            "kv_shards": int(getattr(self.engine, "kv_shards", 1) or 1),
        }
        if self.streams > 0:
            hello["version"] = PROTOCOL_VERSION
            hello["streams"] = self.streams
        return json.dumps(hello).encode("utf-8")

    def _dial(self) -> tuple[socket.socket, int]:
        """One connection's JOIN: dial, send magic+hello, return (socket,
        ACK status) for an accepted JOIN; raise HandoffClosed (with the
        config hint) on rejection or sever."""
        s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if chaos.fire("kv.handoff.hello", side="export"):
            s.close()
            raise HandoffClosed("handoff JOIN severed (chaos kv.handoff.hello)")
        hello = self._hello()
        s.sendall(_MAGIC + _I32.pack(len(hello)) + hello)
        try:
            (status,) = _I32.unpack(_recv_exact(s, _I32.size))
        except HandoffClosed:
            s.close()
            raise
        if status not in (ACK_OK, ACK_OK_STREAM):
            s.close()
            if status == ACK_ADAPTER_MISMATCH:
                raise HandoffClosed(
                    "decode worker rejected JOIN (ACK_ADAPTER_MISMATCH): the "
                    "P/D sides disagree on the loaded adapter set (register "
                    "the same adapters — names, ranks, scales — on both)")
            if status == ACK_EPOCH_MISMATCH:
                raise HandoffClosed(
                    "decode worker rejected JOIN (ACK_EPOCH_MISMATCH): the "
                    "P/D sides are at different base-weight epochs (a live "
                    "hot-swap must land on both before pages move)")
            if status == ACK_SHARD_MISMATCH:
                raise HandoffClosed(
                    "decode worker rejected JOIN (ACK_SHARD_MISMATCH): the "
                    f"P/D sides shard the KV pool differently (local tp "
                    f"degree {int(getattr(self.engine, 'kv_shards', 1) or 1)}"
                    "; ENGINE_KV_SHARD and the mesh tp size must agree "
                    "across the split)")
            raise HandoffClosed(
                f"decode worker rejected JOIN (status {status}): "
                f"kv dtype {engine_kv_dtype(self.engine)!r} does not match the "
                "import pool (ENGINE_KV_DTYPE must agree across the P/D split)")
        return s, status

    def _negotiate(self) -> None:
        """Resolve the wire mode on first use. ACK_OK_STREAM selects the
        chunked pipeline over up to ``streams`` connections (extra-stream
        dial failures degrade to fewer streams, never fail the JOIN);
        plain ACK_OK from a HANDOFF1 peer negotiates DOWN to blob mode on
        that same connection."""
        if self._mode is not None:
            return
        s, status = self._dial()
        if status == ACK_OK_STREAM and self.streams > 0:
            socks = [s]
            for _ in range(1, self.streams):
                try:
                    s2, st2 = self._dial()
                except (OSError, HandoffClosed):
                    break
                if st2 != ACK_OK_STREAM:
                    s2.close()
                    break
                socks.append(s2)
            self._socks = socks
            self._mode = "stream"
            with self._lock:
                self._stream_bytes = [0] * len(socks)
                self._stream_seconds = [0.0] * len(socks)
            if self.metrics is not None:
                self.metrics.set_gauge("app_tpu_kv_handoff_streams", len(socks))
            if self.logger is not None:
                self.logger.infof(
                    "kv handoff: GOFR-HANDOFF2 streaming over %d stream(s)",
                    len(socks))
        else:
            self._sock = s
            self._mode = "blob"
            if self.metrics is not None:
                self.metrics.set_gauge("app_tpu_kv_handoff_streams", 0)
            if self.logger is not None and self.streams > 0:
                self.logger.warn(
                    "kv handoff: peer speaks GOFR-HANDOFF1 — negotiated down "
                    "to blob mode (transfer will not overlap prefill)")

    def _connect(self) -> socket.socket:
        """The blob-mode connection (HANDOFF1 path and negotiated-down
        HANDOFF2 transfers)."""
        self._negotiate()
        if self._mode != "blob" or self._sock is None:
            raise HandoffClosed("handoff: blob send without a blob-mode JOIN")
        return self._sock

    def _sever(self) -> None:
        for s in ([self._sock] if self._sock is not None else []) + self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._sock = None
        self._socks = []
        self._mode = None  # the next transfer re-dials and re-negotiates

    def _pace(self, nbytes: int) -> None:
        if self.pace_mbps > 0.0 and nbytes > 0:
            time.sleep(nbytes / (self.pace_mbps * 1e6))

    def _budget(self, req) -> float:
        """Per-write budget: the tighter of the handoff timeout and the
        request's remaining deadline (PR 10 plane) — enforced per CHUNK
        in streaming mode, so a mid-stream stall sheds at the chunk
        boundary instead of after the whole transfer's worth of waiting."""
        budget = self.timeout_s
        if req is not None and req.deadline is not None:
            budget = min(budget, max(0.05, req.deadline - time.monotonic()))
        return budget

    # -- engine-facing API -----------------------------------------------------

    def submit(self, job: HandoffJob) -> None:
        self._q.put(job)

    def begin_stream(self, request, prompt_tokens, nbytes_page,
                     t0: float) -> StreamTransfer:
        """Allocate a transfer handle for a (possibly still-prefilling)
        slot. Pure bookkeeping — nothing moves until ``kick``."""
        with self._lock:
            self._xfer_seq += 1
            n = self._xfer_seq
        return StreamTransfer(request, prompt_tokens, nbytes_page, t0,
                              f"{self._xfer_tag}:{n}")

    def kick(self, transfer: StreamTransfer) -> None:
        """New pages staged: wake the exporter thread to drain them."""
        self._q.put(("xfer", transfer))

    def finish(self, transfer: StreamTransfer, first_token: int,
               now: float) -> None:
        """Activation: the slot sampled its first token and was freed —
        ship the tail, send ``end``, settle on the ACK."""
        transfer.first_token = int(first_token)
        transfer.t_activate = now
        transfer.finished = True
        self._q.put(("xfer", transfer))

    def abort(self, transfer: StreamTransfer) -> None:
        """The slot died before activation (preemption, cancel): tear the
        wire state down WITHOUT touching the request — a preempted prompt
        re-enters prefill and re-streams from page 0 (the importer
        touch-skips positions it already holds)."""
        transfer.failed = True
        self._q.put(("abort", transfer))

    def known_blob(self) -> bool:
        """True once the peer negotiated down to HANDOFF1 — the engine
        skips mid-prefill staging (pages would only accumulate)."""
        return self._mode == "blob"

    # -- exporter thread -------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                break
            try:
                if isinstance(item, HandoffJob):
                    self._export(item)
                else:
                    kind, transfer = item
                    if kind == "abort":
                        self._drop(transfer)
                    else:
                        self._advance(transfer)
            except Exception as e:  # noqa: BLE001 - one bad job must not kill the thread
                if isinstance(item, HandoffJob):
                    self._fail(item, f"handoff export error: {e}")
                elif item[0] != "abort":
                    self._fail_stream(item[1], f"handoff export error: {e}")

    # -- streaming path --------------------------------------------------------

    def _advance(self, t: StreamTransfer) -> None:
        if t.failed or t.settled:
            return
        try:
            self._negotiate()
        except (OSError, HandoffClosed) as e:
            self._sever()
            self._fail_stream(t, f"handoff JOIN failed: {e}")
            return
        if self._mode == "blob":
            # negotiated down: pages accumulate on the handle and ship as
            # one HANDOFF1 frame at activation (satellite: mixed-version
            # pairs stay token-exact through an in-place upgrade)
            if t.finished:
                job = HandoffJob(t.request, t.prompt_tokens, t.first_token,
                                 list(t.staged), t.nbytes_page,
                                 t.t_activate or t.t0)
                t.settled = True  # _export settles/fails the request
                self._export(job)
            return
        try:
            self._pump(t)
        except (OSError, HandoffClosed, ValueError) as e:
            self._sever()
            self._fail_stream(t, f"handoff stream failed: {e}")

    def _pump(self, t: StreamTransfer) -> None:
        """Drain staged pages onto the streams; on the finished transfer,
        close with ``end`` and settle on the ACK."""
        req = t.request
        now = time.monotonic()
        if req.cancelled or req.expired(now):
            raise HandoffClosed("request expired mid-stream")
        if not t.begun:
            # transfer-granular chaos (the original kv.handoff drill):
            # sever before ANY chunk moves
            if chaos.fire("kv.handoff", side="export", pages=t.staged_pages):
                raise HandoffClosed("handoff transfer severed (chaos kv.handoff)")
            meta = {"v": PROTOCOL_VERSION, "kind": "begin", "xfer": t.xfer,
                    "toks": np.asarray(t.prompt_tokens, np.int64).tolist(),
                    "nbytes_page": t.nbytes_page,
                    "kv_dtype": engine_kv_dtype(self.engine)}
            t.sent_bytes += self._send_chunk(0, req, meta, ())
            t.begun = True
        while t.sent_pages < t.staged_pages:
            hi = min(t.sent_pages + self.chunk_pages, t.staged_pages)
            batch = t.staged[t.sent_pages:hi]
            # device→host readback OUTSIDE every engine lock: the gathers
            # were dispatched at the chunk fold; np.asarray blocks here,
            # overlapped with the device's next chunk
            t_rb = time.monotonic()
            host = [tuple(np.asarray(a) for a in page) for page in batch]
            plane = getattr(self.engine, "perf", None)
            if plane is not None:
                now = time.monotonic()
                flops, bytes_ = plane.model.handoff_export(len(host))
                plane.note_external("handoff_export", now - t_rb, flops,
                                    bytes_, now)
            # chunk-granular chaos: sever at this chunk boundary
            if chaos.fire("kv.handoff.chunk", side="export", seq=t.seq):
                raise HandoffClosed(
                    "handoff stream severed at a chunk boundary "
                    "(chaos kv.handoff.chunk)")
            overlap = not t.finished
            meta = {"v": PROTOCOL_VERSION, "kind": "pages", "xfer": t.xfer,
                    "seq": t.seq, "start_page": t.sent_pages,
                    "n_pages": len(host),
                    "planes": [{"dtype": str(a.dtype), "shape": list(a.shape)}
                               for a in host[0]]}
            parts = [_byte_view(a) for page in host for a in page]
            si = t.seq % max(1, len(self._socks))
            nbytes = self._send_chunk(si, req, meta, parts)
            t.sent_bytes += nbytes
            if overlap:
                t.overlap_bytes += nbytes
            t.sent_pages = hi
            t.seq += 1
        if not t.finished:
            return  # more chunks still prefilling; the next kick resumes
        meta = {"v": PROTOCOL_VERSION, "kind": "end", "xfer": t.xfer,
                "total_pages": t.sent_pages}
        t.sent_bytes += self._send_chunk(0, req, meta, ())
        s = self._socks[0]
        s.settimeout(self._budget(req))
        (status,) = _I32.unpack(_recv_exact(s, _I32.size))
        if status != ACK_OK:
            self._fail_stream(
                t, f"decode worker rejected the KV stream (status {status})")
            return
        t.settled = True
        self._settle(req, t.first_token, t.sent_pages, t.sent_bytes,
                     t.overlap_bytes, t.t_activate or t.t0)

    def _send_chunk(self, si: int, req, meta: dict, parts) -> int:
        """One bounded vectored chunk write on stream ``si``; returns the
        bytes written. Prices the wire time into the perf plane as
        off-device-thread work (never moves the ``_dq`` bubble floor)."""
        if req is not None and req.expired(time.monotonic()):
            raise HandoffClosed("request deadline exhausted mid-stream")
        s = self._socks[si]
        bufs = chunk_parts(meta, parts)
        nbytes = sum(memoryview(b).nbytes for b in bufs)
        s.settimeout(self._budget(req))
        t_w = time.monotonic()
        if chaos.fire("kv.handoff.midchunk", side="export"):
            # tear the write INSIDE the chunk: header out, payload not —
            # the importer sees a short read, the drill proves neither
            # side leaks on a torn frame
            sendmsg_all(s, bufs[:1])
            raise HandoffClosed("handoff stream severed mid-chunk "
                                "(chaos kv.handoff.midchunk)")
        sendmsg_all(s, bufs)
        dt = time.monotonic() - t_w
        self._pace(nbytes)
        with self._lock:
            if si < len(self._stream_bytes):
                self._stream_bytes[si] += nbytes
                self._stream_seconds[si] = round(
                    self._stream_seconds[si] + dt, 6)
        plane = getattr(self.engine, "perf", None)
        if plane is not None:
            now = time.monotonic()
            plane.note_external("handoff_stream", dt, 0.0, nbytes, now)
        return nbytes

    def _drop(self, t: StreamTransfer) -> None:
        """Abort a dead slot's transfer: best-effort ``abort`` chunk so
        the importer frees its reassembly session, request untouched."""
        if t.settled or not t.begun or self._mode != "stream" or not self._socks:
            return
        try:
            self._send_chunk(0, t.request,
                             {"v": PROTOCOL_VERSION, "kind": "abort",
                              "xfer": t.xfer}, ())
        except (OSError, HandoffClosed, ValueError):
            self._sever()

    def _fail_stream(self, t: StreamTransfer, why: str) -> None:
        if t.settled:
            return
        t.failed = True
        t.settled = True
        with self._lock:
            self._stats["failed"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_request_deadline_exceeded_total", 1, where="handoff")
        rt = t.request.kw.get("_rt")
        if rt is not None and t.t_activate is not None:
            rt.end("engine.handoff", error=why[:120])
        if self.logger is not None:
            self.logger.warn(f"kv handoff: {why}")
        # a transfer can die while its slot is still PREFILLING: cancel
        # cooperatively so the next chunk fold frees the slot/pages (the
        # zero-leak half), then complete — first-writer-wins makes the
        # fold's RequestTimeout a no-op
        t.request.cancel("kv handoff severed")
        t.request.complete(error=DeadlineExceeded(f"kv handoff failed: {why}"))

    # -- blob path (HANDOFF1 / negotiated-down) --------------------------------

    def _export(self, job: HandoffJob) -> None:
        req = job.request
        # device→host readback OUTSIDE every engine lock: the gathers were
        # dispatched at activation; np.asarray blocks on them here
        t_rb = time.monotonic()
        host_pages = [tuple(np.asarray(a) for a in page) for page in job.payloads]
        plane = getattr(self.engine, "perf", None)
        if plane is not None:
            # off-device-thread transfer: contributes bytes/device_s to the
            # roofline window but never moves the _dq bubble floor
            now = time.monotonic()
            flops, bytes_ = plane.model.handoff_export(len(host_pages))
            plane.note_external("handoff_export", now - t_rb, flops, bytes_, now)
        if req.cancelled or req.expired(time.monotonic()):
            self._fail(job, "request expired before KV export began")
            return
        try:
            frame = encode_frame(job.prompt_tokens, host_pages, job.nbytes_page,
                                 kv_dtype=engine_kv_dtype(self.engine))
        except ValueError as e:
            self._fail(job, str(e))
            return
        # bound the whole send+ACK by the tighter of the handoff budget and
        # the request's remaining deadline (PR 10 plane)
        budget = self._budget(req)
        # chaos kv.handoff, client side: drop = sever the connection with
        # the frame (possibly partially) unsent — no ACK ever arrives
        if chaos.fire("kv.handoff", side="export", pages=len(host_pages)):
            self._sever()
            self._fail(job, "handoff transfer severed (chaos kv.handoff)")
            return
        try:
            s = self._connect()
            s.settimeout(budget)
            s.sendall(frame)
            self._pace(len(frame))
            (status,) = _I32.unpack(_recv_exact(s, _I32.size))
        except (OSError, HandoffClosed) as e:
            self._sever()
            self._fail(job, f"handoff transfer failed: {e}")
            return
        if status != ACK_OK:
            self._fail(job, f"decode worker rejected the KV frame (status {status})")
            return
        self._settle(req, job.first_token, len(host_pages), len(frame), 0,
                     job.t0)

    # -- shared settle/fail ----------------------------------------------------

    def _settle(self, req, first_token, n_pages: int, nbytes: int,
                overlap_bytes: int, t_anchor: float) -> None:
        now = time.monotonic()
        with self._lock:
            self._stats["exported"] += 1
            self._stats["pages"] += n_pages
            self._stats["bytes"] += nbytes
            self._stats["overlap_bytes"] += overlap_bytes
            tot_b = self._stats["bytes"]
            tot_o = self._stats["overlap_bytes"]
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_tpu_kv_handoff_pages_total", n_pages, side="export")
            self.metrics.increment_counter(
                "app_tpu_kv_handoff_bytes_total", nbytes, side="export")
            if overlap_bytes:
                self.metrics.increment_counter(
                    "app_tpu_kv_handoff_overlap_bytes_total", overlap_bytes,
                    side="export")
            self.metrics.set_gauge(
                "app_tpu_kv_handoff_overlap_ratio",
                round(tot_o / tot_b, 4) if tot_b else 0.0)
            self.metrics.record_histogram(
                "app_tpu_kv_handoff_seconds", now - t_anchor)
        rt = req.kw.get("_rt")
        if rt is not None:
            rt.end("engine.handoff", pages=n_pages, bytes=nbytes,
                   overlap_bytes=overlap_bytes)
        eng = self.engine
        tokenizer = getattr(eng, "tokenizer", None) if eng is not None else None
        tokens = [int(first_token)]
        ft = req.kw.get("_first_token_at", t_anchor)
        req.complete(result={
            "tokens": tokens,
            "text": tokenizer.decode(tokens) if tokenizer is not None else None,
            "finish_reason": "handoff",
            "ttft_s": ft - req.enqueued_at,
        })

    def _fail(self, job: HandoffJob, why: str) -> None:
        with self._lock:
            self._stats["failed"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_request_deadline_exceeded_total", 1, where="handoff")
        rt = job.request.kw.get("_rt")
        if rt is not None:
            rt.end("engine.handoff", error=why[:120])
        if self.logger is not None:
            self.logger.warn(f"kv handoff: {why}")
        job.request.complete(error=DeadlineExceeded(f"kv handoff failed: {why}"))

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["stream_bytes"] = list(self._stream_bytes)
            out["stream_seconds"] = list(self._stream_seconds)
        out["mode"] = self._mode or ""
        out["streams"] = len(self._socks)
        b = out["bytes"]
        out["overlap_ratio"] = round(out["overlap_bytes"] / b, 4) if b else 0.0
        return out

    def close(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=2.0)
        self._sever()


class _ImportSession:
    """Reassembly state for one streamed transfer, shared across every
    stream connection of the exporting peer: chunks carry the transfer
    id, per-stream ordering is TCP's, cross-stream order is rebuilt from
    ``start_page``. ``done`` fires when the contiguous imported prefix
    reaches the ``end`` chunk's total (or the session fails)."""

    __slots__ = ("toks", "nbytes_page", "pages", "cursor", "total",
                 "added", "bytes", "status", "done", "lock")

    def __init__(self):
        self.toks = None
        self.nbytes_page = 0
        self.pages: dict[int, tuple] = {}
        self.cursor = 0
        self.total: int | None = None
        self.added = 0
        self.bytes = 0
        self.status = ACK_OK
        self.done = threading.Event()
        self.lock = threading.Lock()


class HandoffServer:
    """Decode-side import listener: accepts prefill workers' connections
    and registers shipped pages as host-tier prefix nodes via
    ``engine.handoff_import`` — refcount-free payloads the next prefix
    hit promotes and uploads through the normal ``swapin`` path. Speaks
    both protocol generations: a HANDOFF1 peer gets the blob frame loop,
    a HANDOFF2 peer gets chunk streaming with INCREMENTAL import — every
    newly contiguous page prefix registers immediately, so a request
    arriving mid-transfer already gets a (partial) prefix hit and its
    first decode step dispatches onto ``_dq`` as soon as early pages
    land, not after the last frame."""

    def __init__(self, engine, listen: str = "127.0.0.1:0", *,
                 logger=None, metrics=None,
                 max_version: int = PROTOCOL_VERSION):
        self.engine = engine
        self.logger = logger
        self.metrics = metrics
        # rolling-upgrade escape hatch (and the mixed-version test seam):
        # max_version=1 makes this server answer every JOIN with plain
        # ACK_OK, forcing HANDOFF1 blob mode exactly like a pre-streaming
        # build would
        self.max_version = int(max_version)
        if metrics is not None:
            _register_handoff_metrics(metrics)
        host, _, port = listen.rpartition(":")
        self.host = host or "127.0.0.1"
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, int(port)))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._stats = {"imported": 0, "rejected": 0, "pages": 0, "bytes": 0}
        self._lock = threading.Lock()
        self._conns: list[socket.socket] = []
        self._sessions: dict[str, _ImportSession] = {}
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-handoff-server", daemon=True)
        self._thread.start()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="kv-handoff-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if _recv_exact(conn, len(_MAGIC)) != _MAGIC:
                return  # not a handoff peer; drop the connection
            # JOIN hello: the peer names its KV pool dtype; reject a
            # mismatch before accepting any page frame (module docstring)
            (hlen,) = _I32.unpack(_recv_exact(conn, _I32.size))
            if not 0 < hlen <= _MAX_HELLO_BYTES:
                return  # not a handoff peer; drop the connection
            hello = json.loads(_recv_exact(conn, hlen).decode("utf-8"))
            want = engine_kv_dtype(self.engine)
            got = str(hello.get("kv_dtype", ""))
            if got != want:
                with self._lock:
                    self._stats["rejected"] += 1
                if self.logger is not None:
                    self.logger.warn(
                        f"kv handoff JOIN rejected: peer kv dtype {got!r} != "
                        f"import pool {want!r}")
                conn.sendall(_I32.pack(ACK_DTYPE_MISMATCH))
                return
            # adapter-era gates — checked ONLY when the hello carries the
            # fields (a pre-adapter peer sends neither and gates on
            # neither; see the ACK code comment)
            if "adapters" in hello:
                want_ad = str(getattr(self.engine, "adapters_digest",
                                      lambda: "")())
                got_ad = str(hello.get("adapters", ""))
                if got_ad != want_ad:
                    with self._lock:
                        self._stats["rejected"] += 1
                    if self.logger is not None:
                        self.logger.warn(
                            f"kv handoff JOIN rejected: peer adapter set "
                            f"{got_ad or '<none>'} != local "
                            f"{want_ad or '<none>'} (register identical "
                            f"adapters on both P/D sides)")
                    conn.sendall(_I32.pack(ACK_ADAPTER_MISMATCH))
                    return
            if "weights_epoch" in hello:
                want_we = int(getattr(self.engine, "weights_epoch", 0) or 0)
                got_we = int(hello.get("weights_epoch", 0) or 0)
                if got_we != want_we:
                    with self._lock:
                        self._stats["rejected"] += 1
                    if self.logger is not None:
                        self.logger.warn(
                            f"kv handoff JOIN rejected: peer base-weight "
                            f"epoch {got_we} != local {want_we} (hot-swap "
                            f"must land on both sides before pages move)")
                    conn.sendall(_I32.pack(ACK_EPOCH_MISMATCH))
                    return
            if "kv_shards" in hello:
                want_sh = int(getattr(self.engine, "kv_shards", 1) or 1)
                got_sh = int(hello.get("kv_shards", 1) or 1)
                if got_sh != want_sh:
                    with self._lock:
                        self._stats["rejected"] += 1
                    if self.logger is not None:
                        self.logger.warn(
                            f"kv handoff JOIN rejected: peer pool tp degree "
                            f"{got_sh} != local {want_sh} (ENGINE_KV_SHARD / "
                            f"mesh tp size must agree across the P/D split)")
                    conn.sendall(_I32.pack(ACK_SHARD_MISMATCH))
                    return
            # chaos kv.handoff.hello, import side: drop AFTER the gates
            # but BEFORE the ACK — the dialer's JOIN wait times out
            if chaos.fire("kv.handoff.hello", side="import"):
                return
            if (int(hello.get("version", 1) or 1) >= PROTOCOL_VERSION
                    and self.max_version >= PROTOCOL_VERSION):
                conn.sendall(_I32.pack(ACK_OK_STREAM))
                self._serve_stream(conn, want)
                return
            conn.sendall(_I32.pack(ACK_OK))
            while not self._stop.is_set():
                toks, payloads, nbytes_page, frame_dtype = decode_frame(conn)
                if frame_dtype and frame_dtype != want:
                    # JOIN said one thing, the frame says another:
                    # protocol corruption — reject, keep the connection
                    conn.sendall(_I32.pack(ACK_DTYPE_MISMATCH))
                    with self._lock:
                        self._stats["rejected"] += 1
                    continue
                # chaos kv.handoff, server side: the frame arrived but is
                # dropped BEFORE import — the exporter times out waiting
                # for an ACK that never comes (raise/delay work too)
                if chaos.fire("kv.handoff", side="import", pages=len(payloads)):
                    return
                try:
                    added = self.engine.handoff_import(toks, payloads, nbytes_page)
                    status = ACK_OK
                except Exception as e:  # noqa: BLE001 - reject, keep serving
                    added = 0
                    status = ACK_REJECTED
                    if self.logger is not None:
                        self.logger.warn(f"kv handoff import rejected: {e}")
                nbytes = len(payloads) * nbytes_page
                with self._lock:
                    if status == ACK_OK:
                        self._stats["imported"] += 1
                        self._stats["pages"] += added
                        self._stats["bytes"] += nbytes
                    else:
                        self._stats["rejected"] += 1
                if self.metrics is not None and status == ACK_OK:
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_pages_total", added, side="import")
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_bytes_total", nbytes, side="import")
                conn.sendall(_I32.pack(status))
        except (HandoffClosed, ValueError, OSError, json.JSONDecodeError):
            pass  # peer gone or corrupt stream: drop the connection
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- HANDOFF2 streaming import ---------------------------------------------

    def _session(self, xfer: str) -> _ImportSession:
        with self._lock:
            sess = self._sessions.get(xfer)
            if sess is None:
                while len(self._sessions) >= _MAX_SESSIONS:
                    # oldest-first orphan drop (dict preserves insertion
                    # order): host payloads only, nothing pool-owned
                    self._sessions.pop(next(iter(self._sessions)))
                sess = self._sessions[xfer] = _ImportSession()
            return sess

    def _ingest(self, sess: _ImportSession) -> None:
        """Advance the contiguous-prefix cursor and register every newly
        contiguous page — the INCREMENTAL import. Repeated ``insert_host``
        calls with a growing payload prefix touch-skip positions already
        registered (tpu/prefix.py), so pages become claimable the moment
        the prefix is contiguous, not at ``end``. Caller holds sess.lock."""
        if sess.toks is None or sess.status != ACK_OK:
            return
        cur = sess.cursor
        while cur in sess.pages:
            cur += 1
        if cur > sess.cursor:
            try:
                sess.added += self.engine.handoff_import(
                    sess.toks, [sess.pages[i] for i in range(cur)],
                    sess.nbytes_page)
                sess.cursor = cur
            except Exception as e:  # noqa: BLE001 - reject the transfer, keep serving
                sess.status = ACK_REJECTED
                if self.logger is not None:
                    self.logger.warn(f"kv handoff stream import rejected: {e}")
        if sess.status != ACK_OK or (sess.total is not None
                                     and sess.cursor >= sess.total):
            sess.done.set()

    def _serve_stream(self, conn: socket.socket, want: str) -> None:
        """One stream connection's chunk loop. Sessions are shared across
        the peer's streams, so a ``pages`` chunk racing ahead of its
        transfer's ``begin`` (different TCP connection) just parks in the
        reassembly dict until the tokens arrive."""
        while not self._stop.is_set():
            meta, payloads, nbytes = read_chunk(conn)
            kind = str(meta.get("kind", ""))
            xfer = str(meta.get("xfer", ""))
            if kind == "begin":
                # transfer-granular chaos (the original kv.handoff drill,
                # import side): sever before ANY page imports
                if chaos.fire("kv.handoff", side="import", pages=0):
                    return
                sess = self._session(xfer)
                with sess.lock:
                    sess.toks = np.asarray(meta["toks"], np.int32)
                    sess.nbytes_page = int(meta["nbytes_page"])
                    if str(meta.get("kv_dtype", "") or want) != want:
                        sess.status = ACK_DTYPE_MISMATCH
                    self._ingest(sess)
            elif kind == "pages":
                # chunk-granular chaos, import side: the chunk arrived
                # but is dropped before import; the connection severs
                if chaos.fire("kv.handoff.chunk", side="import",
                              seq=int(meta.get("seq", 0))):
                    return
                sess = self._session(xfer)
                with sess.lock:
                    start = int(meta["start_page"])
                    for j, page in enumerate(payloads):
                        sess.pages[start + j] = page
                    sess.bytes += nbytes
                    self._ingest(sess)
            elif kind == "end":
                sess = self._session(xfer)
                with sess.lock:
                    sess.total = int(meta["total_pages"])
                    self._ingest(sess)
                # other streams may still be draining their chunks: bound
                # the wait by the engine's own handoff budget, then answer
                # on THIS connection (the exporter's control stream)
                ok = sess.done.wait(max(
                    0.1, float(getattr(self.engine, "handoff_timeout_s", 5.0))))
                status = sess.status if ok else ACK_REJECTED
                with self._lock:
                    self._sessions.pop(xfer, None)
                    if status == ACK_OK:
                        self._stats["imported"] += 1
                        self._stats["pages"] += sess.added
                        self._stats["bytes"] += sess.bytes
                    else:
                        self._stats["rejected"] += 1
                if self.metrics is not None and status == ACK_OK:
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_pages_total", sess.added,
                        side="import")
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_bytes_total", sess.bytes,
                        side="import")
                conn.sendall(_I32.pack(status))
            elif kind == "abort":
                # exporter-side slot death (preemption/cancel): drop the
                # reassembly state; pages ALREADY registered stay — they
                # are a valid prefix of that prompt, refcount-free
                with self._lock:
                    self._sessions.pop(xfer, None)
            else:
                raise ValueError(f"handoff: unknown chunk kind {kind!r}")

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), []
            self._sessions.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)


__all__ = [
    "ACK_ADAPTER_MISMATCH", "ACK_DTYPE_MISMATCH", "ACK_EPOCH_MISMATCH",
    "ACK_OK", "ACK_OK_STREAM", "ACK_REJECTED", "ACK_SHARD_MISMATCH",
    "HandoffClosed",
    "HandoffExporter", "HandoffJob", "HandoffServer", "PROTOCOL_VERSION",
    "StreamTransfer", "chunk_parts", "decode_frame", "encode_frame",
    "engine_kv_dtype", "read_chunk",
]
