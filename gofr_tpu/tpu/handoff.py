"""Prefill→decode paged-KV handoff for disaggregated serving (ISSUE 12).

Role-split engines (``ENGINE_ROLE`` — tpu/engine.py) separate the two
phases continuous batching otherwise interleaves on one device: a
*prefill* worker runs prompt prefill and ships the resulting full KV
pages here; a *decode* worker imports them as HOST-tier prefix-cache
nodes (tpu/prefix.py ``insert_host``), so the next admission of that
prompt gets a prefix hit and the page upload rides the existing
``swapin`` kind on the unified in-flight queue ``_dq`` — the transfer
overlaps live decode steps instead of stalling them.

Wire format (own magic; the framing discipline — length prefix, exact
reads, loud size cap — is fleet/channel.py's): the one-time JOIN is
``_MAGIC`` followed by a hello frame ``<i len> <JSON {"kv_dtype": ...}>``
naming the exporter's KV pool dtype (``bf16`` | ``int8`` | ``int4`` —
``ENGINE_KV_DTYPE``); the server ACKs ``<i status>`` and REJECTS a
mismatched peer right there, because a page payload quantized for one
pool layout is garbage in another (the int4 planes are packed nibbles —
shape-compatible with nothing else, but int8 vs bf16 could otherwise
fail only deep inside ``handoff_import``'s shape check, after megabytes
moved). After JOIN, each KV frame is::

    <i meta_nbytes> <meta JSON> <payload bytes>

where meta carries the prompt tokens, page count, the kv dtype tag
(belt and braces vs the JOIN gate: frames are self-describing for
capture/replay tooling), and per-plane dtype/shape (the paged cache is
a pytree; each page's payload is the per-layer K/V planes
``ops.paged.gather_page`` returns, int8/int4 scale planes included),
and the payload is the pages' planes concatenated in chain order. The
receiver replies ``<i status>`` (0 = imported) — the ACK is what bounds
the exporter's wait and closes the ``engine.handoff`` span. Both sides
inherit ``MAX_FRAME_BYTES`` so a corrupt length can never silently OOM
the importer.

Failure contract (the PR 10 deadline plane): the exporter waits at most
``min(handoff_timeout_s, request deadline remaining)`` for the ACK; a
stuck or severed transfer completes the request with a 504
(``where="handoff"``). The prefill side's pages were retained by its own
prefix cache BEFORE export and the decode side registers only refcount-
free host payloads, so a transfer severed at ANY byte leaks zero pool
pages on either side (``assert_page_refs_consistent``) — the chaos point
``kv.handoff`` (docs/testing.md) proves it from both ends.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time

import numpy as np

from gofr_tpu.fleet import chaos
from gofr_tpu.fleet.channel import MAX_FRAME_BYTES
from gofr_tpu.http.errors import DeadlineExceeded

_MAGIC = b"GOFR-HANDOFF1\n"
_I32 = struct.Struct("<i")

ACK_OK = 0
ACK_REJECTED = 1
ACK_DTYPE_MISMATCH = 2
# adapter-era JOIN gates: the P/D split must agree on WHICH adapters
# exist (a decode worker resolving an adapter the prefill side never
# loaded would serve the wrong weights) and on the base-weight epoch (a
# hot-swap landing on one side only would mix weights across one
# request). Both fields are optional in the hello — absent means a
# pre-adapter peer, which gates on neither (wildcard), preserving
# rolling-upgrade compatibility.
ACK_ADAPTER_MISMATCH = 3
ACK_EPOCH_MISMATCH = 4

# the JOIN hello is a few dozen bytes of JSON; anything bigger is not ours
_MAX_HELLO_BYTES = 4096


def engine_kv_dtype(engine) -> str:
    """The engine's KV pool dtype as it rides the wire: the canonical
    ENGINE_KV_DTYPE spelling ('' quantize means the dense bf16 pool)."""
    return getattr(engine, "kv_quantize", "") or "bf16"


class HandoffClosed(ConnectionError):
    """The peer went away mid-frame (sever, crash, chaos drop)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes (same discipline as fleet/channel.py — a
    short read mid-frame is a protocol error, not a retry)."""
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise HandoffClosed(f"handoff peer closed mid-read ({len(buf)}/{n} bytes)")
        buf.extend(part)
    return bytes(buf)


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, reaching into ml_dtypes for the accelerator
    dtypes numpy itself doesn't know (bfloat16 — jax always ships it)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_frame(toks: np.ndarray, payloads: list[tuple], nbytes_page: int,
                 kv_dtype: str = "") -> bytes:
    """One KV frame: meta-length + meta JSON + concatenated plane bytes.
    ``payloads`` holds one tuple of HOST numpy planes per full page, in
    chain order (the caller already read the device buffers back).
    ``kv_dtype`` tags the pool layout the planes were quantized for."""
    planes = [{"dtype": str(a.dtype), "shape": list(a.shape)} for a in payloads[0]]
    meta = json.dumps({
        "toks": np.asarray(toks, np.int64).tolist(),
        "n_pages": len(payloads),
        "nbytes_page": int(nbytes_page),
        "kv_dtype": str(kv_dtype),
        "planes": planes,
    }).encode("utf-8")
    parts = [_I32.pack(len(meta)), meta]
    for page in payloads:
        for a in page:
            parts.append(np.ascontiguousarray(a).tobytes())
    frame = b"".join(parts)
    if len(frame) > MAX_FRAME_BYTES:
        raise ValueError(
            f"handoff: refusing to send a {len(frame)}-byte frame "
            f"(cap {MAX_FRAME_BYTES}); {len(payloads)} pages")
    return frame


def decode_frame(sock: socket.socket) -> tuple[np.ndarray, list[tuple], int, str]:
    """Read one KV frame off ``sock``: (prompt tokens, per-page plane
    tuples, nbytes_page, kv_dtype tag — "" from a pre-tag peer). Raises
    HandoffClosed on sever, ValueError on a frame that lies about its
    size."""
    (meta_len,) = _I32.unpack(_recv_exact(sock, _I32.size))
    if not 0 < meta_len <= MAX_FRAME_BYTES:
        raise ValueError(f"handoff: frame advertises {meta_len} meta bytes — corrupt stream")
    meta = json.loads(_recv_exact(sock, meta_len).decode("utf-8"))
    toks = np.asarray(meta["toks"], np.int32)
    n_pages = int(meta["n_pages"])
    planes = meta["planes"]
    dtypes = [_np_dtype(p["dtype"]) for p in planes]
    shapes = [tuple(int(d) for d in p["shape"]) for p in planes]
    per_page = sum(int(np.prod(sh)) * dt.itemsize for sh, dt in zip(shapes, dtypes))
    if not 0 < n_pages * per_page <= MAX_FRAME_BYTES:
        raise ValueError(
            f"handoff: frame advertises {n_pages} pages x {per_page} bytes "
            f"(cap {MAX_FRAME_BYTES}) — corrupt stream")
    payloads: list[tuple] = []
    for _ in range(n_pages):
        page = []
        for sh, dt in zip(shapes, dtypes):
            raw = _recv_exact(sock, int(np.prod(sh)) * dt.itemsize)
            page.append(np.frombuffer(raw, dtype=dt).reshape(sh).copy())
        payloads.append(tuple(page))
    return toks, payloads, int(meta["nbytes_page"]), str(meta.get("kv_dtype", ""))


def _register_handoff_metrics(metrics) -> None:
    """The registry's record-by-name API drops writes to unregistered
    names, so both endpoints declare the transfer metrics up front
    (idempotent: _register returns the existing metric)."""
    metrics.new_counter("app_tpu_kv_handoff_pages_total",
                        "KV pages shipped between role-split workers")
    metrics.new_counter("app_tpu_kv_handoff_bytes_total",
                        "KV handoff wire bytes (frame size, export side)")
    metrics.new_histogram("app_tpu_kv_handoff_seconds",
                          "prefill-side handoff latency: activation to ACK")


class HandoffJob:
    """One staged export: everything the exporter thread needs to ship a
    slot's prompt pages and settle the request, captured under the engine
    state lock at activation time. ``payloads`` are DEVICE buffers — the
    gathers were dispatched under the lock (the _evict_prefix_page
    discipline); the exporter blocks on them outside it."""

    __slots__ = ("request", "prompt_tokens", "first_token", "payloads",
                 "nbytes_page", "t0")

    def __init__(self, request, prompt_tokens, first_token, payloads,
                 nbytes_page, t0):
        self.request = request
        self.prompt_tokens = prompt_tokens
        self.first_token = first_token
        self.payloads = payloads
        self.nbytes_page = nbytes_page
        self.t0 = t0


class HandoffExporter:
    """Prefill-side export thread: serializes staged jobs onto one TCP
    connection to the decode worker's HandoffServer, lazily (re)dialing.
    Jobs are strictly serial — KV frames are multi-MB and the decode
    side imports under its state lock, so pipelining frames buys nothing
    and interleaving them would corrupt the stream."""

    def __init__(self, target: str, *, engine=None, timeout_s: float = 5.0,
                 logger=None, metrics=None):
        host, _, port = target.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.timeout_s = max(0.1, float(timeout_s))
        self.engine = engine
        self.logger = logger
        self.metrics = metrics
        if metrics is not None:
            _register_handoff_metrics(metrics)
        self._sock: socket.socket | None = None
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._stop = threading.Event()
        self._stats = {"exported": 0, "failed": 0, "pages": 0, "bytes": 0}
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="kv-handoff-export", daemon=True)
        self._thread.start()

    # -- connection ------------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is not None:
            return self._sock
        s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # JOIN: magic + hello (kv dtype, adapter-set digest, base-weight
        # epoch); a mismatched pool layout / adapter set / weights epoch
        # is rejected HERE, before any multi-MB page frame moves
        hello = json.dumps({
            "kv_dtype": engine_kv_dtype(self.engine),
            "adapters": str(getattr(self.engine, "adapters_digest",
                                    lambda: "")()),
            "weights_epoch": int(getattr(self.engine, "weights_epoch", 0) or 0),
        }).encode("utf-8")
        s.sendall(_MAGIC + _I32.pack(len(hello)) + hello)
        try:
            (status,) = _I32.unpack(_recv_exact(s, _I32.size))
        except HandoffClosed:
            s.close()
            raise
        if status != ACK_OK:
            s.close()
            if status == ACK_ADAPTER_MISMATCH:
                raise HandoffClosed(
                    "decode worker rejected JOIN (ACK_ADAPTER_MISMATCH): the "
                    "P/D sides disagree on the loaded adapter set (register "
                    "the same adapters — names, ranks, scales — on both)")
            if status == ACK_EPOCH_MISMATCH:
                raise HandoffClosed(
                    "decode worker rejected JOIN (ACK_EPOCH_MISMATCH): the "
                    "P/D sides are at different base-weight epochs (a live "
                    "hot-swap must land on both before pages move)")
            raise HandoffClosed(
                f"decode worker rejected JOIN (status {status}): "
                f"kv dtype {engine_kv_dtype(self.engine)!r} does not match the "
                "import pool (ENGINE_KV_DTYPE must agree across the P/D split)")
        self._sock = s
        return s

    def _sever(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- export ----------------------------------------------------------------

    def submit(self, job: HandoffJob) -> None:
        self._q.put(job)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            if job is None:
                break
            try:
                self._export(job)
            except Exception as e:  # noqa: BLE001 - one bad job must not kill the thread
                self._fail(job, f"handoff export error: {e}")

    def _export(self, job: HandoffJob) -> None:
        req = job.request
        # device→host readback OUTSIDE every engine lock: the gathers were
        # dispatched at activation; np.asarray blocks on them here
        t_rb = time.monotonic()
        host_pages = [tuple(np.asarray(a) for a in page) for page in job.payloads]
        plane = getattr(self.engine, "perf", None)
        if plane is not None:
            # off-device-thread transfer: contributes bytes/device_s to the
            # roofline window but never moves the _dq bubble floor
            now = time.monotonic()
            flops, bytes_ = plane.model.handoff_export(len(host_pages))
            plane.note_external("handoff_export", now - t_rb, flops, bytes_, now)
        if req.cancelled or req.expired(time.monotonic()):
            self._fail(job, "request expired before KV export began")
            return
        try:
            frame = encode_frame(job.prompt_tokens, host_pages, job.nbytes_page,
                                 kv_dtype=engine_kv_dtype(self.engine))
        except ValueError as e:
            self._fail(job, str(e))
            return
        # bound the whole send+ACK by the tighter of the handoff budget and
        # the request's remaining deadline (PR 10 plane)
        budget = self.timeout_s
        if req.deadline is not None:
            budget = min(budget, max(0.05, req.deadline - time.monotonic()))
        # chaos kv.handoff, client side: drop = sever the connection with
        # the frame (possibly partially) unsent — no ACK ever arrives
        if chaos.fire("kv.handoff", side="export", pages=len(host_pages)):
            self._sever()
            self._fail(job, "handoff transfer severed (chaos kv.handoff)")
            return
        try:
            s = self._connect()
            s.settimeout(budget)
            s.sendall(frame)
            (status,) = _I32.unpack(_recv_exact(s, _I32.size))
        except (OSError, HandoffClosed) as e:
            self._sever()
            self._fail(job, f"handoff transfer failed: {e}")
            return
        if status != ACK_OK:
            self._fail(job, f"decode worker rejected the KV frame (status {status})")
            return
        self._settle(job, len(host_pages), len(frame))

    def _settle(self, job: HandoffJob, n_pages: int, nbytes: int) -> None:
        req = job.request
        now = time.monotonic()
        with self._lock:
            self._stats["exported"] += 1
            self._stats["pages"] += n_pages
            self._stats["bytes"] += nbytes
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_tpu_kv_handoff_pages_total", n_pages, side="export")
            self.metrics.increment_counter(
                "app_tpu_kv_handoff_bytes_total", nbytes, side="export")
            self.metrics.record_histogram(
                "app_tpu_kv_handoff_seconds", now - job.t0)
        rt = req.kw.get("_rt")
        if rt is not None:
            rt.end("engine.handoff", pages=n_pages, bytes=nbytes)
        eng = self.engine
        tokenizer = getattr(eng, "tokenizer", None) if eng is not None else None
        tokens = [int(job.first_token)]
        ft = req.kw.get("_first_token_at", job.t0)
        req.complete(result={
            "tokens": tokens,
            "text": tokenizer.decode(tokens) if tokenizer is not None else None,
            "finish_reason": "handoff",
            "ttft_s": ft - req.enqueued_at,
        })

    def _fail(self, job: HandoffJob, why: str) -> None:
        with self._lock:
            self._stats["failed"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_request_deadline_exceeded_total", 1, where="handoff")
        rt = job.request.kw.get("_rt")
        if rt is not None:
            rt.end("engine.handoff", error=why[:120])
        if self.logger is not None:
            self.logger.warn(f"kv handoff: {why}")
        job.request.complete(error=DeadlineExceeded(f"kv handoff failed: {why}"))

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def close(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=2.0)
        self._sever()


class HandoffServer:
    """Decode-side import listener: accepts prefill workers' connections
    and registers each frame's pages as host-tier prefix nodes via
    ``engine.handoff_import`` — refcount-free payloads the next prefix
    hit promotes and uploads through the normal ``swapin`` path."""

    def __init__(self, engine, listen: str = "127.0.0.1:0", *,
                 logger=None, metrics=None):
        self.engine = engine
        self.logger = logger
        self.metrics = metrics
        if metrics is not None:
            _register_handoff_metrics(metrics)
        host, _, port = listen.rpartition(":")
        self.host = host or "127.0.0.1"
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.host, int(port)))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._stats = {"imported": 0, "rejected": 0, "pages": 0, "bytes": 0}
        self._lock = threading.Lock()
        self._conns: list[socket.socket] = []
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-handoff-server", daemon=True)
        self._thread.start()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="kv-handoff-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            if _recv_exact(conn, len(_MAGIC)) != _MAGIC:
                return  # not a handoff peer; drop the connection
            # JOIN hello: the peer names its KV pool dtype; reject a
            # mismatch before accepting any page frame (module docstring)
            (hlen,) = _I32.unpack(_recv_exact(conn, _I32.size))
            if not 0 < hlen <= _MAX_HELLO_BYTES:
                return  # not a handoff peer; drop the connection
            hello = json.loads(_recv_exact(conn, hlen).decode("utf-8"))
            want = engine_kv_dtype(self.engine)
            got = str(hello.get("kv_dtype", ""))
            if got != want:
                with self._lock:
                    self._stats["rejected"] += 1
                if self.logger is not None:
                    self.logger.warn(
                        f"kv handoff JOIN rejected: peer kv dtype {got!r} != "
                        f"import pool {want!r}")
                conn.sendall(_I32.pack(ACK_DTYPE_MISMATCH))
                return
            # adapter-era gates — checked ONLY when the hello carries the
            # fields (a pre-adapter peer sends neither and gates on
            # neither; see the ACK code comment)
            if "adapters" in hello:
                want_ad = str(getattr(self.engine, "adapters_digest",
                                      lambda: "")())
                got_ad = str(hello.get("adapters", ""))
                if got_ad != want_ad:
                    with self._lock:
                        self._stats["rejected"] += 1
                    if self.logger is not None:
                        self.logger.warn(
                            f"kv handoff JOIN rejected: peer adapter set "
                            f"{got_ad or '<none>'} != local "
                            f"{want_ad or '<none>'} (register identical "
                            f"adapters on both P/D sides)")
                    conn.sendall(_I32.pack(ACK_ADAPTER_MISMATCH))
                    return
            if "weights_epoch" in hello:
                want_we = int(getattr(self.engine, "weights_epoch", 0) or 0)
                got_we = int(hello.get("weights_epoch", 0) or 0)
                if got_we != want_we:
                    with self._lock:
                        self._stats["rejected"] += 1
                    if self.logger is not None:
                        self.logger.warn(
                            f"kv handoff JOIN rejected: peer base-weight "
                            f"epoch {got_we} != local {want_we} (hot-swap "
                            f"must land on both sides before pages move)")
                    conn.sendall(_I32.pack(ACK_EPOCH_MISMATCH))
                    return
            conn.sendall(_I32.pack(ACK_OK))
            while not self._stop.is_set():
                toks, payloads, nbytes_page, frame_dtype = decode_frame(conn)
                if frame_dtype and frame_dtype != want:
                    # JOIN said one thing, the frame says another:
                    # protocol corruption — reject, keep the connection
                    conn.sendall(_I32.pack(ACK_DTYPE_MISMATCH))
                    with self._lock:
                        self._stats["rejected"] += 1
                    continue
                # chaos kv.handoff, server side: the frame arrived but is
                # dropped BEFORE import — the exporter times out waiting
                # for an ACK that never comes (raise/delay work too)
                if chaos.fire("kv.handoff", side="import", pages=len(payloads)):
                    return
                try:
                    added = self.engine.handoff_import(toks, payloads, nbytes_page)
                    status = ACK_OK
                except Exception as e:  # noqa: BLE001 - reject, keep serving
                    added = 0
                    status = ACK_REJECTED
                    if self.logger is not None:
                        self.logger.warn(f"kv handoff import rejected: {e}")
                nbytes = len(payloads) * nbytes_page
                with self._lock:
                    if status == ACK_OK:
                        self._stats["imported"] += 1
                        self._stats["pages"] += added
                        self._stats["bytes"] += nbytes
                    else:
                        self._stats["rejected"] += 1
                if self.metrics is not None and status == ACK_OK:
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_pages_total", added, side="import")
                    self.metrics.increment_counter(
                        "app_tpu_kv_handoff_bytes_total", nbytes, side="import")
                conn.sendall(_I32.pack(status))
        except (HandoffClosed, ValueError, OSError, json.JSONDecodeError):
            pass  # peer gone or corrupt stream: drop the connection
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._thread.join(timeout=2.0)


__all__ = [
    "ACK_ADAPTER_MISMATCH", "ACK_DTYPE_MISMATCH", "ACK_EPOCH_MISMATCH",
    "ACK_OK", "ACK_REJECTED", "HandoffClosed",
    "HandoffExporter", "HandoffJob", "HandoffServer", "decode_frame",
    "encode_frame", "engine_kv_dtype",
]
