"""Hierarchical automatic prefix caching for the paged KV layout.

Full KV pages of completed prompt prefixes are retained in a token-addressed
chain (one pool reference per cached page) and reused by later prompts that
share the prefix: the slot starts with the cached pages in its block table
and prefill runs only on the remainder through the chunked-prefill offset
path. This is the TPU-serving analog of the reference's response-side reuse
patterns (it has none — SURVEY §5.7 notes the model layer is new capability);
the design matches the public automatic-prefix-caching idea from paged
serving systems, re-built here over ``ops.paged`` block tables.

Two tiers (ISSUE 4): pages live in the device pool (HBM tier) or, once pool
pressure would have dropped them, as host-DRAM copies of their K/V content
(host tier, bounded by ``host_budget_bytes`` — 0 disables the tier and
restores the single-tier behavior exactly). A chain may interleave tiers:
each node is independently device-resident (``page_id >= 0``) or
host-resident (``page_id == -1`` + a ``host`` payload). The engine owns all
device access — it copies page content out at spill time (``spill_lru`` /
``commit_spill``) and back in at hit time (``promote`` + an async device
upload riding the unified in-flight queue).

Correctness invariants:
- Only FULL pages are cached, and a hit is capped at ``prompt_len - 1``
  tokens, so the final prompt token's logits are always recomputed — the
  request's first sampled token is identical with or without a hit.
- Cached pages are immutable: decode/prefill writes land at positions at or
  beyond the hit length, which live in pages the slot allocated itself.
- Pages carry pool refcounts (engine ``_page_refs``): a page returns to the
  free pool only when no slot uses it AND the cache no longer holds it.
- Cached K/V is ADAPTER-INDEPENDENT: multi-LoRA multiplexing
  (gofr_tpu.adapters) applies its delta at the lm_head only, so a prefix
  cached by one adapter's request is a valid hit for any other adapter
  (and for the base model). No adapter id belongs in the chain key. A
  full-model hot-swap (engine.adopt_weights) is the opposite case — the
  cache is cleared wholesale via ``_reset_device_state``.
  Pool pressure spills (or, with the host tier off, evicts) least-recently-
  used cache leaves before the engine resorts to preemption. Host-resident
  nodes hold NO pool reference — a page is counted in exactly one tier.
- A node promoted to the device tier with its upload still in flight is
  ``pending``: spill/evict skip it (its device content is not yet valid to
  copy out), and ``settle`` clears the flag at upload fold time.

KV content equality: a page holding positions [i*P, (i+1)*P) of a given
token prefix has deterministically identical K/V regardless of which request
computed it, so chains may interleave pages registered by different requests
— and a host payload captured from one request's pages is valid content for
every later request that hits the same chain node. This holds per POOL
DTYPE: quantized pools (int8, packed int4 — ISSUE 13) write deterministic
quantized planes, so the equality argument carries over unchanged, but
content from one dtype's pool is meaningless in another's — which is why
the cross-worker handoff path tags frames and rejects mismatched-dtype
peers at JOIN (tpu/handoff.py).

Speculative decoding note (ISSUE 13): with spec rounds in the pipeline a
lane over-claims trailing pages for its in-flight rounds and trims the
surplus at fold time (engine ``_trim_lane_pages``). Only TRAILING pages —
beyond the last accepted position — are ever trimmed; cached prefix pages
are leading prompt pages and carry their own cache refcount besides, so
the prefix tiers never see a trimmed page disappear from under a chain.
"""

from __future__ import annotations

import hashlib
import heapq

import numpy as np

_ROOT = 0


def chain_key(parent_key: int, tokens: bytes) -> int:
    """Stable chain key for the page holding ``tokens`` under ``parent_key``.

    blake2b over the parent key's 8 little-endian bytes + the page's raw
    token bytes, NOT Python's builtin ``hash``: bytes hashing is
    PYTHONHASHSEED-salted, so builtin-hash keys differ across processes and
    could neither shard a consistent-hash ring (router/ring.py computes
    these same keys router-side) nor survive a replica restart. 64-bit
    digest: collisions land in the ``_get`` ancestry check like any other
    dict-slot collision."""
    h = hashlib.blake2b(parent_key.to_bytes(8, "little"), digest_size=8)
    h.update(tokens)
    return int.from_bytes(h.digest(), "little")


def chain_keys(toks, page_size: int) -> list[int]:
    """Chain keys of every FULL page of ``toks`` — the same page-granular
    token-bytes walk ``PrefixCache.lookup``/``insert`` perform, exposed so
    the data-plane router derives shard keys identical to the keys the
    replica's cache stores (docs/routing.md)."""
    arr = np.asarray(toks)
    p = int(page_size)
    n_full = int(arr.shape[0]) // p
    buf = np.ascontiguousarray(arr[: n_full * p], dtype=np.int32)
    keys: list[int] = []
    key = _ROOT
    for i in range(n_full):
        key = chain_key(key, buf[i * p:(i + 1) * p].tobytes())
        keys.append(key)
    return keys


class _Node:
    __slots__ = ("parent_key", "tokens", "page_id", "children", "dev_children",
                 "last_used", "host", "host_nbytes", "pending")

    def __init__(self, parent_key: int, tokens: bytes, page_id: int, last_used: int):
        self.parent_key = parent_key
        self.tokens = tokens          # the page's token BYTES (int32 little-endian)
        self.page_id = page_id        # device page id, or -1 when host-resident
        self.children = 0             # children in ANY tier
        self.dev_children = 0         # device-tier children (spill eligibility)
        self.last_used = last_used
        self.host = None              # host payload (tuple of per-plane arrays)
        self.host_nbytes = 0
        self.pending = False          # device upload dispatched, not yet folded


class PrefixCache:
    """Token-addressed chain of cached full KV pages, in two tiers.

    The cache stores bookkeeping (plus host-tier page payloads) — device
    page contents stay in the engine's paged pool; the engine owns refcounts
    and calls back into the cache for lookup/insert/spill/promote under its
    state lock (single-threaded access).

    Eviction is a lazy min-heap of ``(last_used, key)`` candidates per tier:
    every touch/creation of an eligible node pushes an entry; the pop side
    skips stale ones (node gone, tier changed, grew children, timestamp
    moved, upload pending). Stale entries cost O(log n) each to skip, so
    eviction under pool pressure is amortized O(log n) instead of the
    O(n)-scan-per-page the first cut shipped with (ADVICE round 3).

    Chain keys are hashes over the page's raw token bytes
    (``np.ascontiguousarray(...).tobytes()``), not per-int Python tuples —
    one contiguous copy + one ``tobytes`` per page keeps lookup/insert free
    of O(page_size) Python-object churn on the admission hot path."""

    def __init__(self, page_size: int, host_budget_bytes: int = 0):
        self.page_size = page_size
        self.host_budget = max(0, int(host_budget_bytes))
        self.host_bytes = 0
        self._nodes: dict[int, _Node] = {}
        self._dev_count = 0
        self._host_count = 0
        self._clock = 0
        self._heap: list[tuple[int, int]] = []   # device-tier (last_used, key)
        self._hheap: list[tuple[int, int]] = []  # host-tier (last_used, key)

    def __len__(self) -> int:
        """Device-resident (HBM-tier) page count — what the pool refcounts
        see, and what ``app_tpu_prefix_cached_pages`` reports."""
        return self._dev_count

    @property
    def host_pages(self) -> int:
        return self._host_count

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # stable (process-independent) digest — see module-level ``chain_key``;
    # router/ring.py shards on these exact values
    _child_key = staticmethod(chain_key)

    def _page_bytes_of(self, toks: np.ndarray) -> np.ndarray:
        """One contiguous int32 copy of the full-page region of ``toks`` —
        per-page keys are ``tobytes()`` slices of this buffer, so neither
        lookup nor insert materializes per-int Python tuples."""
        p = self.page_size
        n_full = int(len(toks)) // p
        return np.ascontiguousarray(toks[: n_full * p], dtype=np.int32)

    def _push(self, key: int, node: _Node) -> None:
        heapq.heappush(self._heap, (node.last_used, key))
        # Lazy deletion leaves one stale entry per touch; without a bound
        # the heap grows with lifetime lookup count. Compact when stale
        # entries dominate — amortized O(1) per push.
        if len(self._heap) > 4 * self._dev_count + 16:
            self._heap = [
                (n.last_used, k) for k, n in self._nodes.items()
                if n.page_id >= 0 and n.dev_children == 0 and not n.pending
            ]
            heapq.heapify(self._heap)

    def _hpush(self, key: int, node: _Node) -> None:
        heapq.heappush(self._hheap, (node.last_used, key))
        if len(self._hheap) > 4 * self._host_count + 16:
            self._hheap = [
                (n.last_used, k) for k, n in self._nodes.items()
                if n.page_id < 0 and n.children == 0
            ]
            heapq.heapify(self._hheap)

    def _touch(self, key: int, node: _Node) -> None:
        node.last_used = self._tick()
        if node.page_id >= 0:
            if node.dev_children == 0:
                self._push(key, node)
        elif node.children == 0:
            self._hpush(key, node)

    def _get(self, parent_key: int, key: int, page_toks: bytes) -> _Node | None:
        """Node for ``key``, or None on a miss OR a dict-slot collision.
        Both tokens and ancestry must match: two chains whose colliding
        pages hold identical tokens but different parents are distinct
        prefixes and must not alias (ADVICE round 3)."""
        node = self._nodes.get(key)
        if node is not None and (node.tokens != page_toks or node.parent_key != parent_key):
            return None
        return node

    def lookup_tiered(self, toks: np.ndarray) -> list[tuple[int, "_Node"]]:
        """``(key, node)`` for the longest cached full-page prefix of
        ``toks``, across BOTH tiers (a chain may interleave device- and
        host-resident nodes). Touches LRU clocks; takes NO references —
        the caller acquires refs for device pages it uses, claims fresh
        pages + ``promote``s host nodes it swaps in, and must cap the hit
        below ``len(toks)`` so the last token is recomputed."""
        chain: list[tuple[int, _Node]] = []
        key = _ROOT
        p = self.page_size
        buf = self._page_bytes_of(toks)
        for i in range(buf.shape[0] // p):
            page_toks = buf[i * p:(i + 1) * p].tobytes()
            parent, key = key, self._child_key(key, page_toks)
            node = self._get(parent, key, page_toks)
            if node is None:
                break
            self._touch(key, node)
            chain.append((key, node))
        return chain

    def lookup(self, toks: np.ndarray) -> list[int]:
        """Device page ids of the longest DEVICE-RESIDENT cached full-page
        prefix of ``toks`` (the single-tier contract: the ids splice
        contiguously into a block table, so the walk stops at the first
        host-resident node). Identical to the pre-tier behavior when the
        host tier is off."""
        pages: list[int] = []
        for _, node in self.lookup_tiered(toks):
            if node.page_id < 0:
                break
            pages.append(node.page_id)
        return pages

    def insert(self, toks: np.ndarray, pages: list[int]) -> list[int]:
        """Register ``pages`` (the slot's own, in chain order) as the full
        pages of ``toks``. Returns the page ids NEWLY retained — the caller
        must take one pool reference per returned id (the cache's share).
        Chain positions already cached on DEVICE are skipped (the existing
        page holds identical K/V); positions cached on HOST are promoted
        for free using the slot's page — the slot just computed identical
        content, so the upload the host tier would otherwise owe is
        unnecessary (the returned id covers the cache's new ref)."""
        new: list[int] = []
        key = _ROOT
        p = self.page_size
        buf = self._page_bytes_of(toks)
        for i in range(min(buf.shape[0] // p, len(pages))):
            page_toks = buf[i * p:(i + 1) * p].tobytes()
            parent, key = key, self._child_key(key, page_toks)
            node = self._get(parent, key, page_toks)
            if node is None:
                if key in self._nodes:
                    break  # collision with a different chain: stop extending
                node = _Node(parent, page_toks, pages[i], self._tick())
                self._nodes[key] = node
                self._dev_count += 1
                pnode = self._nodes.get(parent)
                if pnode is not None:
                    pnode.children += 1
                    pnode.dev_children += 1
                self._push(key, node)
                new.append(pages[i])
            elif node.page_id < 0:
                self._promote(key, node, pages[i], pending=False)
                new.append(pages[i])
        return new

    def insert_host(self, toks: np.ndarray, payloads, nbytes_each: int) -> int:
        """Register transferred page payloads (chain order, one per full
        page of ``toks``) as HOST-tier nodes — the decode-side import half
        of the disaggregated handoff (tpu/handoff.py). Host nodes hold no
        pool references, so a severed transfer leaves only droppable host
        bytes behind: zero-leak by construction. Positions already cached
        in either tier are touched and skipped (KV content equality — the
        payload is identical to what the cache already holds). Enforces the
        host byte budget like ``commit_spill``. Returns the number of nodes
        added (0 when the host tier is disabled)."""
        if self.host_budget <= 0:
            return 0
        added = 0
        key = _ROOT
        p = self.page_size
        buf = self._page_bytes_of(toks)
        for i in range(min(buf.shape[0] // p, len(payloads))):
            page_toks = buf[i * p:(i + 1) * p].tobytes()
            parent, key = key, self._child_key(key, page_toks)
            node = self._get(parent, key, page_toks)
            if node is not None:
                self._touch(key, node)
                continue
            if key in self._nodes:
                break  # collision with a different chain: stop extending
            node = _Node(parent, page_toks, -1, self._tick())
            node.host = payloads[i]
            node.host_nbytes = int(nbytes_each)
            self._nodes[key] = node
            self._host_count += 1
            self.host_bytes += node.host_nbytes
            pnode = self._nodes.get(parent)
            if pnode is not None:
                pnode.children += 1
            if node.children == 0:
                self._hpush(key, node)
            added += 1
        while self.host_bytes > self.host_budget:
            if self._drop_host_lru() is None:
                break  # only interior host nodes left: transient overshoot
        return added

    # -- device-tier eviction / spill -------------------------------------------

    def _pop_dev_lru(self) -> tuple[int, _Node] | None:
        """Pop the live least-recently-used device-tier node with no
        device-tier children (descendants must leave HBM first, or chained
        pages would become unreachable while still refcounted)."""
        while self._heap:
            last_used, key = heapq.heappop(self._heap)
            node = self._nodes.get(key)
            if (node is None or node.page_id < 0 or node.dev_children != 0
                    or node.pending or node.last_used != last_used):
                continue  # stale: evicted, spilled, grew children, or touched
            return key, node
        return None

    def _unlink(self, node: _Node) -> None:
        """Parent bookkeeping for a node REMOVED from the chain entirely."""
        parent = self._nodes.get(node.parent_key)
        if parent is None:
            return
        parent.children -= 1
        if node.page_id >= 0:
            parent.dev_children -= 1
        if parent.page_id >= 0:
            if parent.dev_children == 0:
                self._push(node.parent_key, parent)
        elif parent.children == 0:
            self._hpush(node.parent_key, parent)

    def evict_lru(self) -> int | None:
        """Remove the least-recently-used device leaf outright and return
        its page id for the caller to release (the host-tier-off path).
        None when no device node is evictable."""
        popped = self._pop_dev_lru()
        if popped is None:
            return None
        key, node = popped
        del self._nodes[key]
        self._dev_count -= 1
        self._unlink(node)
        return node.page_id

    def spill_lru(self) -> tuple[int, int] | None:
        """``(key, page_id)`` of the device node ``evict_lru`` would take,
        WITHOUT removing it: the engine copies the page's K/V to host and
        then calls ``commit_spill(key, ...)`` (the two-phase split exists
        because only the engine can touch device memory). Callers must
        commit before selecting again. None when nothing is spillable."""
        popped = self._pop_dev_lru()
        if popped is None:
            return None
        key, node = popped
        return key, node.page_id

    def commit_spill(self, key: int, payload, nbytes: int) -> int:
        """Flip the node selected by ``spill_lru`` to the host tier, holding
        ``payload`` (per-plane host copies of its K/V, ``nbytes`` total).
        Enforces the host byte budget by dropping least-recently-used host
        LEAVES (children == 0 in any tier — interior nodes must stay or the
        chain below them becomes unreachable); returns the number of host
        pages dropped. The caller releases the cache's pool reference on
        the spilled page id afterwards — the page leaves HBM either way."""
        node = self._nodes[key]
        node.page_id = -1
        node.host = payload
        node.host_nbytes = int(nbytes)
        node.pending = False
        self._dev_count -= 1
        self._host_count += 1
        self.host_bytes += node.host_nbytes
        parent = self._nodes.get(node.parent_key)
        if parent is not None:
            parent.dev_children -= 1
            if parent.page_id >= 0 and parent.dev_children == 0:
                self._push(node.parent_key, parent)
        if node.children == 0:
            self._hpush(key, node)
        dropped = 0
        while self.host_bytes > self.host_budget:
            if self._drop_host_lru() is None:
                break  # only interior host nodes left: transient overshoot
            dropped += 1
        return dropped

    def replace_host_payload(self, key: int, payload) -> None:
        """Swap a host node's payload in place — the engine stages spills
        as small DEVICE buffers under its state lock (the gather dispatch
        is asynchronous) and completes the device→host read outside it,
        then materializes the node's payload here. No-op if the node was
        dropped or promoted in between."""
        node = self._nodes.get(key)
        if node is not None and node.page_id < 0 and node.host is not None:
            node.host = payload

    def _drop_host_lru(self) -> int | None:
        """Remove the least-recently-used host LEAF; returns its key or
        None when no host node is droppable."""
        while self._hheap:
            last_used, key = heapq.heappop(self._hheap)
            node = self._nodes.get(key)
            if (node is None or node.page_id >= 0 or node.children != 0
                    or node.last_used != last_used):
                continue
            del self._nodes[key]
            self._host_count -= 1
            self.host_bytes -= node.host_nbytes
            self._unlink(node)
            return key
        return None

    # -- host-tier promotion (swap-in) -------------------------------------------

    def _promote(self, key: int, node: _Node, page_id: int, pending: bool) -> None:
        node.page_id = page_id
        node.host = None
        self.host_bytes -= node.host_nbytes
        node.host_nbytes = 0
        node.pending = pending
        self._host_count -= 1
        self._dev_count += 1
        parent = self._nodes.get(node.parent_key)
        if parent is not None:
            parent.dev_children += 1
        node.last_used = self._tick()
        if not pending and node.dev_children == 0:
            self._push(key, node)

    def promote(self, key: int, page_id: int) -> None:
        """Move a host-resident node back to the device tier at ``page_id``
        (the engine claimed the page and takes the cache's pool reference;
        the host payload is dropped). The node stays ``pending`` — excluded
        from spill/evict — until ``settle`` confirms the async upload
        folded, because until then its device content is not yet valid."""
        self._promote(key, self._nodes[key], page_id, pending=True)

    def settle(self, key: int) -> None:
        """Upload fold: the node's device content is now valid — it becomes
        spillable/evictable like any other device-resident node."""
        node = self._nodes.get(key)
        if node is None or node.page_id < 0 or not node.pending:
            return
        node.pending = False
        if node.dev_children == 0:
            self._push(key, node)

    def clear(self) -> list[int]:
        """Drop everything (both tiers); returns the DEVICE page ids that
        were held — host payloads carry no pool references."""
        pages = [n.page_id for n in self._nodes.values() if n.page_id >= 0]
        self._nodes.clear()
        self._heap.clear()
        self._hheap.clear()
        self._dev_count = 0
        self._host_count = 0
        self.host_bytes = 0
        return pages
