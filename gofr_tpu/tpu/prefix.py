"""Automatic prefix caching for the paged KV layout.

Full KV pages of completed prompt prefixes are retained in a token-addressed
chain (one pool reference per cached page) and reused by later prompts that
share the prefix: the slot starts with the cached pages in its block table
and prefill runs only on the remainder through the chunked-prefill offset
path. This is the TPU-serving analog of the reference's response-side reuse
patterns (it has none — SURVEY §5.7 notes the model layer is new capability);
the design matches the public automatic-prefix-caching idea from paged
serving systems, re-built here over ``ops.paged`` block tables.

Correctness invariants:
- Only FULL pages are cached, and a hit is capped at ``prompt_len - 1``
  tokens, so the final prompt token's logits are always recomputed — the
  request's first sampled token is identical with or without a hit.
- Cached pages are immutable: decode/prefill writes land at positions at or
  beyond the hit length, which live in pages the slot allocated itself.
- Pages carry pool refcounts (engine ``_page_refs``): a page returns to the
  free pool only when no slot uses it AND the cache no longer holds it.
  Pool pressure evicts least-recently-used cache leaves before the engine
  resorts to preemption.

KV content equality: a page holding positions [i*P, (i+1)*P) of a given
token prefix has deterministically identical K/V regardless of which request
computed it, so chains may interleave pages registered by different requests.
"""

from __future__ import annotations

import heapq

import numpy as np


class _Node:
    __slots__ = ("parent_key", "tokens", "page_id", "children", "last_used")

    def __init__(self, parent_key: int, tokens: tuple, page_id: int, last_used: int):
        self.parent_key = parent_key
        self.tokens = tokens
        self.page_id = page_id
        self.children = 0
        self.last_used = last_used


_ROOT = 0


class PrefixCache:
    """Token-addressed chain of cached full KV pages.

    The cache stores bookkeeping only — page contents stay in the engine's
    paged pool; the engine owns refcounts and calls back into the cache for
    lookup/insert/evict under its state lock (single-threaded access).

    Eviction is a lazy min-heap of ``(last_used, key)`` candidates: every
    touch/creation of a LEAF pushes an entry; ``evict_lru`` pops until it
    finds a live one (node still present, still a leaf, timestamp current).
    Stale entries cost O(log n) each to skip, so eviction under pool
    pressure is amortized O(log n) instead of the O(n)-scan-per-page the
    first cut shipped with (ADVICE round 3)."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._nodes: dict[int, _Node] = {}
        self._clock = 0
        self._heap: list[tuple[int, int]] = []  # lazy (last_used, key) min-heap

    def __len__(self) -> int:
        return len(self._nodes)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _child_key(parent_key: int, tokens: tuple) -> int:
        return hash((parent_key, tokens))

    def _push(self, key: int, node: _Node) -> None:
        heapq.heappush(self._heap, (node.last_used, key))
        # Lazy deletion leaves one stale entry per touch; without a bound
        # the heap grows with lifetime lookup count. Compact when stale
        # entries dominate — amortized O(1) per push.
        if len(self._heap) > 4 * len(self._nodes) + 16:
            self._heap = [
                (n.last_used, k) for k, n in self._nodes.items() if n.children == 0
            ]
            heapq.heapify(self._heap)

    def _get(self, parent_key: int, key: int, page_toks: tuple) -> _Node | None:
        """Node for ``key``, or None on a miss OR a dict-slot collision.
        Both tokens and ancestry must match: two chains whose colliding
        pages hold identical tokens but different parents are distinct
        prefixes and must not alias (ADVICE round 3)."""
        node = self._nodes.get(key)
        if node is not None and (node.tokens != page_toks or node.parent_key != parent_key):
            return None
        return node

    def lookup(self, toks: np.ndarray) -> list[int]:
        """Page ids of the longest cached full-page prefix of ``toks``.
        Touches LRU clocks; takes NO references — the caller acquires refs
        for the pages it actually uses (and must cap the hit below
        ``len(toks)`` so the last token is recomputed)."""
        pages: list[int] = []
        key = _ROOT
        p = self.page_size
        for i in range(int(len(toks)) // p):
            page_toks = tuple(int(t) for t in toks[i * p:(i + 1) * p])
            parent, key = key, self._child_key(key, page_toks)
            node = self._get(parent, key, page_toks)
            if node is None:
                break
            node.last_used = self._tick()
            if node.children == 0:
                self._push(key, node)
            pages.append(node.page_id)
        return pages

    def insert(self, toks: np.ndarray, pages: list[int]) -> list[int]:
        """Register ``pages`` (the slot's own, in chain order) as the full
        pages of ``toks``. Returns the page ids NEWLY retained — the caller
        must take one pool reference per returned id (the cache's share).
        Pages whose chain position is already cached are skipped: the
        existing page holds identical K/V for the same tokens."""
        new: list[int] = []
        key = _ROOT
        p = self.page_size
        for i in range(min(int(len(toks)) // p, len(pages))):
            page_toks = tuple(int(t) for t in toks[i * p:(i + 1) * p])
            parent, key = key, self._child_key(key, page_toks)
            node = self._get(parent, key, page_toks)
            if node is None:
                if key in self._nodes:
                    break  # collision with a different chain: stop extending
                node = _Node(parent, page_toks, pages[i], self._tick())
                self._nodes[key] = node
                pnode = self._nodes.get(parent)
                if pnode is not None:
                    pnode.children += 1
                self._push(key, node)
                new.append(pages[i])
        return new

    def evict_lru(self) -> int | None:
        """Remove the least-recently-used LEAF node (children == 0 — interior
        nodes must outlive their descendants or chained pages leak) and
        return its page id for the caller to release. None when empty."""
        while self._heap:
            last_used, key = heapq.heappop(self._heap)
            node = self._nodes.get(key)
            if node is None or node.children != 0 or node.last_used != last_used:
                continue  # stale: evicted, grew children, or touched since
            del self._nodes[key]
            parent = self._nodes.get(node.parent_key)
            if parent is not None:
                parent.children -= 1
                if parent.children == 0:
                    self._push(node.parent_key, parent)
            return node.page_id
        return None

    def clear(self) -> list[int]:
        """Drop everything; returns the page ids that were held."""
        pages = [n.page_id for n in self._nodes.values()]
        self._nodes.clear()
        self._heap.clear()
        return pages
