"""Automatic prefix caching for the paged KV layout.

Full KV pages of completed prompt prefixes are retained in a token-addressed
chain (one pool reference per cached page) and reused by later prompts that
share the prefix: the slot starts with the cached pages in its block table
and prefill runs only on the remainder through the chunked-prefill offset
path. This is the TPU-serving analog of the reference's response-side reuse
patterns (it has none — SURVEY §5.7 notes the model layer is new capability);
the design matches the public automatic-prefix-caching idea from paged
serving systems, re-built here over ``ops.paged`` block tables.

Correctness invariants:
- Only FULL pages are cached, and a hit is capped at ``prompt_len - 1``
  tokens, so the final prompt token's logits are always recomputed — the
  request's first sampled token is identical with or without a hit.
- Cached pages are immutable: decode/prefill writes land at positions at or
  beyond the hit length, which live in pages the slot allocated itself.
- Pages carry pool refcounts (engine ``_page_refs``): a page returns to the
  free pool only when no slot uses it AND the cache no longer holds it.
  Pool pressure evicts least-recently-used cache leaves before the engine
  resorts to preemption.

KV content equality: a page holding positions [i*P, (i+1)*P) of a given
token prefix has deterministically identical K/V regardless of which request
computed it, so chains may interleave pages registered by different requests.
"""

from __future__ import annotations

import numpy as np


class _Node:
    __slots__ = ("parent_key", "tokens", "page_id", "children", "last_used")

    def __init__(self, parent_key: int, tokens: tuple, page_id: int, last_used: int):
        self.parent_key = parent_key
        self.tokens = tokens
        self.page_id = page_id
        self.children = 0
        self.last_used = last_used


_ROOT = 0


class PrefixCache:
    """Token-addressed chain of cached full KV pages.

    The cache stores bookkeeping only — page contents stay in the engine's
    paged pool; the engine owns refcounts and calls back into the cache for
    lookup/insert/evict under its state lock (single-threaded access)."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self._nodes: dict[int, _Node] = {}
        self._clock = 0

    def __len__(self) -> int:
        return len(self._nodes)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _child_key(parent_key: int, tokens: tuple) -> int:
        return hash((parent_key, tokens))

    def _walk(self, toks: np.ndarray):
        """Yield (key, node-or-None, page_tokens) down the chain of full
        pages of ``toks``; stops at the first miss or token mismatch."""
        key = _ROOT
        p = self.page_size
        for i in range(int(len(toks)) // p):
            page_toks = tuple(int(t) for t in toks[i * p:(i + 1) * p])
            key = self._child_key(key, page_toks)
            node = self._nodes.get(key)
            if node is not None and node.tokens != page_toks:
                node = None  # dict-slot collision: treat as a miss, stop
            yield key, node, page_toks
            if node is None:
                return

    def lookup(self, toks: np.ndarray) -> list[int]:
        """Page ids of the longest cached full-page prefix of ``toks``.
        Touches LRU clocks; takes NO references — the caller acquires refs
        for the pages it actually uses (and must cap the hit below
        ``len(toks)`` so the last token is recomputed)."""
        pages: list[int] = []
        for _, node, _ in self._walk(toks):
            if node is None:
                break
            node.last_used = self._tick()
            pages.append(node.page_id)
        return pages

    def insert(self, toks: np.ndarray, pages: list[int]) -> list[int]:
        """Register ``pages`` (the slot's own, in chain order) as the full
        pages of ``toks``. Returns the page ids NEWLY retained — the caller
        must take one pool reference per returned id (the cache's share).
        Pages whose chain position is already cached are skipped: the
        existing page holds identical K/V for the same tokens."""
        new: list[int] = []
        prev_key = _ROOT
        for i, (key, node, page_toks) in enumerate(self._walk(toks)):
            if i >= len(pages):
                break
            if node is None:
                if key in self._nodes:
                    break  # collision with a different chain: stop extending
                node = _Node(prev_key, page_toks, pages[i], self._tick())
                self._nodes[key] = node
                parent = self._nodes.get(prev_key)
                if parent is not None:
                    parent.children += 1
                new.append(pages[i])
            prev_key = key
        return new

    def evict_lru(self) -> int | None:
        """Remove the least-recently-used LEAF node (children == 0 — interior
        nodes must outlive their descendants or chained pages leak) and
        return its page id for the caller to release. None when empty."""
        victim_key, victim = None, None
        for key, node in self._nodes.items():
            if node.children == 0 and (victim is None or node.last_used < victim.last_used):
                victim_key, victim = key, node
        if victim is None:
            return None
        del self._nodes[victim_key]
        parent = self._nodes.get(victim.parent_key)
        if parent is not None:
            parent.children -= 1
        return victim.page_id

    def clear(self) -> list[int]:
        """Drop everything; returns the page ids that were held."""
        pages = [n.page_id for n in self._nodes.values()]
        self._nodes.clear()
        return pages
