"""Multi-host lockstep serving (SURVEY §5.8; BASELINE row 4).

A multi-host mesh (v5e-64 = 16 hosts) runs ONE XLA program per step across
every process: all processes must issue identical jit calls in identical
order, but only one process sees the request queue. The reference scales
out with NCCL/MPI ranks driven by an external launcher; the TPU-native
analog is leader/follower lockstep over the runtime's own collectives:

- the LEADER (process 0) runs the full GenerateEngine — admission, EDF
  planning, slot bookkeeping, streaming — and before every device call
  broadcasts a small header (program tag + shape/flag fields) followed by
  the packed host inputs (``multihost_utils.broadcast_one_to_all`` — a
  device collective, so it rides the same ICI/DCN fabric as the program);
- FOLLOWERS run ``engine.serve_follower()``: receive the header,
  reconstruct the packed array's shape from it plus engine config,
  receive the payload, and issue the SAME jit call. Their host loops never
  touch requests; their contribution is their device shards inside the
  sharded programs.

Determinism makes this sound: params come from the same seed, the RNG step
rides inside the packed inputs, decode-chunk length is static, and the
device-resident ``prev_last`` carry is reproduced on every process because
each executes the same calls in the same order (warmup decode announces a
live=0 flag so followers mirror the leader's no-carry warmup exactly).
The leader's unified async pipeline (engine ``_dq``) preserves this: it
announces immediately before each DISPATCH on the device thread, so the
broadcast stream is the dispatch order even while older calls' readbacks
are still in flight — followers execute synchronously and replay
identically (tests/test_async_pipeline.py records and replays a stream).

Failure semantics: the leader broadcasts the STOP tag on ``stop()`` AND
from the device loop's terminal crash path, so follower processes never
block forever on a CLEANLY-dying leader. A leader stopped with a WEDGED
device thread cannot safely broadcast (the wedged thread may still be
inside a collective) — followers must be torn down externally in that
case, which is also the only safe multi-host response to a wedged
program.

Liveness against a HARD-KILLED leader (kill -9 / OOM — no STOP reaches
the fabric): set ``LOCKSTEP_DEADLINE_S``. The leader then broadcasts a
NOP heartbeat from its device thread whenever it idles with no
announcement for deadline/3, and each follower arms a watchdog that
hard-exits the process (``os._exit(LOCKSTEP_EXIT_CODE)``, default
handler) when nothing — program, heartbeat, or stop — arrives for a full
deadline. Hard exit is deliberate: the follower is blocked INSIDE a
device collective that can never complete, so no Python-level unwind can
release it; the supervisor (k8s, systemd) sees a distinct exit code and
restarts the pod. Size the deadline above the worst-case program
compile+step gap (run ``warmup()`` before serving so steady-state gaps
are steps, not compiles). Heartbeats ride the leader's device thread —
never a second thread — because interleaving a second broadcast stream
would corrupt the collective ordering.

Restart-resync design (documented for v2; NOT implemented): after any
process death, the group must be torn down and re-formed — coordinator
restart, same seed, fresh engines — because KV/hist/carry state cannot
be trusted to match across survivors. The leader's request queue (and
any durable queue in front of it) is the only state worth preserving;
slot-resident generations are lost, exactly like the single-host
crash-recover path (engine._crash_recover). v1 therefore forbids
in-lockstep engine restarts (max_restarts=0) and treats every failure
as group-fatal.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

TAG_STOP = 0
TAG_PREFILL = 1
TAG_CHUNK = 2
TAG_DECODE = 3
TAG_SPEC = 4
TAG_NOP = 5  # leader heartbeat: header only, no payload, no device call

LOCKSTEP_EXIT_CODE = 17  # follower watchdog hard-exit (distinct for supervisors)

_HEADER_LEN = 3  # (tag, a, b)


def _broadcast(value):
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)


class LockstepLeader:
    """Leader-side announcer: one (header, payload) broadcast per device
    call. Called from the engine's device thread only."""

    def __init__(self):
        self._stopped = False
        self._last_announce = time.monotonic()

    def announce(self, tag: int, a: int, b: int, packed: np.ndarray) -> None:
        _broadcast(np.array([tag, a, b], np.int32))
        _broadcast(np.asarray(packed, np.int32))
        self._last_announce = time.monotonic()

    def maybe_heartbeat(self, interval_s: float) -> None:
        """NOP-header broadcast when idle past ``interval_s`` — resets the
        followers' liveness watchdogs. Device-thread only (a heartbeat from
        any other thread could interleave with a live announcement and
        corrupt the collective stream)."""
        if not self._stopped and time.monotonic() - self._last_announce > interval_s:
            _broadcast(np.array([TAG_NOP, 0, 0], np.int32))
            self._last_announce = time.monotonic()

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            _broadcast(np.array([TAG_STOP, 0, 0], np.int32))


class LockstepFollower:
    """Follower-side receive loop bound to an engine built with the same
    config/seed. Blocks in the broadcast collective until the leader's
    next call; returns when the leader announces stop.

    ``deadline_s > 0`` arms a liveness watchdog: when no header (program,
    heartbeat, or stop) completes for a full deadline, ``on_timeout`` runs
    — by default a CRITICAL log + ``os._exit(LOCKSTEP_EXIT_CODE)``,
    because the receive thread is wedged inside a collective that can
    never complete once the leader is gone (module docstring)."""

    def __init__(self, engine, deadline_s: float = 0.0, on_timeout=None):
        self.engine = engine
        self.deadline_s = float(deadline_s)
        self._on_timeout = on_timeout or self._default_timeout
        self._progress_at = time.monotonic()
        self._done = threading.Event()

    def _default_timeout(self) -> None:  # pragma: no cover - exits hard
        self.engine.logger.fatal(
            f"lockstep follower: no leader traffic for {self.deadline_s:.0f}s "
            f"— leader presumed dead; exiting {LOCKSTEP_EXIT_CODE}"
        )
        os._exit(LOCKSTEP_EXIT_CODE)

    def _watch(self) -> None:
        step = min(1.0, self.deadline_s / 4)
        while not self._done.wait(step):
            if time.monotonic() - self._progress_at > self.deadline_s:
                self._on_timeout()
                return

    def _recv(self, shape) -> np.ndarray:
        return np.asarray(_broadcast(np.zeros(shape, np.int32)))

    def run(self) -> None:
        import jax.numpy as jnp

        from gofr_tpu.ops.pallas import platform_hint

        if self.deadline_s > 0:
            threading.Thread(target=self._watch, name="lockstep-watchdog",
                             daemon=True).start()
        try:
            self._run_inner(jnp, platform_hint)
        finally:
            self._done.set()

    def _run_inner(self, jnp, platform_hint) -> None:
        eng = self.engine
        w = eng.pages_per_slot if eng.kv_layout == "paged" else 1
        wt = eng.pages_per_slot if eng.kv_layout == "paged" else 0
        n, k = eng.num_slots, eng.decode_chunk
        # same platform pin as the leader's device thread (engine._run):
        # first-time traces here must resolve kernels for the engine's
        # actual backend, not whatever jax.default_backend() guesses —
        # plus the engine's paged KV write-mode pin (engine._trace_scope)
        with platform_hint(getattr(eng.tpu, "platform", None)), eng._trace_scope():
            while True:
                header = np.asarray(_broadcast(np.zeros(_HEADER_LEN, np.int32)))
                self._progress_at = time.monotonic()
                tag, a, b = int(header[0]), int(header[1]), int(header[2])
                if tag == TAG_STOP:
                    return
                if tag == TAG_NOP:
                    continue  # leader heartbeat: liveness only
                if tag == TAG_PREFILL:
                    packed = self._recv((b, a + w + 3))
                    toks, eng.cache = eng._prefill_sample(
                        eng.params, eng._base_key, eng.cache, jnp.asarray(packed))
                    del toks
                elif tag == TAG_CHUNK:
                    packed = self._recv((1, a + w + 4))
                    toks, eng.cache = eng._chunk_prefill(
                        eng.params, eng._base_key, eng.cache, jnp.asarray(packed))
                    del toks
                elif tag == TAG_DECODE:
                    live = bool(a)  # 0 = leader warmup: zeros carry, no store
                    packed = self._recv((5 + wt, n))
                    prev = eng._prev_last if live else None
                    if prev is None:
                        prev = jnp.zeros((n,), jnp.int32)
                    out, last, eng.cache = eng._decode_chunk(
                        eng.params, eng._base_key, eng.cache, k,
                        jnp.asarray(packed), prev)
                    if live:
                        eng._prev_last = last
                    del out
                elif tag == TAG_SPEC:
                    if eng.kv_layout == "slot":
                        # slot spec: a is a live flag (0 = leader warmup:
                        # zeros carry in, output carry DISCARDED — the
                        # TAG_DECODE convention), payload is [5, n]. Live
                        # rounds reproduce the device-resident (token,
                        # hlen) carry because every process executes the
                        # same deterministic calls in order (sampled
                        # requests too: the rng step rides the payload and
                        # folds into the same config-seeded base key).
                        live = bool(a)
                        packed = self._recv((5, n))
                        carry = eng._spec_carry if live else None
                        if carry is None:
                            carry = (jnp.zeros((n,), jnp.int32),
                                     jnp.zeros((n,), jnp.int32))
                        toks, accs, eng.cache, carry_out = eng._spec_chunk_fn(
                            eng.params, eng._base_key, eng.cache, k,
                            jnp.asarray(packed), carry)
                        if live:
                            eng._spec_carry = carry_out
                    else:
                        packed = self._recv((a, n))
                        toks, accs, eng.cache = eng._spec_chunk_fn(
                            eng.params, eng._base_key, eng.cache, k,
                            jnp.asarray(packed))
                    del toks, accs
                else:  # pragma: no cover - protocol corruption
                    raise RuntimeError(f"lockstep follower: unknown tag {tag}")
