"""Multi-host lockstep serving (SURVEY §5.8; BASELINE row 4; docs/parallelism.md).

A multi-host mesh (v5e-64 = 16 hosts) runs ONE XLA program per step across
every process: all processes must issue identical jit calls in identical
order, but only one process sees the request queue. The reference scales
out with NCCL/MPI ranks driven by an external launcher; the TPU-native
analog is leader/follower lockstep:

- the LEADER (process 0) runs the full GenerateEngine — admission, EDF
  planning, slot bookkeeping, streaming — and before every device call
  announces a small header (program tag + shape/flag fields + the fleet
  epoch) followed by the packed host inputs;
- FOLLOWERS run ``engine.serve_follower()``: receive the header,
  reconstruct the packed array's shape from it plus engine config,
  receive the payload, and issue the SAME jit call. Their host loops never
  touch requests; their contribution is their device shards inside the
  sharded programs (collective transport) or their replica's compute
  (fleet transport).

Announces ride one of two transports (fleet/channel.py):

- ``CollectiveChannel`` — ``multihost_utils.broadcast_one_to_all``, a
  device collective on the same ICI/DCN fabric as the programs. v1
  semantics: membership is frozen, any process death is group-fatal
  (an announce IS a collective; a dead peer wedges everyone inside it),
  so lockstep engines on this transport never restart (max_restarts=0)
  and recovery is full group re-formation by the supervisor.
- ``FleetLeaderChannel``/``FleetFollowerChannel`` — host-side TCP.
  Followers execute the announced programs on their own process-local
  mesh, so membership changes are handled OUTSIDE the compiled programs:
  announces carry a fleet EPOCH, and any membership event (leader
  device-loop restart, follower rejoin after leader or follower death)
  is a step-boundary epoch bump — the leader requeues slot-resident work
  (preemption-by-recompute), resets per-epoch device state (cache,
  carries), and frames TAG_EPOCH; every follower resets the same state
  on receipt. Weights and jit caches stay resident across epochs — the
  warm-rejoin that makes a leader restart a blip instead of fleet death.

Determinism makes replay sound: params come from the same seed, the RNG
step rides inside the packed inputs, decode-chunk length is static, and
the device-resident ``prev_last`` carry is reproduced on every process
because each executes the same calls in the same order (warmup decode
announces a live=0 flag so followers mirror the leader's no-carry warmup
exactly). Epoch resets restore the virgin-cache state on every process
at the same stream position, so the property holds across rejoins.

Liveness: with ``LOCKSTEP_DEADLINE_S`` set, the leader heartbeats
(TAG_NOP) from its device thread when idle for deadline/3, and each
follower arms a watchdog. On a silent leader the collective-transport
follower hard-exits ``LOCKSTEP_EXIT_CODE`` (it is wedged inside a dead
collective; only the process supervisor can recover it) while the fleet
follower aborts its socket and redials — only a failed redial within
``FLEET_REJOIN_S`` escalates to the same exit code. Exit 17 is therefore
the one cross-transport signal meaning "leader presumed dead"; the
fleet.Supervisor restarts on it into rejoin-wait.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from gofr_tpu.fleet import chaos

TAG_STOP = 0
TAG_PREFILL = 1
TAG_CHUNK = 2
TAG_DECODE = 3
TAG_SPEC = 4
TAG_NOP = 5    # leader heartbeat: header only, no payload, no device call
TAG_EPOCH = 6  # fleet epoch bump: reset per-epoch state, adopt header epoch

LOCKSTEP_EXIT_CODE = 17  # follower watchdog hard-exit (distinct for supervisors)

_HEADER_LEN = 4  # (tag, a, b, epoch)


class LockstepLeader:
    """Leader-side announcer: one (header, payload) frame per device call,
    fanned out over the configured channel. Called from the engine's
    device thread only (interleaving a second announce stream would
    corrupt the replay order on every transport)."""

    def __init__(self, channel=None, epoch: int = 0):
        from gofr_tpu.fleet.channel import CollectiveChannel

        self.channel = channel if channel is not None else CollectiveChannel()
        self.epoch = int(epoch)
        self._stopped = False
        self._last_announce = time.monotonic()
        # chaos point "lockstep.announce": drop (skip the frame) or delay
        # (sleep before sending) — the fault schedule the follower-liveness
        # and desync tests inject (fleet/chaos.py; zero-cost when unarmed)
        self._chaos = chaos.hook("lockstep.announce")

    @property
    def supports_rejoin(self) -> bool:
        return bool(getattr(self.channel, "supports_rejoin", False))

    def _header(self, tag: int, a: int, b: int) -> np.ndarray:
        return np.array([tag, a, b, self.epoch], np.int32)

    def announce(self, tag: int, a: int, b: int, packed: np.ndarray) -> None:
        if self._chaos is not None and self._chaos(tag=tag):
            return  # injected drop: the frame never reaches the fabric
        self.channel.send(self._header(tag, a, b), np.asarray(packed, np.int32))
        self._last_announce = time.monotonic()

    def maybe_heartbeat(self, interval_s: float) -> None:
        """NOP-header frame when idle past ``interval_s`` — resets the
        followers' liveness watchdogs. Device-thread only."""
        if not self._stopped and time.monotonic() - self._last_announce > interval_s:
            self.channel.send(self._header(TAG_NOP, 0, 0), None)
            self._last_announce = time.monotonic()

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self.channel.send(self._header(TAG_STOP, 0, 0), None)
            close = getattr(self.channel, "close", None)
            if close is not None:
                close()

    # -- fleet membership (no-ops on the collective transport) -----------------

    def has_pending(self) -> bool:
        fn = getattr(self.channel, "has_pending", None)
        return bool(fn()) if fn is not None else False

    def admit_pending(self) -> int:
        """Bump the fleet epoch and admit every pending follower (plus
        re-frame the epoch to survivors). The caller — the engine's device
        loop, at a step boundary — has already reset its per-epoch state."""
        self.epoch += 1
        return self.channel.admit_pending(self.epoch)

    def wait_ready(self, expect: int, timeout_s: float) -> None:
        self.channel.wait_ready(expect, self.epoch, timeout_s)

    def reset_connections(self) -> None:
        """Leader device-loop restart: a crash mid-``send`` may have left a
        partial frame on some wire; close every follower socket so each
        redials into pending and rejoins at the bumped epoch with framing
        intact (fleet/channel.py)."""
        fn = getattr(self.channel, "reset_connections", None)
        if fn is not None:
            fn()

    def follower_count(self) -> int:
        fn = getattr(self.channel, "follower_count", None)
        return int(fn()) if fn is not None else 0


class LockstepFollower:
    """Follower-side replay loop bound to an engine built with the same
    config/seed. Blocks in the channel until the leader's next frame;
    returns when the leader announces stop.

    ``deadline_s > 0`` arms a liveness watchdog. Over the collective
    transport a silent leader means this process is wedged inside a dead
    collective — ``on_timeout`` (default: CRITICAL log +
    ``os._exit(LOCKSTEP_EXIT_CODE)``) is the only release. Over a fleet
    channel the watchdog aborts the socket instead, which surfaces as
    ``ChannelClosed`` on the replay thread and enters the REJOIN path:
    redial the leader endpoint until ``rejoin_timeout_s``; only redial
    failure escalates to ``on_timeout``."""

    def __init__(self, engine, deadline_s: float = 0.0, on_timeout=None,
                 channel=None):
        from gofr_tpu.fleet.channel import CollectiveChannel

        self.engine = engine
        self.channel = channel if channel is not None else CollectiveChannel()
        self.deadline_s = float(deadline_s)
        self.epoch: int | None = None  # adopted from the first frame
        self.rejoins = 0
        self._on_timeout = on_timeout or self._default_timeout
        self._progress_at = time.monotonic()
        self._done = threading.Event()

    def _default_timeout(self) -> None:  # pragma: no cover - exits hard
        self.engine.logger.fatal(
            f"lockstep follower: no leader traffic for {self.deadline_s:.0f}s "
            f"— leader presumed dead; exiting {LOCKSTEP_EXIT_CODE}"
        )
        os._exit(LOCKSTEP_EXIT_CODE)

    def _watch(self) -> None:
        step = min(1.0, self.deadline_s / 4)
        while not self._done.wait(step):
            if time.monotonic() - self._progress_at > self.deadline_s:
                if getattr(self.channel, "supports_rejoin", False):
                    # not wedged — a socket abort unblocks the replay
                    # thread into the rejoin path; the deadline clock
                    # restarts there, so this fires at most once per
                    # silence window
                    self._progress_at = time.monotonic()
                    self.channel.abort()
                else:
                    self._on_timeout()
                    return

    def _recv(self, shape) -> np.ndarray:
        return self.channel.recv_payload(shape)

    def run(self) -> None:
        import jax.numpy as jnp

        from gofr_tpu.ops.pallas import platform_hint

        if self.deadline_s > 0:
            threading.Thread(target=self._watch, name="lockstep-watchdog",
                             daemon=True).start()
        try:
            self._run_inner(jnp, platform_hint)
        finally:
            self._done.set()

    def _rejoin(self) -> None:
        """Leader went away (EOF / reset / watchdog abort): redial into the
        leader endpoint until the channel's rejoin deadline. State is NOT
        reset here — the admitting leader's TAG_EPOCH frame is the one
        reset trigger, so a reconnect and a survivor epoch bump take the
        identical path."""
        from gofr_tpu.fleet.channel import ChannelClosed

        eng = self.engine
        eng.logger.warn("fleet follower: leader connection lost; redialing")
        try:
            self.channel.rejoin()
        except ChannelClosed:
            self._on_timeout()
            raise  # on_timeout overrides that don't exit: surface the loss
        self.rejoins += 1
        eng.metrics.increment_counter("app_fleet_rejoins_total", 1)
        self._progress_at = time.monotonic()

    def _run_inner(self, jnp, platform_hint) -> None:
        from gofr_tpu.fleet.channel import ChannelClosed

        eng = self.engine
        from gofr_tpu.tpu.executor import prefill_cols
        w = prefill_cols(eng)  # paged+spec carries a trailing slot-id column
        wt = eng.pages_per_slot if eng.kv_layout == "paged" else 0
        n, k = eng.num_slots, eng.decode_chunk
        rejoinable = getattr(self.channel, "supports_rejoin", False)
        # same platform pin as the leader's device thread (engine._run):
        # first-time traces here must resolve kernels for the engine's
        # actual backend, not whatever jax.default_backend() guesses —
        # plus the engine's paged KV write-mode pin (engine._trace_scope)
        with platform_hint(getattr(eng.tpu, "platform", None)), eng._trace_scope():
            while True:
                # the WHOLE frame — header, payload, and dispatch — rides
                # inside the rejoin guard: leader death surfaces as
                # ChannelClosed from the payload recv just as readily as
                # from the header (mid-frame crash, or the watchdog abort()
                # landing between the two). The torn frame is discarded and
                # the reconnect restarts at a frame boundary (channel.py
                # framing note); engine state is safe because every branch
                # receives its full payload before touching it.
                try:
                    header = self.channel.recv_header()
                    self._progress_at = time.monotonic()
                    tag, a, b, epoch = (int(header[0]), int(header[1]),
                                        int(header[2]), int(header[3]))
                    if tag == TAG_STOP:
                        return
                    if tag == TAG_NOP:
                        continue  # leader heartbeat: liveness only
                    if tag == TAG_EPOCH:
                        # membership changed at a step boundary: reset per-epoch
                        # device state (virgin cache, no carries) exactly like
                        # the leader just did, then replay the new epoch's
                        # stream. Weights and jit caches stay warm.
                        if self.epoch is not None and epoch != self.epoch:
                            eng.logger.warn(
                                f"fleet follower: epoch {self.epoch} -> {epoch}; "
                                "resetting per-epoch device state")
                        eng._reset_device_state()
                        self.epoch = epoch
                        eng.metrics.set_gauge("app_fleet_epoch", epoch)
                        continue
                    if self.epoch is None:
                        self.epoch = epoch  # collective transport: no TAG_EPOCH
                    elif epoch != self.epoch:
                        raise RuntimeError(
                            f"lockstep follower: frame epoch {epoch} != current "
                            f"{self.epoch} (protocol corruption)")
                    if tag == TAG_PREFILL:
                        packed = self._recv((b, a + w + 3))
                        toks, eng.cache = eng._prefill_sample(
                            eng.params, eng._base_key, eng.cache, jnp.asarray(packed))
                        del toks
                    elif tag == TAG_CHUNK:
                        packed = self._recv((1, a + w + 4))
                        toks, eng.cache = eng._chunk_prefill(
                            eng.params, eng._base_key, eng.cache, jnp.asarray(packed))
                        del toks
                    elif tag == TAG_DECODE:
                        live = bool(a)  # 0 = leader warmup: zeros carry, no store
                        packed = self._recv((5 + wt, n))
                        prev = eng._prev_last if live else None
                        if prev is None:
                            prev = jnp.zeros((n,), jnp.int32)
                        out, last, eng.cache = eng._decode_chunk(
                            eng.params, eng._base_key, eng.cache, k,
                            jnp.asarray(packed), prev)
                        if live:
                            eng._prev_last = last
                        del out
                    elif tag == TAG_SPEC:
                        # unified spec frame: a is the packed row count ([5, n]
                        # slot, [5 + pages_per_slot, n] paged), b is the live
                        # flag (0 = leader warmup: zeros carry in, output carry
                        # DISCARDED — the TAG_DECODE convention). Live rounds
                        # reproduce the device-resident (token, hlen) carry
                        # because every process executes the same deterministic
                        # calls in order (sampled requests too: the rng step
                        # rides the payload and folds into the same
                        # config-seeded base key). Paged spec rounds stay
                        # pipelined under lockstep: the leader announces at
                        # dispatch time, so frame order on the wire is the
                        # leader's _dq dispatch order and the carry chain
                        # matches step for step.
                        live = bool(b)
                        packed = self._recv((a, n))
                        carry = eng._spec_carry if live else None
                        if carry is None:
                            carry = (jnp.zeros((n,), jnp.int32),
                                     jnp.zeros((n,), jnp.int32))
                        toks, accs, eng.cache, carry_out = eng._spec_chunk_fn(
                            eng.params, eng._base_key, eng.cache, k,
                            jnp.asarray(packed), carry)
                        if live:
                            eng._spec_carry = carry_out
                        del toks, accs
                    else:  # pragma: no cover - protocol corruption
                        raise RuntimeError(f"lockstep follower: unknown tag {tag}")
                except ChannelClosed:
                    if not rejoinable:
                        raise
                    self._rejoin()
                    continue
