"""Cron scheduler: 5-field crontab with per-firing tracing.

Parity with gofr `pkg/gofr/cron.go`: schedules are ``min hour dom month dow``
supporting ``*``, ``*/n``, ranges ``a-b`` (with step), and lists ``a,b,c``
(parser semantics of `cron.go:86-224`); a minute ticker walks the job table
(`cron.go:226-240`); every firing runs concurrently with a fresh root span and a
no-op-request Context (`cron.go:252-262,332-356`).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
FIELD_NAMES = ("minute", "hour", "day-of-month", "month", "day-of-week")


class CronParseError(ValueError):
    pass


def _parse_field(spec: str, lo: int, hi: int, name: str) -> frozenset[int]:
    values: set[int] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            raise CronParseError(f"empty {name} field entry")
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError as e:
                raise CronParseError(f"bad step in {name} field: {step_s!r}") from e
            if step <= 0:
                raise CronParseError(f"step must be positive in {name} field")
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            try:
                start, end = int(a), int(b)
            except ValueError as e:
                raise CronParseError(f"bad range in {name} field: {part!r}") from e
        else:
            try:
                start = end = int(part)
            except ValueError as e:
                raise CronParseError(f"bad value in {name} field: {part!r}") from e
        if start < lo or end > hi or start > end:
            raise CronParseError(f"{name} value out of range [{lo},{hi}]: {part!r}")
        values.update(range(start, end + 1, step))
    return frozenset(values)


@dataclass(frozen=True)
class Schedule:
    minutes: frozenset[int]
    hours: frozenset[int]
    days: frozenset[int]
    months: frozenset[int]
    weekdays: frozenset[int]
    # Vixie-cron day rule: when BOTH day-of-month and day-of-week are
    # restricted (field doesn't start with "*"), a day matching EITHER fires.
    days_restricted: bool = True
    weekdays_restricted: bool = True

    @classmethod
    def parse(cls, spec: str) -> "Schedule":
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(f"schedule must have 5 fields, got {len(fields)}: {spec!r}")
        parsed = [
            _parse_field(f, lo, hi, name)
            for f, (lo, hi), name in zip(fields, FIELD_RANGES, FIELD_NAMES)
        ]
        return cls(
            *parsed,
            days_restricted=not fields[2].startswith("*"),
            weekdays_restricted=not fields[4].startswith("*"),
        )

    def matches(self, t: time.struct_time) -> bool:
        # dow: python tm_wday Mon=0..Sun=6; cron uses Sun=0..Sat=6
        cron_dow = (t.tm_wday + 1) % 7
        dom_ok = t.tm_mday in self.days
        dow_ok = cron_dow in self.weekdays
        day_ok = (
            (dom_ok or dow_ok)
            if (self.days_restricted and self.weekdays_restricted)
            else (dom_ok and dow_ok)
        )
        return (
            t.tm_min in self.minutes
            and t.tm_hour in self.hours
            and t.tm_mon in self.months
            and day_ok
        )


@dataclass
class Job:
    name: str
    schedule: Schedule
    fn: Callable[..., Any]
    last_fired_minute: int = -1


class Crontab:
    """Minute-resolution scheduler; each firing runs in its own thread with a
    fresh root span and a no-op-request Context."""

    def __init__(self, container, tick_seconds: float = 20.0):
        self._container = container
        self._jobs: list[Job] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._tick_seconds = tick_seconds

    def add_job(self, spec: str, name: str, fn: Callable[..., Any]) -> None:
        schedule = Schedule.parse(spec)
        with self._lock:
            self._jobs.append(Job(name or fn.__name__, schedule, fn))

    @property
    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs)

    def start(self) -> None:
        if not self.jobs:
            return
        self._thread = threading.Thread(target=self._run, name="gofr-cron", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._tick_seconds):
            self.tick(time.time())

    def tick(self, now: float) -> list[str]:
        """Fire all jobs matching the minute containing ``now``; at most once
        per minute per job. Returns names fired (for tests)."""
        t = time.localtime(now)
        minute_id = int(now // 60)
        fired = []
        with self._lock:
            jobs = list(self._jobs)
        for job in jobs:
            if job.last_fired_minute == minute_id:
                continue
            if job.schedule.matches(t):
                job.last_fired_minute = minute_id
                fired.append(job.name)
                threading.Thread(target=self._fire, args=(job,), name=f"cron-{job.name}", daemon=True).start()
        return fired

    def _fire(self, job: Job) -> None:
        from gofr_tpu.context import Context
        from gofr_tpu.http.request import Request

        span = self._container.tracer.start_span(f"cron {job.name}", set_current=False)
        ctx = Context(Request(), self._container, span=span)
        try:
            job.fn(ctx)
            span.set_status("OK")
        except Exception as e:  # noqa: BLE001 - panic recovery per firing
            span.set_status("ERROR")
            self._container.logger.errorf("cron job %s failed: %r", job.name, e)
        finally:
            span.finish()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
