"""Remote log-level polling (gofr `pkg/gofr/logging/remotelogger/dynamic_level_logger.go`).

A background thread GETs ``REMOTE_LOG_URL`` every ``REMOTE_LOG_FETCH_INTERVAL``
seconds (default 15) and live-changes the logger level. Expected response:
``{"data": [{"serviceName": ..., "logLevel": {"LOG_LEVEL": "DEBUG"}}]}`` or any
JSON containing a ``LOG_LEVEL``-ish string — we accept ``{"level": "DEBUG"}``
and plain ``DEBUG`` bodies too.
"""

from __future__ import annotations

import json
import threading
import urllib.request

from gofr_tpu.logging import Level, Logger


def _extract_level(body: str) -> str | None:
    body = body.strip()
    try:
        data = json.loads(body)
    except json.JSONDecodeError:
        return body if body.upper() in Level.__members__ else None
    # walk the structure for a LOG_LEVEL / logLevel / level key
    stack = [data]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            for key in ("LOG_LEVEL", "logLevel", "level"):
                v = node.get(key)
                if isinstance(v, str):
                    return v
                if isinstance(v, dict):
                    stack.append(v)
            stack.extend(node.values())
        elif isinstance(node, list):
            stack.extend(node)
    return None


class RemoteLevelPoller:
    def __init__(self, logger: Logger, url: str, interval: float = 15.0, timeout: float = 5.0):
        self._logger = logger
        self._url = url
        self._interval = max(1.0, interval)
        self._timeout = timeout
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="gofr-remote-log-level", daemon=True)
        self._thread.start()

    def poll_once(self) -> None:
        try:
            with urllib.request.urlopen(self._url, timeout=self._timeout) as resp:
                body = resp.read().decode(errors="replace")
        except Exception:  # noqa: BLE001 - remote being down must not affect the app
            return
        name = _extract_level(body)
        if not name:
            return
        new_level = Level.parse(name, default=self._logger.level)
        if new_level != self._logger.level:
            self._logger.infof("remote log level change: %s -> %s", self._logger.level.name, new_level.name)
            self._logger.change_level(new_level)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
