"""Leveled structured logging: JSON when piped, colorized pretty output on a TTY.

Capability parity with the reference's logging package (gofr `pkg/gofr/logging/`):
six levels DEBUG..FATAL (`level.go:12-19`), TTY-detected pretty-vs-JSON output
(`logger.go:80-84,210-217`), a ``PrettyPrint`` protocol so structured records
(request logs, RPC logs, SQL logs) control their own terminal rendering
(`logger.go:17-19,158-170`), live level changes (used by the remote-level poller),
and a file logger for CLI apps.
"""

from __future__ import annotations

import io
import json
import sys
import threading
import time
import traceback
from enum import IntEnum
from typing import Any, Protocol, TextIO, runtime_checkable


class Level(IntEnum):
    DEBUG = 1
    INFO = 2
    NOTICE = 3
    WARN = 4
    ERROR = 5
    FATAL = 6

    @staticmethod
    def parse(name: str, default: "Level | None" = None) -> "Level":
        try:
            return Level[name.strip().upper()]
        except KeyError:
            return default if default is not None else Level.INFO


_LEVEL_COLORS = {
    Level.DEBUG: 36,  # cyan
    Level.INFO: 34,  # blue
    Level.NOTICE: 35,  # magenta
    Level.WARN: 33,  # yellow
    Level.ERROR: 31,  # red
    Level.FATAL: 31,
}


@runtime_checkable
class PrettyPrint(Protocol):
    """Structured records implement this to control their TTY rendering."""

    def pretty_print(self, writer: TextIO) -> None: ...


class Logger:
    """Thread-safe leveled logger.

    ``terminal=None`` auto-detects: pretty colorized output on a TTY, one JSON
    object per line otherwise.
    """

    def __init__(
        self,
        level: Level = Level.INFO,
        out: TextIO | None = None,
        err: TextIO | None = None,
        terminal: bool | None = None,
    ):
        self._level = level
        self._out = out if out is not None else sys.stdout
        self._err = err if err is not None else sys.stderr
        if terminal is None:
            terminal = bool(getattr(self._out, "isatty", lambda: False)())
        self._terminal = terminal
        self._lock = threading.Lock()

    # -- level management (live change supports the remote-level poller) ------

    @property
    def level(self) -> Level:
        return self._level

    def change_level(self, level: Level) -> None:
        self._level = level

    # -- log methods -----------------------------------------------------------

    def debug(self, *args: Any) -> None:
        self._log(Level.DEBUG, args)

    def debugf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.DEBUG, fmt, args)

    def info(self, *args: Any) -> None:
        self._log(Level.INFO, args)

    def infof(self, fmt: str, *args: Any) -> None:
        self._logf(Level.INFO, fmt, args)

    def notice(self, *args: Any) -> None:
        self._log(Level.NOTICE, args)

    def noticef(self, fmt: str, *args: Any) -> None:
        self._logf(Level.NOTICE, fmt, args)

    def warn(self, *args: Any) -> None:
        self._log(Level.WARN, args)

    def warnf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.WARN, fmt, args)

    def error(self, *args: Any) -> None:
        self._log(Level.ERROR, args)

    def errorf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.ERROR, fmt, args)

    def fatal(self, *args: Any) -> None:
        self._log(Level.FATAL, args)

    def fatalf(self, fmt: str, *args: Any) -> None:
        self._logf(Level.FATAL, fmt, args)

    def log_exception(self, exc: BaseException, note: str = "") -> None:
        stack = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        self.error(f"{note + ': ' if note else ''}{exc!r}\n{stack}")

    # -- internals -------------------------------------------------------------

    def _logf(self, level: Level, fmt: str, args: tuple[Any, ...]) -> None:
        if level < self._level:
            return
        try:
            message = fmt % args if args else fmt
        except (TypeError, ValueError):
            message = " ".join([fmt, *map(str, args)])
        self._log(level, (message,))

    def _log(self, level: Level, args: tuple[Any, ...]) -> None:
        if level < self._level:
            return
        stream = self._err if level >= Level.ERROR else self._out
        now = time.time()
        if self._terminal:
            self._write_pretty(stream, level, now, args)
        else:
            self._write_json(stream, level, now, args)

    def _write_json(self, stream: TextIO, level: Level, now: float, args: tuple[Any, ...]) -> None:
        structured: dict[str, Any] = {}
        plain: list[str] = []
        for arg in args:
            if isinstance(arg, dict):
                structured.update(arg)
            elif hasattr(arg, "to_log_dict"):
                structured.update(arg.to_log_dict())
            elif isinstance(arg, PrettyPrint):
                structured.update(_object_fields(arg))
            else:
                plain.append(str(arg))
        # metadata keys always win over structured fields of the same name so a
        # payload containing "level"/"time"/"message" can't corrupt the record
        message = " ".join(plain) if plain else structured.get("message", "")
        for reserved in ("level", "time", "message"):
            structured.pop(reserved, None)
        record = {
            "level": level.name,
            "time": _rfc3339(now),
            "message": message,
            **structured,
        }
        line = json.dumps(record, default=str)
        with self._lock:
            stream.write(line + "\n")
            stream.flush()

    def _write_pretty(self, stream: TextIO, level: Level, now: float, args: tuple[Any, ...]) -> None:
        color = _LEVEL_COLORS[level]
        prefix = f"\x1b[{color}m{level.name:<6}\x1b[0m [{time.strftime('%H:%M:%S', time.localtime(now))}] "
        buf = io.StringIO()
        buf.write(prefix)
        for arg in args:
            if isinstance(arg, PrettyPrint):
                buf.write("\n")
                arg.pretty_print(buf)
            else:
                buf.write(str(arg))
                buf.write(" ")
        with self._lock:
            stream.write(buf.getvalue().rstrip(" ") + "\n")
            stream.flush()


def _object_fields(obj: Any) -> dict[str, Any]:
    if hasattr(obj, "__dict__"):
        return {k: v for k, v in vars(obj).items() if not k.startswith("_")}
    return {"value": str(obj)}


def _rfc3339(ts: float) -> str:
    ms = int((ts % 1) * 1000)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts)) + f".{ms:03d}Z"


def new_logger(level_name: str = "INFO", **kw: Any) -> Logger:
    return Logger(level=Level.parse(level_name), **kw)


def new_file_logger(path: str, level: Level = Level.INFO) -> Logger:
    """File logger for CLI apps (gofr `logging/logger.go:189-208`)."""
    f = open(path, "a", encoding="utf-8")  # noqa: SIM115 - lifetime == process
    return Logger(level=level, out=f, err=f, terminal=False)


class MockLogger(Logger):
    """Captures log lines for assertions in tests."""

    def __init__(self, level: Level = Level.DEBUG):
        self.buffer = io.StringIO()
        super().__init__(level=level, out=self.buffer, err=self.buffer, terminal=False)

    @property
    def lines(self) -> list[str]:
        return [line for line in self.buffer.getvalue().splitlines() if line]

    @property
    def records(self) -> list[dict[str, Any]]:
        out = []
        for line in self.lines:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                out.append({"message": line})
        return out


_NOOP = None


def noop_logger() -> Logger:
    global _NOOP
    if _NOOP is None:
        _NOOP = Logger(level=Level.FATAL, out=io.StringIO(), err=io.StringIO(), terminal=False)
    return _NOOP
