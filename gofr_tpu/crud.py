"""CRUD generator: reflect a dataclass entity into REST routes + SQL.

Parity with gofr `pkg/gofr/crud_handlers.go`: the first dataclass field is the
primary key (`crud_handlers.go:83`); POST/GET/GET-all/PUT/DELETE are registered
(`crud_handlers.go:115-148`) with default implementations built on the
dialect-aware query builder (`crud_handlers.go:150-289`); users override any
operation by defining ``create/get_all/get/update/delete`` methods on the
entity class, and ``__table_name__``/``__rest_path__`` override naming.
"""

from __future__ import annotations

import dataclasses
import re

from gofr_tpu.datasource import sql as sqlb
from gofr_tpu.http.errors import EntityNotFound


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


_SQL_TYPES = {int: "INTEGER", float: "REAL", str: "TEXT", bytes: "BLOB", bool: "INTEGER"}


def register_crud_routes(app, entity: type, table: str | None = None, path: str | None = None) -> None:
    if not dataclasses.is_dataclass(entity):
        raise TypeError("add_rest_handlers requires a dataclass entity")
    fields = dataclasses.fields(entity)
    if not fields:
        raise TypeError("entity has no fields")
    pk = fields[0].name
    columns = [f.name for f in fields]
    table = table or getattr(entity, "__table_name__", None) or _snake(entity.__name__)
    path = path or getattr(entity, "__rest_path__", None) or _snake(entity.__name__)
    path = "/" + path.strip("/")

    ensured = set()  # DDL runs once per DB handle, not per request

    def _ensure_table(ctx) -> None:
        if id(ctx.sql) in ensured:
            return
        cols = ", ".join(
            f"{sqlb.quote_ident(f.name, ctx.sql.dialect)} {_SQL_TYPES.get(f.type if not isinstance(f.type, str) else str, 'TEXT')}"
            + (" PRIMARY KEY" if f.name == pk else "")
            for f in fields
        )
        ctx.sql.execute(f"CREATE TABLE IF NOT EXISTS {sqlb.quote_ident(table, ctx.sql.dialect)} ({cols})")
        ensured.add(id(ctx.sql))

    def create(ctx):
        if hasattr(entity, "create"):
            return entity.create(ctx)
        _ensure_table(ctx)
        obj = ctx.bind(entity)
        values = [getattr(obj, c) for c in columns]
        ctx.sql.execute(sqlb.insert_query(table, columns, ctx.sql.dialect), values)
        return f"{entity.__name__} successfully created with id: {getattr(obj, pk)}"

    def get_all(ctx):
        if hasattr(entity, "get_all"):
            return entity.get_all(ctx)
        _ensure_table(ctx)
        return ctx.sql.select_into(entity, sqlb.select_all_query(table, ctx.sql.dialect))

    def get_one(ctx):
        if hasattr(entity, "get"):
            return entity.get(ctx)
        _ensure_table(ctx)
        key = ctx.path_param(pk)
        rows = ctx.sql.select_into(entity, sqlb.select_by_query(table, pk, ctx.sql.dialect), [key])
        if not rows:
            raise EntityNotFound(pk, key)
        return rows[0]

    def update(ctx):
        if hasattr(entity, "update"):
            return entity.update(ctx)
        _ensure_table(ctx)
        key = ctx.path_param(pk)
        obj = ctx.bind(entity)
        non_pk = [c for c in columns if c != pk]
        values = [getattr(obj, c) for c in non_pk] + [key]
        affected = ctx.sql.execute(sqlb.update_query(table, non_pk, pk, ctx.sql.dialect), values)
        if affected == 0:
            raise EntityNotFound(pk, key)
        return f"{entity.__name__} successfully updated with id: {key}"

    def delete(ctx):
        if hasattr(entity, "delete"):
            return entity.delete(ctx)
        _ensure_table(ctx)
        key = ctx.path_param(pk)
        affected = ctx.sql.execute(sqlb.delete_query(table, pk, ctx.sql.dialect), [key])
        if affected == 0:
            raise EntityNotFound(pk, key)
        return f"{entity.__name__} successfully deleted with id: {key}"

    app.post(path, create)
    app.get(path, get_all)
    app.get(f"{path}/{{{pk}}}", get_one)
    app.put(f"{path}/{{{pk}}}", update)
    app.delete(f"{path}/{{{pk}}}", delete)
