"""Framework version stamped into logs/metrics/traces (gofr `pkg/gofr/version/version.go:3`)."""

FRAMEWORK = "0.1.0"
