"""App facade: build servers from config, register routes, run everything.

Parity with gofr `pkg/gofr/gofr.go`: ``App`` owns the HTTP server (with the
5-stage middleware chain), the metrics server on its own port, the gRPC server,
the pub/sub subscription manager, the cron table, and the CLI runtime — all fed
by one Container and serving handlers through one transport-neutral Context.

TPU-first: ``app.serve_model(...)`` registers a continuous-batching engine on
the container; handlers then call ``ctx.infer``/``ctx.generate``. ``run()`` adds
graceful shutdown (absent in the reference, `gofr.go:211`).
"""

from __future__ import annotations

import asyncio
import inspect
import math
import os
import signal
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

from aiohttp import web, WSMsgType

from gofr_tpu.config import DictConfig, EnvConfig
from gofr_tpu.container import Container
from gofr_tpu.fleet.chaos import fire as chaos_fire
from gofr_tpu.context import Context
from gofr_tpu import deadline
from gofr_tpu.http.errors import DeadlineExceeded, RequestTimeout
from gofr_tpu.http.middleware import (
    SPAN_KEY,
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    tracer_middleware,
)
from gofr_tpu.http.request import HTTPRequest
from gofr_tpu.http.responder import respond, to_json
from gofr_tpu.http.streaming import RawStreamingResponse, StreamingResponse
from gofr_tpu.websocket import ConnectionHub, WSConnection

Handler = Callable[[Context], Any]


class App:
    def __init__(self, config_folder: str = "./configs", config=None, container: Container | None = None):
        self.config = config if config is not None else EnvConfig(folder=config_folder)
        self.container = container if container is not None else Container.create(self.config)
        self.logger = self.container.logger

        self.http_port = self.config.get_int("HTTP_PORT", 8000)
        self.metrics_port = self.config.get_int("METRICS_PORT", 2121)
        self.grpc_port = self.config.get_int("GRPC_PORT", 9000)
        self.request_timeout = self.config.get_float("REQUEST_TIMEOUT", 0.0)

        self._routes: list[tuple[str, str, Handler]] = []
        self._ws_routes: list[tuple[str, Handler]] = []
        self._static: list[tuple[str, str]] = []
        self._auth_middlewares: list[Any] = []
        self._subscriptions: dict[str, Handler] = {}
        self._grpc_services: list[Any] = []
        self._executor = ThreadPoolExecutor(
            max_workers=self.config.get_int("HANDLER_THREADS", 32), thread_name_prefix="gofr-handler"
        )
        self.ws_hub = ConnectionHub()

        from gofr_tpu.cron import Crontab

        self.cron = Crontab(self.container)
        if self.config.get_bool("QOS_ENABLED"):
            self.enable_qos()
        self._shutdown = asyncio.Event()
        self._runners: list[web.AppRunner] = []
        self._sub_threads: list[threading.Thread] = []
        self._sub_stop = threading.Event()
        self._gossip = None  # GossipReporter once enable_router_gossip runs
        self._cleanup: list[Callable[[], None]] = []
        # one /debug/profile capture at a time (409 while held): concurrent
        # jax.profiler.trace calls crash, and N stray curls must not pin N
        # handler threads for N×seconds each
        self._profile_busy = threading.Lock()

    # -- route registration (gofr.go:244-276) ----------------------------------

    def add_route(self, method: str, path: str, handler: Handler) -> None:
        self._routes.append((method.upper(), path, handler))

    def get(self, path: str, handler: Handler) -> None:
        self.add_route("GET", path, handler)

    def post(self, path: str, handler: Handler) -> None:
        self.add_route("POST", path, handler)

    def put(self, path: str, handler: Handler) -> None:
        self.add_route("PUT", path, handler)

    def patch(self, path: str, handler: Handler) -> None:
        self.add_route("PATCH", path, handler)

    def delete(self, path: str, handler: Handler) -> None:
        self.add_route("DELETE", path, handler)

    def websocket(self, path: str, handler: Handler) -> None:
        self._ws_routes.append((path, handler))

    def add_static_files(self, route: str, directory: str) -> None:
        self._static.append((route if route.startswith("/") else f"/{route}", directory))

    def add_rest_handlers(self, entity: type, table: str | None = None, path: str | None = None) -> None:
        """Reflect a dataclass into CRUD routes (gofr `crud_handlers.go`)."""
        from gofr_tpu.crud import register_crud_routes

        register_crud_routes(self, entity, table=table, path=path)

    # -- auth (gofr.go:436-507) ------------------------------------------------

    def enable_basic_auth(self, users: dict[str, str]) -> None:
        from gofr_tpu.http.middleware.auth import basic_auth_middleware

        self._auth_middlewares.append(basic_auth_middleware(users=users))

    def enable_basic_auth_with_validator(self, validator: Callable[..., bool]) -> None:
        from gofr_tpu.http.middleware.auth import basic_auth_middleware

        self._auth_middlewares.append(basic_auth_middleware(validator=validator, container=self.container))

    def enable_api_key_auth(self, *keys: str) -> None:
        from gofr_tpu.http.middleware.auth import apikey_auth_middleware

        self._auth_middlewares.append(apikey_auth_middleware(keys=list(keys)))

    def enable_api_key_auth_with_validator(self, validator: Callable[..., bool]) -> None:
        from gofr_tpu.http.middleware.auth import apikey_auth_middleware

        self._auth_middlewares.append(apikey_auth_middleware(validator=validator, container=self.container))

    def enable_oauth(self, jwks_url: str, refresh_interval: float = 300.0,
                     audience: str | None = None, issuer: str | None = None) -> None:
        from gofr_tpu.http.middleware.auth import JWKSCache, oauth_middleware

        jwks = JWKSCache(jwks_url, refresh_interval)
        jwks.start()
        self._auth_middlewares.append(oauth_middleware(jwks=jwks, audience=audience, issuer=issuer))

    def enable_jwt_hs256(self, secret: bytes | str, audience: str | None = None,
                         issuer: str | None = None) -> None:
        from gofr_tpu.http.middleware.auth import oauth_middleware

        secret_b = secret.encode() if isinstance(secret, str) else secret
        self._auth_middlewares.append(oauth_middleware(hs_secret=secret_b, audience=audience, issuer=issuer))

    # -- QoS: admission control / rate limiting / load shedding ----------------

    def enable_qos(self, policy=None, **overrides: Any):
        """Turn on the QoS subsystem (gofr_tpu.qos; also auto-enabled by
        ``QOS_ENABLED=true``): rate limits and load shedding at the HTTP
        middleware (429/503 + ``Retry-After``) and gRPC interceptor
        (``RESOURCE_EXHAUSTED``/``UNAVAILABLE``), weighted-fair priority
        scheduling and deadline-aware admission on every served engine.
        ``policy`` is a prebuilt ``QoSPolicy``; otherwise one is built from
        ``QOS_*`` config keys with ``overrides`` applied (docs/qos.md).
        Returns the AdmissionController."""
        from gofr_tpu.qos import AdmissionController, QoSPolicy

        if policy is None:
            policy = QoSPolicy.from_config(self.config, **overrides)
        controller = AdmissionController(policy, self.container.metrics, logger=self.logger)
        self.container.register_qos(controller)
        return controller

    def enable_router_gossip(self, name: str | None = None, url: str | None = None,
                             **kw: Any):
        """Make this replica visible to a data-plane router tier
        (gofr_tpu.router; docs/routing.md): a GossipReporter publishes this
        process's health/epoch/shed snapshot on the pubsub backbone every
        ``ROUTER_GOSSIP_INTERVAL_S``. Starts with ``run()`` (after the
        engines), publishes a terminal DOWN at shutdown. Returns the
        reporter, or None when no PUBSUB_BACKEND is wired."""
        if self.container.pubsub is None:
            self.logger.error("enable_router_gossip ignored: no PUBSUB_BACKEND configured")
            return None
        from gofr_tpu.router.gossip import GossipReporter

        self._gossip = GossipReporter(
            self.container, name=name,
            url=url or f"http://127.0.0.1:{self.http_port}", **kw)
        return self._gossip

    def on_cleanup(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` during graceful shutdown, before the container closes
        — how components bound to the app (the data-plane router's gossip
        subscription, custom pollers) stop with it."""
        self._cleanup.append(fn)

    # -- other entrypoints -----------------------------------------------------

    def subscribe(self, topic: str, handler: Handler) -> None:
        if self.container.pubsub is None:
            self.logger.error(f"subscribe({topic!r}) ignored: no PUBSUB_BACKEND configured")
            return
        self._subscriptions[topic] = handler

    def add_cron_job(self, schedule: str, name: str, handler: Handler) -> None:
        self.cron.add_job(schedule, name, handler)

    def register_grpc_service(self, adder: Callable[[Any], None] | Any, servicer: Any = None) -> None:
        """Register a gRPC service: either ``(add_fn, servicer)`` from generated
        code, or an object handled by the gofr_tpu.grpc server."""
        self._grpc_services.append((adder, servicer))

    def register_service(self, name: str, base_url: str, *options: Any):
        """Register an inter-service HTTP client (circuit breaker/retry/auth
        via options, gofr `service/new.go` decorator pattern)."""
        from gofr_tpu.service import new_http_service

        client = new_http_service(base_url, self.logger, self.container.metrics, *options)
        self.container.register_service(name, client)
        return client

    def migrate(self, migrations: dict[int, Any]) -> None:
        from gofr_tpu.migration import run_migrations

        run_migrations(migrations, self.container)

    # -- external datasource plugins (gofr `external_db.go:8-52` pattern) ------

    def add_mongo(self, client: Any) -> None:
        self.container.add_mongo(client)

    def add_cassandra(self, client: Any) -> None:
        self.container.add_cassandra(client)

    def add_clickhouse(self, client: Any) -> None:
        self.container.add_clickhouse(client)

    def add_kv_store(self, client: Any) -> None:
        self.container.add_kv_store(client)

    def add_file_store(self, provider: Any) -> None:
        """Swap the container's file datasource for a remote-FS provider
        (gofr ``file/file.go:69-78`` FileSystemProvider pattern): any object
        implementing the ``datasource.file.FileSystemProvider`` surface —
        S3/FTP/SFTP wrappers plug in here; handlers keep using ``ctx.file``
        unchanged."""
        self.container.add_file_store(provider)

    # -- TPU model serving (the new capability) --------------------------------

    def serve_model(self, name: str, spec: Any = None, *, engine: Any = None, **engine_kw: Any):
        """Attach a model to the app behind a continuous-batching engine.

        ``spec`` is a ModelSpec (see gofr_tpu.models); alternatively pass a
        prebuilt ``engine``. The engine starts with ``app.run()`` (or
        immediately when the app is already running) and is reachable from any
        handler via ``ctx.infer(name, ...)`` / ``ctx.generate(name, ...)``.
        """
        if engine is None:
            from gofr_tpu.tpu.engine import build_engine

            engine = build_engine(spec, self.container, **engine_kw)
        self.container.register_engine(name, engine)
        return engine

    # -- assembly --------------------------------------------------------------

    def _registered_methods(self) -> list[str]:
        methods = sorted({m for m, _, _ in self._routes} | {"OPTIONS"})
        return methods

    def _build_http_app(self) -> web.Application:
        middlewares = [
            tracer_middleware(self.container.tracer),
            logging_middleware(self.logger),
            cors_middleware(self.config, self._registered_methods),
            metrics_middleware(self.container.metrics),
        ]
        if self.container.qos is not None:
            # after metrics (rejections must show in app_http_response),
            # before auth — admission is cheaper than signature checks, so
            # shed load never pays the auth path
            from gofr_tpu.http.middleware import qos_middleware

            middlewares.append(qos_middleware(self.container.qos))
        middlewares.extend(self._auth_middlewares)
        http_app = web.Application(middlewares=middlewares, client_max_size=64 * 1024 * 1024)

        # well-known routes (gofr.go:155-163)
        http_app.router.add_get("/.well-known/health", self._health_handler)
        http_app.router.add_get("/.well-known/alive", self._alive_handler)
        http_app.router.add_get("/favicon.ico", self._favicon_handler)
        self._add_openapi_routes(http_app)
        if self._debug_env():
            # profiling tier, gated like the reference's pprof routes
            # (http_server.go:53-60): trace capture on demand, plus the
            # always-recording flight recorder's read endpoints
            http_app.router.add_get("/debug/profile", self._profile_handler)
            http_app.router.add_get("/debug/requests", self._debug_requests_handler)
            http_app.router.add_get("/debug/engine", self._debug_engine_handler)
            http_app.router.add_get("/debug/perf", self._debug_perf_handler)
            http_app.router.add_get("/debug/quality", self._debug_quality_handler)
            http_app.router.add_get("/debug/control", self._debug_control_handler)

        for method, path, handler in self._routes:
            http_app.router.add_route(method, path, self._wrap(handler))
        for path, handler in self._ws_routes:
            http_app.router.add_get(path, self._wrap_ws(handler))
        for route, directory in self._static:
            http_app.router.add_get(
                f"{route}/{{static_tail:.*}}", self._static_handler(directory))
        # catch-all 404 with the JSON envelope (gofr handler.go:95-119)
        http_app.router.add_route("*", "/{tail:.*}", self._not_found_handler)
        return http_app

    def _build_metrics_app(self) -> web.Application:
        metrics_app = web.Application()

        async def metrics_handler(_request: web.Request) -> web.Response:
            text = self.container.metrics.expose_text()
            return web.Response(text=text, content_type="text/plain", charset="utf-8")

        metrics_app.router.add_get("/metrics", metrics_handler)
        return metrics_app

    # -- request pipeline ------------------------------------------------------

    async def _materialize(self, request: web.Request) -> HTTPRequest:
        body = await request.read()
        route = request.match_info.route
        template = getattr(route.resource, "canonical", request.path) if route and route.resource else request.path
        req = HTTPRequest(
            method=request.method,
            path=request.path,
            query_string=request.rel_url.query_string,
            headers=dict(request.headers),
            body=body,
            path_params=dict(request.match_info),
            remote=request.remote or "",
            route_template=template,
        )
        auth = request.get("gofr_auth")
        if auth:
            req.context().update(auth)
        qos_class = request.get("gofr_qos_class")
        if qos_class:
            # resolved by the QoS middleware; ctx.generate/infer pick it up
            # so handlers need no QoS-awareness to schedule correctly
            req.context()["qos_class"] = qos_class
        # request-lifetime plane (docs/resilience.md): the client's absolute
        # deadline, converted once to the monotonic domain; ctx.generate
        # folds the remaining budget into the engine timeout
        deadline.set_deadline(
            req.context(),
            deadline.parse_deadline_ms(req.headers.get(deadline.DEADLINE_HEADER)))
        return req

    def _wrap(self, handler: Handler):
        is_coro = inspect.iscoroutinefunction(handler)

        async def aio_handler(request: web.Request) -> web.Response:
            req = await self._materialize(request)
            ctx = Context(req, self.container, span=request.get(SPAN_KEY))
            result, err = None, None
            # effective budget: the server-side request_timeout and the
            # client's propagated deadline, whichever is tighter. An
            # already-expired deadline is shed here, before the handler
            # (and any engine submit) runs at all.
            remaining = deadline.remaining(req.context())
            deadline_bound = False
            if remaining is not None and remaining <= 0:
                self.container.metrics.increment_counter(
                    "app_request_deadline_exceeded_total", 1, where="edge")
                err = DeadlineExceeded("request deadline already expired")
                remaining = None
            budget = self.request_timeout if self.request_timeout > 0 else None
            if remaining is not None and (budget is None or remaining < budget):
                budget, deadline_bound = remaining, True
            try:
                if err is None:
                    if is_coro:
                        coro = handler(ctx)
                    else:
                        loop = asyncio.get_running_loop()
                        coro = loop.run_in_executor(self._executor, handler, ctx)
                    if budget is not None:
                        result = await asyncio.wait_for(coro, timeout=budget)
                    else:
                        result = await coro
            except asyncio.TimeoutError:
                if deadline_bound:
                    # the CLIENT's clock ran out, not ours: 504, and any
                    # engine work this context submitted is cancelled so
                    # slots/pages stop burning for an answer nobody reads
                    self.container.metrics.increment_counter(
                        "app_request_deadline_exceeded_total", 1, where="edge")
                    ctx.cancel_inflight("deadline")
                    err = DeadlineExceeded()
                else:
                    ctx.cancel_inflight("timeout")
                    err = RequestTimeout()
            except asyncio.CancelledError:
                # client closed the socket mid-handler: propagate to every
                # engine Request this context submitted (cooperative
                # cancellation, docs/resilience.md), then let aiohttp
                # finish tearing the transport down
                ctx.cancel_inflight("client_disconnect")
                raise
            except Exception as e:  # noqa: BLE001
                err = e
                if not hasattr(e, "status_code"):
                    self.logger.log_exception(e, f"handler {request.method} {request.path}")
            if err is None and isinstance(result, RawStreamingResponse):
                return await self._stream_raw(request, result)
            if err is None and isinstance(result, StreamingResponse):
                return await self._stream_sse(request, result)
            wire = respond(result, err, request.method)
            # a header-borne Content-Type (proxy Passthrough: the replica's
            # verbatim value, parameters included) wins — aiohttp rejects
            # parameterized values in the content_type argument
            has_ct = any(k.lower() == "content-type" for k in wire.headers)
            return web.Response(
                body=wire.body,
                status=wire.status,
                content_type=None if has_ct else wire.content_type,
                headers=wire.headers,
            )

        return aio_handler

    async def _stream_sse(self, request: web.Request, stream: StreamingResponse) -> web.StreamResponse:
        """Drive a StreamingResponse as text/event-stream. Items are pulled
        on the executor (the engine's stream queue blocks); each flush makes
        the token visible to the client before generation finishes."""
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Accel-Buffering": "no"},
        )
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        sentinel = object()
        try:
            while True:
                item = await loop.run_in_executor(self._executor, next, stream.iterator, sentinel)
                if item is sentinel:
                    break
                # chaos point "client.disconnect" (drop action): the storm
                # drill's deterministic mid-stream client hangup — exercises
                # the REAL disconnect path below, not a shortcut around it
                if chaos_fire("client.disconnect"):
                    raise ConnectionResetError("chaos: injected client disconnect")
                await resp.write(stream.encode_sse(item))
            await resp.write(StreamingResponse.sse_done())
        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
            # client went away mid-decode: cancel the generation so the
            # engine frees the slot/pages instead of decoding for a ghost
            self._cancel_stream(stream)
            raise
        except Exception as e:  # noqa: BLE001 - surface mid-stream failure in-band
            self.logger.log_exception(e, "sse stream")
            self._cancel_stream(stream)
            try:
                await resp.write(StreamingResponse.sse_error(str(e)))
            except Exception:  # noqa: BLE001 - client already gone
                return resp
        try:
            await resp.write_eof()
        except Exception:  # noqa: BLE001 - broken transport on eof
            pass
        return resp

    async def _stream_raw(self, request: web.Request, stream: RawStreamingResponse) -> web.StreamResponse:
        """Drive a RawStreamingResponse: write the handler's wire chunks
        through verbatim (proxy passthrough — the router's SSE hop). Chunks
        are pulled on the executor (the upstream read blocks); a client
        disconnect closes the upstream iterator so the proxied transfer is
        aborted, not drained."""
        headers = {k: v for k, v in stream.headers.items()
                   if k.lower() not in ("content-length", "transfer-encoding",
                                        "connection", "content-encoding")}
        if not any(k.lower() == "content-type" for k in headers):
            headers["Content-Type"] = stream.content_type
        resp = web.StreamResponse(status=stream.status, headers=headers)
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        sentinel = object()
        try:
            while True:
                chunk = await loop.run_in_executor(self._executor, next, stream.iterator, sentinel)
                if chunk is sentinel:
                    break
                if chaos_fire("client.disconnect"):
                    raise ConnectionResetError("chaos: injected client disconnect")
                if chunk:
                    await resp.write(chunk)
        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
            stream.close()
            raise
        except Exception as e:  # noqa: BLE001 - upstream died mid-proxy; the
            # status line is already on the wire, so all we can do is stop
            self.logger.log_exception(e, "raw stream proxy")
            stream.close()
        try:
            await resp.write_eof()
        except Exception:  # noqa: BLE001 - broken transport on eof
            pass
        return resp

    @staticmethod
    def _cancel_stream(stream: StreamingResponse) -> None:
        cancel = getattr(stream.iterator, "cancel", None)
        if callable(cancel):
            cancel()

    def _wrap_ws(self, handler: Handler):
        is_coro = inspect.iscoroutinefunction(handler)

        async def ws_handler(request: web.Request) -> web.StreamResponse:
            ws = web.WebSocketResponse()
            if not ws.can_prepare(request).ok:
                return await self._not_found_handler(request)
            await ws.prepare(request)
            # server-generated id: the Sec-WebSocket-Key header is client
            # controlled and duplicates would cross-wire hub entries
            conn_id = uuid.uuid4().hex
            self.ws_hub.add(conn_id, ws)
            loop = asyncio.get_running_loop()
            try:
                async for msg in ws:
                    if msg.type not in (WSMsgType.TEXT, WSMsgType.BINARY):
                        continue
                    conn = WSConnection(conn_id, ws, msg.data, loop)
                    ctx = Context(conn, self.container)
                    try:
                        if is_coro:
                            result = await handler(ctx)
                        else:
                            result = await loop.run_in_executor(self._executor, handler, ctx)
                    except Exception as e:  # noqa: BLE001
                        self.logger.log_exception(e, "websocket handler")
                        await ws.send_str(to_json({"error": {"message": "handler error"}}).decode())
                        continue
                    if isinstance(result, StreamingResponse):
                        # token streaming: one ws message per item, pulled on
                        # the executor (websocket.go:37-53 parity, per-token).
                        # A mid-stream engine error becomes an in-band error
                        # frame — the connection survives; a transport error
                        # cancels the generation so the slot is freed.
                        sentinel = object()
                        try:
                            while True:
                                item = await loop.run_in_executor(
                                    self._executor, next, result.iterator, sentinel)
                                if item is sentinel:
                                    break
                                await ws.send_str(result.encode_ws(item))
                            await ws.send_str(to_json({"done": True}).decode())
                        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
                            self._cancel_stream(result)
                            raise
                        except Exception as e:  # noqa: BLE001
                            self.logger.log_exception(e, "websocket token stream")
                            self._cancel_stream(result)
                            await ws.send_str(to_json(
                                {"error": {"message": str(e)}, "done": True}).decode())
                    elif result is not None:
                        payload = result if isinstance(result, str) else to_json(result).decode()
                        await ws.send_str(payload)
            finally:
                self.ws_hub.remove(conn_id)
            return ws

        return ws_handler

    # -- built-in handlers -----------------------------------------------------

    async def _health_handler(self, _request: web.Request) -> web.Response:
        health = await asyncio.get_running_loop().run_in_executor(self._executor, self.container.health)
        status = 200 if health["status"] != "DOWN" else 503
        return web.Response(body=to_json({"data": health}), status=status, content_type="application/json")

    async def _alive_handler(self, _request: web.Request) -> web.Response:
        return web.json_response({"data": {"status": "UP"}})

    async def _favicon_handler(self, _request: web.Request) -> web.Response:
        return web.Response(body=b"", content_type="image/x-icon")

    async def _not_found_handler(self, _request: web.Request) -> web.Response:
        return web.json_response({"error": {"message": "route not registered"}}, status=404)

    def _static_handler(self, directory: str):
        """Static file serving with the reference's hardening
        (`http/router.go:62-82`): ``openapi.json`` must never be fetchable
        through a static mount — the spec is served, access-controlled and
        versioned, at ``/.well-known/openapi.json`` only — so a direct
        download attempt gets 403; path traversal out of the mounted
        directory gets 404 like any other absent file."""
        import pathlib

        base = pathlib.Path(directory).resolve()

        async def handler(request: web.Request) -> web.StreamResponse:
            tail = request.match_info.get("static_tail", "")
            if pathlib.PurePosixPath(tail).name == "openapi.json":
                return web.json_response(
                    {"error": {"message": "openapi.json is not downloadable from "
                                          "static routes; use /.well-known/openapi.json"}},
                    status=403)
            try:
                target = (base / tail).resolve()
            except (OSError, ValueError):
                return await self._not_found_handler(request)
            if base not in target.parents and target != base:
                return await self._not_found_handler(request)
            if not target.is_file():
                return await self._not_found_handler(request)
            return web.FileResponse(target)

        return handler

    # -- profiling (SURVEY §5.1; reference http_server.go:53-60) ---------------

    def _debug_env(self) -> bool:
        return self.config.get_or_default("APP_ENV", "").upper() == "DEBUG"

    def _profiler_port_base(self) -> int | None:
        """Resolve PROFILER_PORT: an explicit port, ``auto`` (derived from
        the serving port, so co-hosted replicas with distinct HTTP_PORTs
        get distinct profiler ports for free), or <=0/garbage = disabled."""
        raw = str(self.config.get_or_default("PROFILER_PORT", "9999")).strip().lower()
        if raw == "auto":
            return self.http_port + 1999  # default HTTP 8000 -> classic 9999
        try:
            base = int(raw)
        except ValueError:
            self.logger.warn(f"PROFILER_PORT {raw!r} is not a port or 'auto'; "
                             "profiler server disabled")
            return None
        return base if base > 0 else None

    @staticmethod
    def _bindable_port(base: int, tries: int = 16) -> int | None:
        """First bindable port in [base, base+tries): N replicas sharing a
        host (and a PROFILER_PORT default) each walk to a free port instead
        of the second-and-later ones logging a bind failure every boot."""
        import socket

        for port in range(base, base + tries):
            try:
                with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind(("0.0.0.0", port))
                return port
            except OSError:
                continue
        return None

    def _start_profiler_server(self) -> None:
        """jax.profiler gRPC server for live tensorboard/xprof attach, on
        PROFILER_PORT (<=0 disables, 'auto' derives from the serving port;
        a busy port retries upward). DEBUG-gated like the pprof routes."""
        base = self._profiler_port_base()
        if base is None:
            return
        port = self._bindable_port(base)
        if port is None:
            self.logger.warn(f"no free profiler port in [{base}, {base + 16}); "
                             "profiler server disabled")
            return
        try:
            import jax

            jax.profiler.start_server(port)
            self.logger.infof("jax profiler server on :%d (APP_ENV=DEBUG)", port)
        except Exception as e:  # noqa: BLE001 - profiling must never block serving
            self.logger.warn(f"profiler server failed to start: {e}")

    async def _profile_handler(self, request: web.Request) -> web.Response:
        """GET /debug/profile?seconds=N → capture an xplane trace of whatever
        the engines/handlers are doing for N seconds; returns the trace dir
        (open with tensorboard/xprof). Bounded so a stray curl can't pin the
        process or fill disk: absurd N is a 400 (sane N still clamps to
        [0.1, 60]), and only ONE capture runs at a time — 409 while busy."""
        try:
            seconds = float(request.query.get("seconds", "2"))
            if not math.isfinite(seconds):
                raise ValueError(seconds)
        except ValueError:
            return web.json_response(
                {"error": {"message": "seconds must be a finite number"}}, status=400)
        if seconds <= 0 or seconds > 300.0:
            return web.json_response(
                {"error": {"message": "seconds must be in (0, 300]"}}, status=400)
        seconds = min(max(seconds, 0.1), 60.0)
        if not self._profile_busy.acquire(blocking=False):
            return web.json_response(
                {"error": {"message": "a profile capture is already running"}},
                status=409)
        out_root = self.config.get_or_default("PROFILER_DIR", "/tmp/gofr_tpu_profile")

        def capture() -> str:
            import time as _time

            import jax

            path = os.path.join(out_root, _time.strftime("trace-%Y%m%d-%H%M%S"))
            with jax.profiler.trace(path):
                _time.sleep(seconds)
            return path

        loop = asyncio.get_running_loop()
        try:
            path = await loop.run_in_executor(self._executor, capture)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": {"message": str(e)}}, status=500)
        finally:
            self._profile_busy.release()
        return web.json_response({"data": {"trace_dir": path, "seconds": seconds}})

    @staticmethod
    def _debug_limit(request: web.Request) -> int | None:
        try:
            n = int(request.query.get("n", "0"))
        except ValueError:
            n = 0
        return n if n > 0 else None

    async def _debug_requests_handler(self, request: web.Request) -> web.Response:
        """GET /debug/requests?n=K → the last K completed request timelines
        (newest first) from the always-on flight recorder: queue wait, TTFT,
        TPOT, e2e, slot, preemptions, trace id — incident diagnosis without
        a trace backend attached (docs/observability.md)."""
        entries = self.container.flight.requests(limit=self._debug_limit(request))
        return web.json_response({"data": {"count": len(entries), "requests": entries}})

    async def _debug_engine_handler(self, request: web.Request) -> web.Response:
        """GET /debug/engine?n=K → the last K device steps (kind, wall time,
        batch occupancy, compile signature, backlog) plus a health snapshot
        of every served engine, including the warmup autotuner's pinned
        kernel backend per op with its timings (ops/autotune.py)."""
        steps = self.container.flight.steps(limit=self._debug_limit(request))
        engines = {}
        for name, engine in self.container.engines.items():
            snap = engine.health_check() if hasattr(engine, "health_check") else {}
            layout = getattr(engine, "kv_layout", None)
            if layout is not None:
                # the KV-pool dimension a kv-dtype A/B flips (ENGINE_KV_DTYPE;
                # docs/kernels.md): '' quantize means the dense bf16 pool
                snap = dict(snap)
                snap["kv"] = {
                    "layout": layout,
                    "dtype": getattr(engine, "kv_quantize", "") or "bf16",
                }
            report = getattr(engine, "autotune_report", None)
            rep = report() if report is not None else None
            if rep:
                snap = dict(snap)
                snap["autotune"] = rep
            ad_stats = getattr(engine, "adapter_stats", None)
            if callable(ad_stats):
                # adapter plane occupancy + the base-weight epoch
                # (gofr_tpu.adapters; docs/serving.md)
                snap = dict(snap)
                snap["adapters"] = ad_stats()
            engines[name] = snap
        return web.json_response(
            {"data": {"count": len(steps), "steps": steps, "engines": engines}})

    async def _debug_perf_handler(self, request: web.Request) -> web.Response:
        """GET /debug/perf → the live roofline view (metrics/perf.py): per
        engine a windowed MFU/MBU snapshot per step kind, the pipeline
        bubble ratio, the page-pool waste stats, and every autotune-pinned
        op joined with the roofline estimate of the step kind it runs in —
        "is the pinned kernel the bottleneck, or is the device starved?"
        answered from one endpoint (docs/observability.md)."""
        import time as _time

        now = _time.monotonic()
        engines = {}
        for name, engine in self.container.engines.items():
            plane = getattr(engine, "perf", None)
            if plane is None:
                continue
            snap = plane.snapshot(now)
            stats_fn = getattr(engine, "page_pool_stats", None)
            stats = stats_fn() if callable(stats_fn) else None
            if stats:
                snap["page_pool"] = stats
            report = getattr(engine, "autotune_report", None)
            rep = report() if report is not None else None
            if rep and rep.get("decisions"):
                # every warmed op today is a decode-step kernel, so each
                # pin joins the "decode" kind's roofline; spec engines
                # fold the same pinned op inside "spec" steps too
                kinds = snap.get("kinds", {})
                joined = {}
                for op, rec in rep["decisions"].items():
                    roof = {k: kinds[k] for k in ("decode", "spec")
                            if k in kinds}
                    joined[op] = {"pin": rec, "roofline": roof or None}
                snap["autotune"] = joined
            ho_fn = getattr(engine, "handoff_stats", None)
            ho = ho_fn() if callable(ho_fn) else None
            if ho and ("export" in ho or "import" in ho):
                # disaggregation transfer plane (tpu/handoff.py): mode,
                # negotiated stream count, per-stream bytes/seconds and
                # the overlap ratio join the roofline view — "is the
                # handoff hiding behind prefill compute?" from the same
                # endpoint as "is the device starved?"
                snap["handoff"] = ho
            engines[name] = snap
        totals = self.container.perf_totals()
        fleet = None
        if totals is not None:
            from gofr_tpu.metrics import perf as perf_mod

            fleet = {"totals": totals, **perf_mod.derive(totals)}
        return web.json_response({"data": {"engines": engines, "rollup": fleet}})

    async def _debug_control_handler(self, request: web.Request) -> web.Response:
        """GET /debug/control → the online step controller's live state
        (gofr_tpu.control; docs/serving.md): per engine the knob vector
        with each knob's allowed range and frozen flag, the persisted pins
        for this replica's (kv dtype, device kind, shard) context, the
        hysteresis gate internals, the in-progress trial, the last judged
        evidence window, and the bounded decision ring — "who changed what
        knob, when, and on what evidence" answered with nothing but curl.
        Engines without a controller report {enabled: false} plus their
        static knob vector so the fleet view stays uniform."""
        engines = {}
        for name, engine in self.container.engines.items():
            report = getattr(engine, "control_report", None)
            if callable(report):
                engines[name] = report()
        decisions = self.container.flight.controls(
            limit=self._debug_limit(request))
        return web.json_response(
            {"data": {"engines": engines, "decisions": decisions}})

    async def _debug_quality_handler(self, request: web.Request) -> web.Response:
        """GET /debug/quality → the numerics/quality plane joined with the
        serving state that produced it (metrics/quality.py; docs/
        observability.md): per engine the shadow-scorer totals and recent
        per-sample divergence reports keyed by autotune pins, weights epoch
        and kv dtype, the per-adapter speculative-decode acceptance ratios
        (the always-on quality proxy), and each class's quality SLO windows
        — "are the tokens still right, and if not, since when and under
        which configuration" answered from one endpoint."""
        engines = {}
        for name, engine in self.container.engines.items():
            entry: dict = {}
            snap_fn = getattr(engine, "quality_snapshot", None)
            snap = snap_fn() if callable(snap_fn) else None
            if snap is not None:
                # trim replay payloads off the live view; bundles carry them
                snap = dict(snap)
                snap["recent"] = [
                    {k: v for k, v in e.items() if k not in ("prompt", "emitted")}
                    for e in snap.get("recent", [])]
                entry["shadow"] = snap
            totals_fn = getattr(engine, "spec_accept_totals", None)
            totals = totals_fn() if callable(totals_fn) else None
            if totals:
                entry["spec_accept"] = {
                    adapter: {
                        "accepted": acc, "proposed": prop,
                        "ratio": round(acc / prop, 4) if prop else None,
                    } for adapter, (acc, prop) in totals.items()}
            if entry:
                engines[name] = entry
        slo = getattr(self.container, "slo", None)
        objectives = None
        if slo is not None:
            objectives = {
                cls: {"quality": objs["quality"]}
                for cls, objs in slo.snapshot().items() if "quality" in objs}
        return web.json_response(
            {"data": {"engines": engines, "slo": objectives}})

    def _add_openapi_routes(self, http_app: web.Application) -> None:
        from gofr_tpu.swagger import openapi_handler, swagger_ui_handler

        http_app.router.add_get("/.well-known/openapi.json", openapi_handler(self))
        http_app.router.add_get("/.well-known/swagger", swagger_ui_handler(self))

    # -- subscription manager (gofr subscriber.go) -----------------------------

    def _start_subscribers(self) -> None:
        # SUBSCRIBER_WORKERS > 1 runs N consumer threads per topic — the
        # consumer-group-partition parallelism analog (subscriber.go spawns
        # one goroutine per topic). With a model engine in the handler, the
        # concurrent handlers are what lets the engine micro-batch: N
        # in-flight messages fill one device batch instead of serializing.
        workers = max(1, self.config.get_int("SUBSCRIBER_WORKERS", 1))
        for topic, handler in self._subscriptions.items():
            for w in range(workers):
                t = threading.Thread(
                    target=self._subscribe_loop, args=(topic, handler),
                    name=f"gofr-sub-{topic}-{w}", daemon=True,
                )
                t.start()
                self._sub_threads.append(t)

    def _subscribe_loop(self, topic: str, handler: Handler) -> None:
        container = self.container
        group = self.config.get_or_default("CONSUMER_GROUP", container.app_name)
        while not self._sub_stop.is_set():
            try:
                msg = container.pubsub.subscribe(topic, group=group, timeout=0.5)
            except Exception as e:  # noqa: BLE001
                container.logger.errorf("subscribe %s failed: %r", topic, e)
                self._sub_stop.wait(1.0)
                continue
            if msg is None:
                continue
            container.metrics.increment_counter("app_pubsub_subscribe_total_count", 1, topic=topic)
            # join the publisher's trace when the message carries one
            # (Context.publish stamps traceparent into the broker headers)
            span = container.tracer.start_span(
                f"subscribe {topic}", kind="CONSUMER", set_current=False,
                traceparent=msg.param("traceparent") or None)
            ctx = Context(msg, container, span=span)
            try:
                result = handler(ctx)
                if inspect.iscoroutine(result):
                    raise TypeError("subscribe handlers must be synchronous (they run on a consumer thread)")
                # chaos point "pubsub.commit": the crash-between-handler-
                # and-commit window — the at-least-once contract's hard
                # case (handler effects applied, offset not advanced, so
                # the message is redelivered; fleet/chaos.py, tested in
                # tests/test_pubsub_clients.py). Zero-cost when unarmed.
                chaos_fire("pubsub.commit", topic=topic)
                msg.commit()  # at-least-once: commit only on success (subscriber.go:54-56)
                container.metrics.increment_counter("app_pubsub_subscribe_success_count", 1, topic=topic)
                span.set_status("OK")
            except Exception as e:  # noqa: BLE001
                span.set_status("ERROR")
                container.logger.errorf("subscriber for %s failed: %r", topic, e)
            finally:
                span.finish()

    # -- run -------------------------------------------------------------------

    def run(self) -> None:
        """Start every configured server; blocks until SIGINT/SIGTERM."""
        try:
            asyncio.run(self.arun())
        except KeyboardInterrupt:
            pass

    async def arun(self, ready: asyncio.Event | None = None) -> None:
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._shutdown.set)
            except (NotImplementedError, RuntimeError):
                pass

        if self._debug_env():
            self._start_profiler_server()

        # engines first (device warm-up), then servers. ENGINE_WARMUP=true
        # front-loads every program compile AND the kernel-backend autotune
        # (docs/serving.md: seconds at boot instead of inside the first
        # requests' latency window; generate engines need no example).
        warm = self.config.get_or_default("ENGINE_WARMUP", "false").lower() == "true"
        for name, engine in self.container.engines.items():
            if warm and hasattr(engine, "warmup"):
                # signature-probed, NOT try/except TypeError around the call
                # — that would conflate "needs an example input" (BatchEngine;
                # app boot has none, first traffic compiles as before) with a
                # genuine TypeError from inside warmup (same rationale as
                # container._pubsub_supports_headers)
                import inspect

                try:
                    needs_example = any(
                        p.default is p.empty
                        and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                        for p in inspect.signature(engine.warmup).parameters.values())
                except (TypeError, ValueError):
                    needs_example = True
                if not needs_example:
                    try:
                        n = engine.warmup()
                        self.logger.infof("model engine %s warmed (%d programs)", name, n)
                    except Exception as e:  # noqa: BLE001 - warmup is an
                        # optimization: surface the failure loudly but let the
                        # engine serve (first traffic compiles lazily)
                        self.logger.log_exception(e, f"engine {name} warmup failed")
            if hasattr(engine, "start"):
                engine.start()
                self.logger.infof("model engine %s started", name)

        metrics_runner = web.AppRunner(self._build_metrics_app())
        await metrics_runner.setup()
        await web.TCPSite(metrics_runner, host="0.0.0.0", port=self.metrics_port).start()
        self._runners.append(metrics_runner)
        self.logger.infof("metrics server on :%d/metrics", self.metrics_port)

        if self._routes or self._ws_routes or self._static or self._debug_env():
            http_runner = web.AppRunner(self._build_http_app())
            await http_runner.setup()
            await web.TCPSite(http_runner, host="0.0.0.0", port=self.http_port).start()
            self._runners.append(http_runner)
            self.logger.infof("HTTP server on :%d", self.http_port)

        grpc_server = None
        if self._grpc_services:
            from gofr_tpu.grpc.server import start_grpc_server

            grpc_server = start_grpc_server(self)
            self.logger.infof("gRPC server on :%d", self.grpc_port)

        self._start_subscribers()
        self.cron.start()
        if self._gossip is not None:
            # after the engines: the first snapshot reports real health
            self._gossip.start()

        if ready is not None:
            ready.set()
        await self._shutdown.wait()
        self.logger.info("shutting down")
        if self._gossip is not None:
            self._gossip.stop()  # terminal DOWN leaves the router ring now
        for fn in self._cleanup:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 - one hook must not block the rest
                self.logger.log_exception(e, "cleanup hook")
        self._sub_stop.set()
        self.cron.stop()
        if grpc_server is not None:
            grpc_server.stop(grace=2)
        for runner in self._runners:
            await runner.cleanup()
        self.container.close()
        self._executor.shutdown(wait=False, cancel_futures=True)

    def stop(self) -> None:
        self._shutdown.set()


def new(config_folder: str = "./configs", config=None) -> App:
    """gofr.New() analog."""
    return App(config_folder=config_folder, config=config)


def new_cmd(config_folder: str = "./configs", config=None):
    """gofr.NewCMD() analog: a CLI app sharing the container/Context model."""
    from gofr_tpu.cli import CmdApp

    cfg = config if config is not None else EnvConfig(folder=config_folder)
    return CmdApp(Container.create(cfg))


def new_testing(config: dict[str, str] | None = None) -> App:
    """App wired to a mock container for tests."""
    from gofr_tpu.container import new_mock_container

    cfg = DictConfig(config or {})
    return App(config=cfg, container=new_mock_container(config))
