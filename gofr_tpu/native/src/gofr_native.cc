// gofr_tpu native runtime core.
//
// Two host-side hot paths live here, off the Python GIL (SURVEY.md §7 —
// the reference keeps its runtime in Go; the TPU build's host runtime is
// C++ around the XLA device loop):
//
//  1. Prefill planner: EDF + bucket-affinity batch packing for the
//     continuous-batching engine. Given pending request metadata it picks
//     which requests to prefill together and at which (len, batch) bucket,
//     minimizing padding FLOPs while honoring deadlines.
//  2. Token data loader: mmap'd token corpus with a background prefetch
//     thread producing fixed-shape [batch, seqlen+1] crops into a ring
//     buffer for the training input pipeline.
//
// C ABI (ctypes-friendly): plain ints/pointers only.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// 1) Prefill planner
// ---------------------------------------------------------------------------

// Pick the batch to prefill next.
//   lens[n], deadlines_us[n] (0 = no deadline), arrival order = index order.
//   len_buckets[n_buckets] ascending; free_slots / max_batch cap the batch.
// Writes chosen request indices into out_chosen (cap max_batch), expired
// indices into out_expired (cap n), bucket results into out_len_bucket /
// out_batch_bucket. Returns the number chosen; *out_n_expired set.
//
// Policy: requests past deadline — or longer than the largest length
// bucket (unschedulable, ever) — are reported in out_expired. The
// earliest-deadline (ties: FIFO) request leads; the batch is filled, in
// EDF order, only with requests that fit the leader's length bucket — a
// longer request never inflates everyone's padding, it simply leads its
// own batch next round.
int gofr_plan_prefill(
    const int32_t* lens, const int64_t* deadlines_us, int32_t n,
    int64_t now_us, int32_t free_slots, int32_t max_batch,
    const int32_t* len_buckets, int32_t n_buckets,
    int32_t* out_chosen, int32_t* out_expired, int32_t* out_n_expired,
    int32_t* out_len_bucket, int32_t* out_batch_bucket) {
  *out_n_expired = 0;
  *out_len_bucket = 0;
  *out_batch_bucket = 0;
  if (n <= 0) return 0;

  // expiry is reported even when no slot is free — the engine must fail
  // timed-out requests promptly, not strand them in the pending list
  const int32_t max_bucket = len_buckets[n_buckets - 1];
  std::vector<int32_t> valid;
  valid.reserve(n);
  for (int32_t i = 0; i < n; ++i) {
    if ((deadlines_us[i] > 0 && deadlines_us[i] < now_us) || lens[i] > max_bucket) {
      out_expired[(*out_n_expired)++] = i;
    } else {
      valid.push_back(i);
    }
  }
  if (valid.empty() || free_slots <= 0 || max_batch <= 0) return 0;

  std::stable_sort(valid.begin(), valid.end(), [&](int32_t a, int32_t b) {
    int64_t da = deadlines_us[a] > 0 ? deadlines_us[a] : INT64_MAX;
    int64_t db = deadlines_us[b] > 0 ? deadlines_us[b] : INT64_MAX;
    if (da != db) return da < db;
    return a < b;  // FIFO tie-break
  });

  // leader sets the length bucket
  int32_t lead_len = lens[valid[0]];
  int32_t bucket = len_buckets[n_buckets - 1];
  for (int32_t bi = 0; bi < n_buckets; ++bi) {
    if (len_buckets[bi] >= lead_len) { bucket = len_buckets[bi]; break; }
  }

  int32_t cap = std::min(free_slots, max_batch);
  int32_t count = 0;
  for (int32_t idx : valid) {
    if (count >= cap) break;
    if (lens[idx] <= bucket) out_chosen[count++] = idx;
  }

  // batch bucket: next power of two >= count (bounded by max_batch)
  int32_t bb = 1;
  while (bb < count) bb <<= 1;
  if (bb > max_batch) bb = max_batch;

  *out_len_bucket = bucket;
  *out_batch_bucket = bb;
  return count;
}

// ---------------------------------------------------------------------------
// 2) Token data loader
// ---------------------------------------------------------------------------

struct Loader {
  const int32_t* tokens = nullptr;   // mmap'd
  int64_t n_tokens = 0;
  int fd = -1;
  size_t map_len = 0;
  bool owns_copy = false;            // fallback: buffer copied from caller

  int32_t batch = 0;
  int32_t seqlen = 0;                // yields [batch, seqlen + 1] (inputs+target)
  uint64_t seed = 0;

  std::vector<std::vector<int32_t>> ring;  // prefetched batches
  size_t ring_cap = 0;
  size_t head = 0, tail = 0, filled = 0;
  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::thread worker;
  std::atomic<bool> stop{false};
  uint64_t counter = 0;

  void fill_batch(std::vector<int32_t>& out) {
    // splitmix64 per (seed, counter) → deterministic, seekable stream
    const int64_t span = seqlen + 1;
    for (int32_t b = 0; b < batch; ++b) {
      uint64_t z = seed + 0x9E3779B97F4A7C15ULL * (++counter);
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      z ^= z >> 31;
      int64_t max_start = n_tokens - span;
      int64_t start = max_start > 0 ? static_cast<int64_t>(z % static_cast<uint64_t>(max_start + 1)) : 0;
      std::memcpy(out.data() + static_cast<size_t>(b) * span,
                  tokens + start, static_cast<size_t>(span) * sizeof(int32_t));
    }
  }

  void run() {
    while (!stop.load()) {
      std::unique_lock<std::mutex> lk(mu);
      cv_full.wait(lk, [&] { return stop.load() || filled < ring_cap; });
      if (stop.load()) return;
      auto& slot = ring[tail];
      lk.unlock();
      fill_batch(slot);           // copy outside the lock
      lk.lock();
      tail = (tail + 1) % ring_cap;
      ++filled;
      cv_empty.notify_one();
    }
  }
};

void* gofr_loader_create(const char* path, int32_t batch, int32_t seqlen,
                         uint64_t seed, int32_t prefetch) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>((seqlen + 1) * sizeof(int32_t))) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* l = new Loader();
  l->tokens = static_cast<const int32_t*>(map);
  l->n_tokens = st.st_size / sizeof(int32_t);
  l->fd = fd;
  l->map_len = st.st_size;
  l->batch = batch;
  l->seqlen = seqlen;
  l->seed = seed;
  l->ring_cap = prefetch > 0 ? prefetch : 2;
  l->ring.assign(l->ring_cap, std::vector<int32_t>(
      static_cast<size_t>(batch) * (seqlen + 1)));
  l->worker = std::thread([l] { l->run(); });
  return l;
}

// Blocks until a prefetched batch is ready; copies it into out
// [batch * (seqlen+1)] int32. Returns 0 on success.
int gofr_loader_next(void* handle, int32_t* out) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_empty.wait(lk, [&] { return l->stop.load() || l->filled > 0; });
  if (l->stop.load()) return 1;
  auto& slot = l->ring[l->head];
  std::memcpy(out, slot.data(), slot.size() * sizeof(int32_t));
  l->head = (l->head + 1) % l->ring_cap;
  --l->filled;
  l->cv_full.notify_one();
  return 0;
}

int64_t gofr_loader_num_tokens(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

void gofr_loader_destroy(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  l->stop.store(true);
  l->cv_full.notify_all();
  l->cv_empty.notify_all();
  if (l->worker.joinable()) l->worker.join();
  if (l->tokens && !l->owns_copy) munmap(const_cast<int32_t*>(l->tokens), l->map_len);
  if (l->fd >= 0) ::close(l->fd);
  delete l;
}

}  // extern "C"
