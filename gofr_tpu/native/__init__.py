"""Native (C++) host-runtime core: prefill planner + token data loader.

The shared library is compiled from ``src/gofr_native.cc`` on first use
(g++, cached next to the source) and bound via ctypes — no pybind11, no
build step for users. Every entry point has a pure-Python fallback with
IDENTICAL semantics (tested against each other), so the framework degrades
gracefully where a toolchain is missing; ``GOFR_NATIVE=0`` forces the
fallback.

Reference capability map: GoFr's runtime is Go (SURVEY.md §2) — the TPU
build keeps Python as the orchestration layer and moves the schedule/IO
hot paths native, mirroring how the reference leans on its compiled
runtime rather than an interpreter.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "gofr_native.cc")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "src", "libgofr_native.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _build() -> str | None:
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
        return _LIB_PATH
    # compile to a private temp path and publish atomically so a concurrent
    # process can never dlopen a half-written .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load_native() -> ctypes.CDLL | None:
    """The shared library, building it if needed; None when unavailable."""
    global _lib, _lib_failed
    if os.environ.get("GOFR_NATIVE", "") == "0":
        return None
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        path = _build()
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _lib_failed = True
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.gofr_plan_prefill.restype = ctypes.c_int32
        lib.gofr_plan_prefill.argtypes = [
            i32p, i64p, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, i32p, ctypes.c_int32, i32p, i32p, i32p, i32p, i32p,
        ]
        lib.gofr_loader_create.restype = ctypes.c_void_p
        lib.gofr_loader_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_int32,
        ]
        lib.gofr_loader_next.restype = ctypes.c_int32
        lib.gofr_loader_next.argtypes = [ctypes.c_void_p, i32p]
        lib.gofr_loader_num_tokens.restype = ctypes.c_int64
        lib.gofr_loader_num_tokens.argtypes = [ctypes.c_void_p]
        lib.gofr_loader_destroy.restype = None
        lib.gofr_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native() is not None


# ---------------------------------------------------------------------------
# Prefill planner
# ---------------------------------------------------------------------------


@dataclass
class PrefillPlan:
    chosen: list[int]       # indices into the pending list, EDF order
    expired: list[int]      # past deadline OR longer than every bucket
    len_bucket: int
    batch_bucket: int


def _plan_prefill_py(
    lens, deadlines_us, now_us: int, free_slots: int, max_batch: int, len_buckets
) -> PrefillPlan:
    """Reference implementation — semantics identical to gofr_plan_prefill."""
    max_bucket = len_buckets[-1]
    expired = [
        i for i in range(len(lens))
        if 0 < deadlines_us[i] < now_us or lens[i] > max_bucket
    ]
    dead = set(expired)
    valid = [i for i in range(len(lens)) if i not in dead]
    if not valid or free_slots <= 0 or max_batch <= 0:
        return PrefillPlan([], expired, 0, 0)
    valid.sort(key=lambda i: (deadlines_us[i] if deadlines_us[i] > 0 else 2**62, i))
    lead_len = lens[valid[0]]
    bucket = next((b for b in len_buckets if b >= lead_len), len_buckets[-1])
    cap = min(free_slots, max_batch)
    chosen = [i for i in valid if lens[i] <= bucket][:cap]
    bb = 1
    while bb < len(chosen):
        bb <<= 1
    return PrefillPlan(chosen, expired, bucket, min(bb, max_batch))


def plan_prefill(
    lens, deadlines_us, now_us: int, free_slots: int, max_batch: int, len_buckets
) -> PrefillPlan:
    """EDF + bucket-affinity prefill packing: the earliest-deadline request
    leads and sets the length bucket; only requests fitting that bucket
    join the batch, so one long prompt never inflates everyone's padding.
    ``deadlines_us[i] <= 0`` means no deadline. Requests longer than the
    largest bucket are unschedulable and come back in ``expired`` (the
    caller fails them) rather than starving silently."""
    lib = load_native()
    n = len(lens)
    if lib is None or n == 0:
        return _plan_prefill_py(lens, deadlines_us, now_us, free_slots, max_batch, len_buckets)
    lens_a = np.ascontiguousarray(lens, np.int32)
    dl_a = np.ascontiguousarray(deadlines_us, np.int64)
    bk_a = np.ascontiguousarray(len_buckets, np.int32)
    chosen = np.zeros((max(max_batch, 1),), np.int32)
    expired = np.zeros((n,), np.int32)
    n_exp = ctypes.c_int32(0)
    lb = ctypes.c_int32(0)
    bb = ctypes.c_int32(0)
    i32p = ctypes.POINTER(ctypes.c_int32)
    count = lib.gofr_plan_prefill(
        lens_a.ctypes.data_as(i32p),
        dl_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, now_us, free_slots, max_batch,
        bk_a.ctypes.data_as(i32p), len(len_buckets),
        chosen.ctypes.data_as(i32p), expired.ctypes.data_as(i32p),
        ctypes.byref(n_exp), ctypes.byref(lb), ctypes.byref(bb),
    )
    return PrefillPlan(
        chosen[:count].tolist(), expired[: n_exp.value].tolist(), int(lb.value), int(bb.value)
    )


# ---------------------------------------------------------------------------
# Token data loader
# ---------------------------------------------------------------------------


class TokenLoader:
    """Batches of [batch, seqlen+1] int32 crops from a flat token file
    (raw little-endian int32), prefetched by a native background thread.
    Falls back to numpy memmap + same splitmix64 crop stream."""

    def __init__(self, path: str, batch: int, seqlen: int, *, seed: int = 0, prefetch: int = 4):
        self.path, self.batch, self.seqlen, self.seed = path, batch, seqlen, seed
        self._lib = load_native()
        self._handle = None
        self._mm = None
        self._counter = 0
        if self._lib is not None:
            h = self._lib.gofr_loader_create(
                path.encode(), batch, seqlen, ctypes.c_uint64(seed), prefetch
            )
            if h:
                self._handle = ctypes.c_void_p(h)
                self.num_tokens = int(self._lib.gofr_loader_num_tokens(self._handle))
                return
        self._mm = np.memmap(path, dtype=np.int32, mode="r")
        self.num_tokens = int(self._mm.shape[0])
        if self.num_tokens < seqlen + 1:
            raise ValueError(f"corpus {path} shorter than seqlen+1={seqlen + 1}")

    @staticmethod
    def _splitmix64(z: int) -> int:
        """The splitmix64 finalizer — bit-for-bit the C++ loader's mix."""
        m = 2**64 - 1
        z &= m
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & m
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & m
        return z ^ (z >> 31)

    def next(self) -> np.ndarray:
        """→ [batch, seqlen+1] int32 (inputs are [:, :-1], targets [:, 1:])."""
        span = self.seqlen + 1
        if self._handle is not None:
            out = np.empty((self.batch, span), np.int32)
            rc = self._lib.gofr_loader_next(
                self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            )
            if rc != 0:
                raise RuntimeError("native loader stopped")
            return out
        out = np.empty((self.batch, span), np.int32)
        max_start = self.num_tokens - span
        for b in range(self.batch):
            self._counter += 1
            z = self._splitmix64(self.seed + 0x9E3779B97F4A7C15 * self._counter)
            start = z % (max_start + 1) if max_start > 0 else 0
            out[b] = self._mm[start : start + span]
        return out

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.gofr_loader_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
