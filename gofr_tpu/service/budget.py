"""Envoy-style shared retry budget: retries are a *fraction of live
traffic*, never an independent knob.

A fixed per-request retry count amplifies: when a dependency browns out
and every caller retries 3 times, the dependency sees 4x its capacity
and stays down. A budget caps the *aggregate*: retries inside a sliding
window may not exceed ``fraction`` of the original requests seen in the
same window (with a ``min_retries`` floor so a near-idle client can
still retry at all). When the budget is spent, callers fail fast with
the last error — the storm decays instead of feeding itself.

One instance is shared by everything that re-sends work: the
``service.Retry`` middleware spends a token per retry attempt, and the
router spends one per spill-on-5xx and per hedge (a hedge is a
speculative retry). Thread-safe; the clock is injectable for tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable


class RetryBudget:
    """Sliding-window retry budget (see module docstring).

    ``note_request()`` records an original request; ``try_spend()``
    asks for one retry/hedge token and answers whether the caller may
    re-send. Metrics (optional): ``app_retry_budget_spent_total`` and
    ``app_retry_budget_exhausted_total``.
    """

    def __init__(self, fraction: float = 0.2, min_retries: int = 3,
                 window_s: float = 10.0, *, metrics: Any = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fraction = float(fraction)
        self.min_retries = int(min_retries)
        self.window_s = float(window_s)
        self._metrics = metrics
        self._clock = clock
        self._reqs: deque[float] = deque()
        self._retries: deque[float] = deque()
        self._lock = threading.Lock()

    def _prune(self, now: float) -> None:
        cut = now - self.window_s
        while self._reqs and self._reqs[0] < cut:
            self._reqs.popleft()
        while self._retries and self._retries[0] < cut:
            self._retries.popleft()

    def note_request(self) -> None:
        """Record one ORIGINAL request (not a retry) in the window."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            self._reqs.append(now)

    def allowed(self) -> int:
        """Current retry allowance for the window."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            return max(self.min_retries, int(len(self._reqs) * self.fraction))

    def try_spend(self) -> bool:
        """Take one retry token. False = budget exhausted: do NOT re-send."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            cap = max(self.min_retries, int(len(self._reqs) * self.fraction))
            if len(self._retries) >= cap:
                ok = False
            else:
                self._retries.append(now)
                ok = True
        if self._metrics is not None:
            if ok:
                self._metrics.increment_counter("app_retry_budget_spent_total")
            else:
                self._metrics.increment_counter("app_retry_budget_exhausted_total")
        return ok

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            self._prune(now)
            return {
                "window_requests": len(self._reqs),
                "window_retries": len(self._retries),
                "allowed": max(self.min_retries,
                               int(len(self._reqs) * self.fraction)),
                "fraction": self.fraction,
            }

    @classmethod
    def from_config(cls, config: Any, metrics: Any = None) -> "RetryBudget":
        """RETRY_BUDGET_FRACTION / RETRY_BUDGET_MIN / RETRY_BUDGET_WINDOW_S
        (docs/configs.md)."""
        return cls(
            fraction=config.get_float("RETRY_BUDGET_FRACTION", 0.2),
            min_retries=config.get_int("RETRY_BUDGET_MIN", 3),
            window_s=config.get_float("RETRY_BUDGET_WINDOW_S", 10.0),
            metrics=metrics,
        )
