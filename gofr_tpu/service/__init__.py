"""Inter-service HTTP client: options-as-decorators with circuit breaker/retry/auth.

Parity with gofr `pkg/gofr/service/`: ``new_http_service(addr, logger, metrics,
*options)`` folds each option over the base client (`new.go:68-87`) — every
option is itself a full client wrapping the next, so auth, retry and circuit
breaking compose freely. Every request gets a client span, traceparent
injection, a structured log and an ``app_http_service_response`` histogram
(`new.go:140-197`). Health checks GET ``/.well-known/alive`` (`health.go:20-35`).
"""

from __future__ import annotations

import base64
import random
import threading
import time
from typing import Any

import httpx

from gofr_tpu.tracing import current_span


class ServiceResponse:
    def __init__(self, status_code: int, body: bytes, headers: dict[str, str]):
        self.status_code = status_code
        self.body = body
        self.headers = headers

    def json(self) -> Any:
        import json

        return json.loads(self.body)

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300

    def close(self) -> None:  # symmetry with StreamedServiceResponse
        pass


class StreamedServiceResponse:
    """Headers-first response for ``request(..., stream=True)``: status and
    headers are available immediately, the body arrives incrementally
    through ``iter_content`` — the shape SSE/chunked proxying needs
    (router data plane, docs/routing.md). The caller MUST exhaust
    ``iter_content`` or call ``close()``; the underlying connection is
    held until then, and ``close()`` mid-stream aborts the upstream
    transfer (client-cancel propagation)."""

    def __init__(self, resp: "httpx.Response"):
        self._resp = resp
        self.status_code = resp.status_code
        self.headers = dict(resp.headers)
        self._closed = False

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300

    def iter_content(self, chunk_size: int | None = None):
        """Body chunks AS THEY ARRIVE (``iter_raw`` — a fixed chunk_size
        would buffer until full, which breaks SSE frame latency; the
        request pinned identity encoding so raw == decoded). Closes on
        exhaustion and on generator teardown, so a ``break`` releases the
        connection too."""
        try:
            yield from self._resp.iter_raw(chunk_size)
        finally:
            self.close()

    def read(self) -> bytes:
        """Materialize the remaining body (spillover decisions need the
        error envelope of a non-streamed 4xx/5xx) and close."""
        try:
            return self._resp.read()
        finally:
            self.close()

    def json(self) -> Any:
        import json

        return json.loads(self.read())

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._resp.close()


class HTTPService:
    """Base client (terminal element of the decorator chain)."""

    def __init__(self, base_url: str, logger=None, metrics=None, tracer=None, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self._logger = logger
        self._metrics = metrics
        self._tracer = tracer
        self._client = httpx.Client(timeout=timeout)

    def request(self, method: str, path: str, params: dict | None = None,
                body: bytes | None = None, headers: dict[str, str] | None = None,
                stream: bool = False) -> "ServiceResponse | StreamedServiceResponse":
        """``stream=False`` (default) reads the full body and returns a
        ``ServiceResponse``. ``stream=True`` returns headers-first
        (``StreamedServiceResponse``); the span/metrics/log then cover
        dispatch-to-headers, not the body transfer — the caller owns the
        connection until it exhausts ``iter_content`` or ``close()``s."""
        url = f"{self.base_url}/{path.lstrip('/')}"
        headers = dict(headers or {})
        span = None
        parent = current_span()
        if self._tracer is not None:
            span = self._tracer.start_span(f"HTTP {method} {self.base_url}", parent=parent,
                                           kind="CLIENT", set_current=False)
            headers.setdefault("traceparent", span.traceparent())
        elif parent is not None:
            headers.setdefault("traceparent", parent.traceparent())
        start = time.perf_counter()
        try:
            if stream:
                # identity, FORCED over any caller value (case variants
                # included): iter_content hands out RAW chunks for frame
                # latency, so the wire must not be content-coded
                for k in [k for k in headers if k.lower() == "accept-encoding"]:
                    del headers[k]
                headers["accept-encoding"] = "identity"
                req = self._client.build_request(method, url, params=params,
                                                 content=body, headers=headers)
                result = StreamedServiceResponse(self._client.send(req, stream=True))
                return result
            resp = self._client.request(method, url, params=params, content=body, headers=headers)
            result = ServiceResponse(resp.status_code, resp.content, dict(resp.headers))
            return result
        except httpx.HTTPError as e:
            if span is not None:
                span.set_status("ERROR").set_attribute("error", repr(e))
            raise ServiceError(str(e)) from e
        finally:
            duration = time.perf_counter() - start
            status = locals().get("result").status_code if locals().get("result") else 0
            if span is not None:
                span.set_attribute("http.status_code", status)
                span.finish()
            if self._metrics is not None:
                self._metrics.record_histogram(
                    "app_http_service_response", duration,
                    service=self.base_url, method=method, status=str(status),
                )
            if self._logger is not None:
                self._logger.debug({
                    "message": "http service call", "service": self.base_url,
                    "method": method, "path": path, "status": status,
                    "duration_us": int(duration * 1e6),
                })

    # verb sugar (gofr new.go:35-64)
    def get(self, path: str, params: dict | None = None, headers: dict | None = None) -> ServiceResponse:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, body: bytes | None = None, params: dict | None = None,
             headers: dict | None = None) -> ServiceResponse:
        return self.request("POST", path, params=params, body=body, headers=headers)

    def put(self, path: str, body: bytes | None = None, params: dict | None = None,
            headers: dict | None = None) -> ServiceResponse:
        return self.request("PUT", path, params=params, body=body, headers=headers)

    def patch(self, path: str, body: bytes | None = None, params: dict | None = None,
              headers: dict | None = None) -> ServiceResponse:
        return self.request("PATCH", path, params=params, body=body, headers=headers)

    def delete(self, path: str, body: bytes | None = None, headers: dict | None = None) -> ServiceResponse:
        return self.request("DELETE", path, body=body, headers=headers)

    def health_check(self, endpoint: str = "/.well-known/alive", timeout: float = 5.0) -> dict[str, Any]:
        try:
            resp = self._client.get(f"{self.base_url}{endpoint}", timeout=timeout)
            up = 200 <= resp.status_code < 300
            return {"status": "UP" if up else "DOWN", "details": {"host": self.base_url}}
        except httpx.HTTPError as e:
            return {"status": "DOWN", "details": {"host": self.base_url, "error": str(e)}}

    def close(self) -> None:
        self._client.close()


class ServiceError(Exception):
    status_code = 503


class _Wrapper:
    """Base for decorating options: delegates everything to the inner client."""

    def __init__(self, inner):
        self._inner = inner

    def request(self, method: str, path: str, **kw: Any) -> ServiceResponse:
        return self._inner.request(method, path, **kw)

    def get(self, path: str, params: dict | None = None, headers: dict | None = None) -> ServiceResponse:
        return self.request("GET", path, params=params, headers=headers)

    def post(self, path: str, body: bytes | None = None, params: dict | None = None,
             headers: dict | None = None) -> ServiceResponse:
        return self.request("POST", path, params=params, body=body, headers=headers)

    def put(self, path: str, body: bytes | None = None, params: dict | None = None,
            headers: dict | None = None) -> ServiceResponse:
        return self.request("PUT", path, params=params, body=body, headers=headers)

    def patch(self, path: str, body: bytes | None = None, params: dict | None = None,
              headers: dict | None = None) -> ServiceResponse:
        return self.request("PATCH", path, params=params, body=body, headers=headers)

    def delete(self, path: str, body: bytes | None = None, headers: dict | None = None) -> ServiceResponse:
        return self.request("DELETE", path, body=body, headers=headers)

    def health_check(self, **kw: Any) -> dict[str, Any]:
        return self._inner.health_check(**kw)

    def close(self) -> None:
        self._inner.close()

    @property
    def base_url(self) -> str:
        return self._inner.base_url


# -- options -------------------------------------------------------------------


class Retry:
    """Retry on transport error or 5xx (gofr `retry.go:95-109`), with the
    storm-safe refinements of docs/resilience.md:

    - *full jitter* on the exponential backoff — ``uniform(0, backoff *
      2**attempt)`` — so synchronized callers don't re-converge on the
      recovering upstream in lockstep waves;
    - a ``Retry-After`` header on a 429/503 response overrides the
      computed backoff (the server knows its recovery horizon better
      than our exponent does), capped at the remaining deadline when the
      outgoing request carries ``X-Request-Deadline-Ms``;
    - an optional shared :class:`~gofr_tpu.service.budget.RetryBudget`:
      each retry must win a token, and an exhausted budget fails fast
      with the last error instead of amplifying the storm;
    - requests whose propagated deadline has expired stop retrying —
      a retry the caller cannot wait for is pure amplification.
    """

    def __init__(self, max_retries: int = 3, backoff: float = 0.05,
                 budget: Any = None, rng: Any = None):
        self.max_retries = max_retries
        self.backoff = backoff
        self.budget = budget
        self._rng = rng if rng is not None else random.Random()

    def add_option(self, inner):
        opt = self

        class _Retry(_Wrapper):
            def request(self, method: str, path: str, **kw: Any) -> ServiceResponse:
                from gofr_tpu import deadline as _deadline

                headers = kw.get("headers") or {}
                dl = _deadline.parse_deadline_ms(
                    headers.get(_deadline.DEADLINE_HEADER))
                if opt.budget is not None:
                    opt.budget.note_request()
                last_exc: Exception | None = None
                for attempt in range(opt.max_retries + 1):
                    retry_after: float | None = None
                    try:
                        resp = self._inner.request(method, path, **kw)
                        if resp.status_code in (429, 503):
                            # httpx normalizes header keys to lowercase;
                            # hand-built responses may not
                            h = resp.headers or {}
                            ra = h.get("Retry-After") or h.get("retry-after")
                            try:
                                retry_after = float(ra) if ra else None
                            except (TypeError, ValueError):
                                retry_after = None
                        # a 429 WITH a Retry-After hint is retryable — the
                        # server said exactly when; a bare 429 stays the
                        # caller's problem (its rate budget, not ours)
                        if resp.status_code < 500 and not (
                                resp.status_code == 429 and retry_after is not None):
                            return resp
                        resp.close()  # a streamed 5xx must not leak its connection
                        last_exc = ServiceError(f"server error {resp.status_code}")
                    except ServiceError as e:
                        last_exc = e
                    if attempt >= opt.max_retries:
                        break
                    # full jitter unless the server named its own horizon
                    sleep = (retry_after if retry_after is not None
                             else opt._rng.uniform(0.0, opt.backoff * (2 ** attempt)))
                    if dl is not None:
                        remaining = dl - time.monotonic()
                        if remaining <= 0:
                            break  # the caller's budget is spent: stop amplifying
                        sleep = min(sleep, remaining)
                    if opt.budget is not None and not opt.budget.try_spend():
                        break  # shared budget exhausted: fail fast, decay the storm
                    time.sleep(max(0.0, sleep))
                if isinstance(last_exc, ServiceError):
                    raise last_exc
                raise ServiceError("retries exhausted")

        return _Retry(inner)


class CircuitBreaker:
    """Two-state breaker with background health probing while open
    (gofr `circuit_breaker.go`)."""

    def __init__(self, threshold: int = 5, interval: float = 5.0):
        self.threshold = threshold
        self.interval = interval

    def add_option(self, inner):
        opt = self

        class _CB(_Wrapper):
            def __init__(self, inner):
                super().__init__(inner)
                self._failures = 0
                self._open = False
                self._lock = threading.Lock()
                self._probe: threading.Thread | None = None

            def request(self, method: str, path: str, **kw: Any) -> ServiceResponse:
                with self._lock:
                    if self._open:
                        raise ServiceError("circuit breaker is open")
                try:
                    resp = self._inner.request(method, path, **kw)
                except ServiceError:
                    self._record_failure()
                    raise
                if resp.status_code >= 500:
                    self._record_failure()
                else:
                    with self._lock:
                        self._failures = 0
                return resp

            def _record_failure(self) -> None:
                with self._lock:
                    self._failures += 1
                    if self._failures >= opt.threshold and not self._open:
                        self._open = True
                        self._probe = threading.Thread(target=self._probe_loop, daemon=True,
                                                       name="gofr-cb-probe")
                        self._probe.start()

            def _probe_loop(self) -> None:
                while True:
                    time.sleep(opt.interval)
                    health = self._inner.health_check()
                    if health.get("status") == "UP":
                        with self._lock:
                            self._open = False
                            self._failures = 0
                        return

            @property
            def is_open(self) -> bool:
                with self._lock:
                    return self._open

            def health_check(self, **kw: Any) -> dict[str, Any]:
                h = self._inner.health_check(**kw)
                h.setdefault("details", {})["circuit_open"] = self.is_open
                return h

        return _CB(inner)


class BasicAuthOption:
    def __init__(self, username: str, password: str):
        token = base64.b64encode(f"{username}:{password}".encode()).decode()
        self._header = f"Basic {token}"

    def add_option(self, inner):
        return _HeaderInjector(inner, {"Authorization": self._header})


class APIKeyOption:
    def __init__(self, key: str):
        self._key = key

    def add_option(self, inner):
        return _HeaderInjector(inner, {"X-API-KEY": self._key})


class DefaultHeaders:
    def __init__(self, **headers: str):
        self._headers = {k.replace("_", "-"): v for k, v in headers.items()}

    def add_option(self, inner):
        return _HeaderInjector(inner, self._headers)


class _HeaderInjector(_Wrapper):
    def __init__(self, inner, headers: dict[str, str]):
        super().__init__(inner)
        self._headers = headers

    def request(self, method: str, path: str, **kw: Any) -> ServiceResponse:
        headers = dict(kw.pop("headers", None) or {})
        for k, v in self._headers.items():
            headers.setdefault(k, v)
        return self._inner.request(method, path, headers=headers, **kw)


class OAuth2ClientCredentials:
    """Client-credentials flow: fetches and caches a bearer token
    (gofr `oauth.go:14-40`)."""

    def __init__(self, token_url: str, client_id: str, client_secret: str, scopes: list[str] | None = None):
        self.token_url = token_url
        self.client_id = client_id
        self.client_secret = client_secret
        self.scopes = scopes or []
        self._token: str | None = None
        self._expiry = 0.0
        self._lock = threading.Lock()

    def _fetch(self) -> str:
        with self._lock:
            if self._token and time.time() < self._expiry - 30:
                return self._token
            resp = httpx.post(self.token_url, data={
                "grant_type": "client_credentials",
                "client_id": self.client_id,
                "client_secret": self.client_secret,
                "scope": " ".join(self.scopes),
            }, timeout=10.0)
            data = resp.json()
            self._token = data["access_token"]
            self._expiry = time.time() + float(data.get("expires_in", 3600))
            return self._token

    def add_option(self, inner):
        opt = self

        class _OAuth(_Wrapper):
            def request(self, method: str, path: str, **kw: Any) -> ServiceResponse:
                headers = dict(kw.pop("headers", None) or {})
                headers.setdefault("Authorization", f"Bearer {opt._fetch()}")
                return self._inner.request(method, path, headers=headers, **kw)

        return _OAuth(inner)


def new_http_service(base_url: str, logger=None, metrics=None, *options: Any,
                     tracer=None, timeout: float = 30.0):
    """Build the decorated client: options fold outermost-last (gofr `new.go:68-87`)."""
    client: Any = HTTPService(base_url, logger, metrics, tracer=tracer, timeout=timeout)
    for option in options:
        client = option.add_option(client)
    return client
