from gofr_tpu.utils.tokenizer import ByteTokenizer

__all__ = ["ByteTokenizer"]
