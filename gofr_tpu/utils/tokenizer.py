"""In-tree tokenizer for the text serving path.

The engine accepts any object with ``encode(str) -> list[int]`` /
``decode(list[int]) -> str`` (HF tokenizers qualify; ``build_engine`` loads
one from ``ModelSpec.tokenizer`` when it's a model id/path). This module
provides a dependency-free fallback so string-in/text-out serving — the
reference's bind-to-any ergonomics (`pkg/gofr/datasource/pubsub/message.go:
13-103`) applied to prompts — works with zero external downloads: a
reversible byte-level tokenizer (UTF-8 bytes shifted past the special ids).

Byte-level means multi-byte characters span several tokens; the engine's
incremental stream detokenizer (engine._emit) holds partial characters until
they complete, so streamed text is always valid UTF-8.
"""

from __future__ import annotations

PAD_ID, BOS_ID, EOS_ID = 0, 1, 2
_OFFSET = 3


class ByteTokenizer:
    """Reversible UTF-8 byte tokenizer: id = byte + 3 (0/1/2 = pad/bos/eos).

    Works with any model whose vocab_size >= 259; intended for examples,
    tests, and air-gapped deployments without a trained tokenizer."""

    vocab_size = 256 + _OFFSET
    pad_token_id = PAD_ID
    bos_token_id = BOS_ID
    eos_token_id = EOS_ID

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = [b + _OFFSET for b in text.encode("utf-8")]
        return [BOS_ID] + ids if add_bos else ids

    def decode(self, ids) -> str:
        # ids outside [3, 259) are specials or out-of-vocab (a model may
        # have a larger vocab than the tokenizer) — skipped, never a crash
        data = bytes(int(i) - _OFFSET for i in ids
                     if _OFFSET <= int(i) < 256 + _OFFSET)
        # errors='replace' keeps partial trailing characters visible as
        # U+FFFD — the stream detokenizer uses that as its hold signal
        return data.decode("utf-8", errors="replace")
