"""Structure binding: parsed payloads → dataclasses / annotated classes.

The Python analog of the reference's reflection binding (gofr
`pkg/gofr/http/request.go:57-74` JSON bind, `pkg/gofr/cmd/request.go:90-117`
flag bind): a payload dict is bound into a user-declared shape with light type
coercion, so handlers declare plain dataclasses instead of parsing dicts.
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import Any


class BindError(Exception):
    status_code = 400


def unwrap_optional(annotation: Any) -> Any:
    """``X | None`` / ``Optional[X]`` → ``X``; anything else unchanged.
    Shared by the JSON and multipart binders so union handling can't drift."""
    if typing.get_origin(annotation) in (typing.Union, types.UnionType):
        args = [a for a in typing.get_args(annotation) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return annotation


def bind_value(value: Any, annotation: Any) -> Any:
    """Coerce ``value`` to ``annotation`` (best effort, raises BindError)."""
    if annotation in (None, Any, typing.Any):
        return value
    origin = typing.get_origin(annotation)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(annotation) if a is not type(None)]
        if value is None:
            return None
        for arg in args:
            try:
                return bind_value(value, arg)
            except (BindError, TypeError, ValueError):
                continue
        raise BindError(f"cannot bind {value!r} to {annotation}")
    if origin in (list, tuple, set):
        (item_t,) = typing.get_args(annotation) or (Any,)
        if not isinstance(value, (list, tuple, set)):
            value = [value]
        seq = [bind_value(v, item_t) for v in value]
        return origin(seq)
    if origin is dict:
        return dict(value)
    if dataclasses.is_dataclass(annotation) and isinstance(annotation, type):
        return bind_dataclass(value, annotation)
    if annotation is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    if annotation in (int, float, str):
        try:
            return annotation(value)
        except (TypeError, ValueError) as e:
            raise BindError(f"cannot bind {value!r} to {annotation.__name__}") from e
    if annotation is bytes:
        if isinstance(value, bytes):
            return value
        return str(value).encode()
    if isinstance(annotation, type) and isinstance(value, annotation):
        return value
    return value


def bind_dataclass(data: Any, cls: type) -> Any:
    if isinstance(data, cls):
        return data
    if not isinstance(data, dict):
        raise BindError(f"expected object for {cls.__name__}, got {type(data).__name__}")
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = bind_value(data[f.name], f.type if not isinstance(f.type, str) else _resolve(cls, f.name))
        elif f.default is dataclasses.MISSING and f.default_factory is dataclasses.MISSING:  # type: ignore[misc]
            raise BindError(f"missing required field {f.name!r}")
    return cls(**kwargs)


def _resolve(cls: type, field_name: str) -> Any:
    try:
        hints = typing.get_type_hints(cls)
        return hints.get(field_name, Any)
    except Exception:  # noqa: BLE001
        return Any


def bind(data: Any, target: Any) -> Any:
    """Bind parsed data into ``target``.

    - dataclass type → constructed instance
    - ``dict``/``list``/scalars types → coerced value
    - annotated plain class → instance with attributes set
    """
    if target is dict:
        if not isinstance(data, dict):
            raise BindError("expected JSON object")
        return data
    if dataclasses.is_dataclass(target) and isinstance(target, type):
        return bind_dataclass(data, target)
    if isinstance(target, type) and hasattr(target, "__annotations__") and target.__annotations__:
        if not isinstance(data, dict):
            raise BindError(f"expected object for {target.__name__}")
        hints = typing.get_type_hints(target)
        obj = target()
        for name, ann in hints.items():
            if name in data:
                setattr(obj, name, bind_value(data[name], ann))
        return obj
    if isinstance(target, type):
        return bind_value(data, target)
    raise BindError(f"cannot bind into {target!r}")
