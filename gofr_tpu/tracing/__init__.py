"""Tracing: W3C-traceparent distributed tracing with pluggable span exporters.

Capability parity with the reference's tracing (gofr `pkg/gofr/gofr.go:307-422`,
`pkg/gofr/exporter.go`): a process-global tracer initialized from config
(``TRACE_EXPORTER`` = none|console|zipkin|otlp|memory), per-request server spans
with traceparent extraction, child spans per datasource call and per user
``ctx.trace(name)``, and background-batched HTTP span exporters — Zipkin JSON v2
(the format the reference's custom exporter also emits, `exporter.go:49-125`)
and OTLP/HTTP JSON for OpenTelemetry collectors.

Self-contained by design: spans are plain objects + contextvars, so tracing adds
no hot-path dependency. The TPU engine reuses the same spans to stitch
enqueue → batch → device-step timelines: ``RequestTrace`` carries the inbound
server span across the submit-thread → device-loop boundary (contextvars don't
cross threads) and hangs ``engine.queue_wait``/``engine.prefill``/
``engine.decode``/``engine.finish`` children under it, guarded by
``Tracer.enabled`` so ``TRACE_EXPORTER=none`` costs the serving loop one branch
(docs/observability.md).
"""

from __future__ import annotations

import contextvars
import json
import os
import queue
import threading
import time
import urllib.request
from typing import Any, Iterator
from contextlib import contextmanager

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gofr_tpu_current_span", default=None
)


def _rand_hex(nbytes: int) -> str:
    # os.urandom: fork-safe and never seed-correlated — the global `random`
    # module would hand every pre-forked worker (and every process sharing a
    # seeded RNG) colliding trace/span ids
    return os.urandom(nbytes).hex()


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attributes", "status", "kind", "sampled", "events", "_tracer", "_token",
    )

    def __init__(self, name: str, trace_id: str, span_id: str, parent_id: str | None,
                 tracer: "Tracer | None", kind: str = "INTERNAL", sampled: bool = True):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.start = time.time()
        self.end: float | None = None
        self.attributes: dict[str, Any] = {}
        self.status: str = "OK"
        self.kind = kind
        self.events: list[dict[str, Any]] | None = None  # lazily allocated
        self._tracer = tracer
        self._token: contextvars.Token | None = None

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attributes: Any) -> "Span":
        """Attach a timestamped point event (e.g. one chunked-prefill chunk)
        — cheaper than a child span for things with no meaningful duration."""
        if self.events is None:
            self.events = []
        self.events.append({"name": name, "ts": time.time(), "attributes": attributes})
        return self

    def set_status(self, status: str) -> "Span":
        self.status = status
        return self

    def finish(self) -> None:
        if self.end is not None:
            return
        self.end = time.time()
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                _current_span.set(None)
            self._token = None
        if self._tracer is not None:
            self._tracer._on_finish(self)

    # context-manager sugar
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = "ERROR"
            self.attributes.setdefault("error", repr(exc))
        self.finish()

    @property
    def duration_us(self) -> int:
        end = self.end if self.end is not None else time.time()
        return int((end - self.start) * 1e6)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


class SpanExporter:
    def export(self, spans: list[Span]) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class NoopExporter(SpanExporter):
    def export(self, spans: list[Span]) -> None:
        pass


class ConsoleExporter(SpanExporter):
    def __init__(self, logger):
        self._logger = logger

    def export(self, spans: list[Span]) -> None:
        for s in spans:
            self._logger.debug({
                "span": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
                "parent_id": s.parent_id, "duration_us": s.duration_us,
                "status": s.status, **{f"attr.{k}": v for k, v in s.attributes.items()},
            })


class MemoryExporter(SpanExporter):
    """Collects finished spans for test assertions."""

    def __init__(self):
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def export(self, spans: list[Span]) -> None:
        with self._lock:
            self.spans.extend(spans)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]


class ZipkinExporter(SpanExporter):
    """POSTs Zipkin v2 JSON batches (the wire format the reference's hosted
    exporter also produces)."""

    def __init__(self, endpoint: str, service_name: str, timeout: float = 5.0):
        self.endpoint = endpoint
        self.service_name = service_name
        self.timeout = timeout

    def export(self, spans: list[Span]) -> None:
        payload = [self._to_zipkin(s) for s in spans]
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.endpoint, data=body, headers={"Content-Type": "application/json"}, method="POST"
        )
        try:
            urllib.request.urlopen(req, timeout=self.timeout).close()
        except Exception:  # noqa: BLE001 - tracing must never break serving
            pass

    def _to_zipkin(self, s: Span) -> dict[str, Any]:
        out = {
            "id": s.span_id,
            "traceId": s.trace_id,
            "name": s.name,
            "timestamp": int(s.start * 1e6),
            "duration": s.duration_us,
            "localEndpoint": {"serviceName": self.service_name},
            "tags": {str(k): str(v) for k, v in s.attributes.items()},
        }
        # absent fields are OMITTED, not null: strict Zipkin collectors
        # reject literal `"kind": null` / `"parentId": null` payloads
        if s.parent_id:
            out["parentId"] = s.parent_id
        if s.kind in ("SERVER", "CLIENT", "PRODUCER", "CONSUMER"):
            out["kind"] = s.kind
        if s.events:
            out["annotations"] = [
                {"timestamp": int(e["ts"] * 1e6), "value": e["name"]} for e in s.events
            ]
        return out


# OTLP SpanKind enum (trace.proto): engine/user spans are INTERNAL
_OTLP_KIND = {"INTERNAL": 1, "SERVER": 2, "CLIENT": 3, "PRODUCER": 4, "CONSUMER": 5}


def _otlp_value(v: Any) -> dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # proto3 JSON: int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: dict[str, Any]) -> list[dict[str, Any]]:
    return [{"key": str(k), "value": _otlp_value(v)} for k, v in attrs.items()]


class OTLPExporter(SpanExporter):
    """OTLP/HTTP JSON exporter: POSTs an ``ExportTraceServiceRequest`` to a
    collector's ``/v1/traces`` endpoint (proto3 JSON mapping of
    opentelemetry/proto/trace/v1 — the wire format every OTel collector
    accepts on :4318). Closes the documented ``TRACE_EXPORTER=otlp`` gap."""

    def __init__(self, endpoint: str, service_name: str, timeout: float = 5.0):
        self.endpoint = endpoint
        self.service_name = service_name
        self.timeout = timeout

    def export(self, spans: list[Span]) -> None:
        body = json.dumps(self.to_payload(spans)).encode()
        req = urllib.request.Request(
            self.endpoint, data=body, headers={"Content-Type": "application/json"}, method="POST"
        )
        try:
            urllib.request.urlopen(req, timeout=self.timeout).close()
        except Exception:  # noqa: BLE001 - tracing must never break serving
            pass

    def to_payload(self, spans: list[Span]) -> dict[str, Any]:
        return {
            "resourceSpans": [{
                "resource": {"attributes": _otlp_attrs({"service.name": self.service_name})},
                "scopeSpans": [{
                    "scope": {"name": "gofr_tpu"},
                    "spans": [self._to_otlp(s) for s in spans],
                }],
            }]
        }

    def _to_otlp(self, s: Span) -> dict[str, Any]:
        out = {
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "name": s.name,
            "kind": _OTLP_KIND.get(s.kind, 1),
            "startTimeUnixNano": str(int(s.start * 1e9)),
            "endTimeUnixNano": str(int((s.end if s.end is not None else time.time()) * 1e9)),
            "attributes": _otlp_attrs(s.attributes),
            # STATUS_CODE_ERROR=2; finished-OK spans report UNSET (0), the
            # OTel default for spans nobody explicitly marked
            "status": {"code": 2, "message": "error"} if s.status == "ERROR" else {},
        }
        if s.parent_id:
            out["parentSpanId"] = s.parent_id
        if s.events:
            out["events"] = [
                {"timeUnixNano": str(int(e["ts"] * 1e9)), "name": e["name"],
                 "attributes": _otlp_attrs(e["attributes"])}
                for e in s.events
            ]
        return out


class Tracer:
    """Process tracer with background batch export."""

    def __init__(self, exporter: SpanExporter | None = None,
                 batch_size: int = 64, flush_interval: float = 2.0):
        self._exporter = exporter or NoopExporter()
        self._queue: queue.SimpleQueue[Span | None] = queue.SimpleQueue()
        self._batch_size = batch_size
        self._flush_interval = flush_interval
        self._worker: threading.Thread | None = None
        self._closed = False
        if not isinstance(self._exporter, (NoopExporter, MemoryExporter, ConsoleExporter)):
            self._worker = threading.Thread(target=self._run, name="gofr-span-export", daemon=True)
            self._worker.start()

    @property
    def enabled(self) -> bool:
        """False when spans go nowhere (``TRACE_EXPORTER=none``) — the hot
        path's guard: callers skip span construction entirely, so disabled
        tracing costs one attribute read and an isinstance check."""
        return not isinstance(self._exporter, NoopExporter)

    def start_span(self, name: str, parent: Span | None = None,
                   traceparent: str | None = None, kind: str = "INTERNAL",
                   set_current: bool = True) -> Span:
        if parent is None:
            parent = _current_span.get()
        trace_id: str | None = None
        parent_id: str | None = None
        sampled = True
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        elif traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed:
                trace_id, parent_id, sampled = parsed
        if trace_id is None:
            trace_id = _rand_hex(16)
        span = Span(name, trace_id, _rand_hex(8), parent_id, self, kind=kind, sampled=sampled)
        if set_current:
            span._token = _current_span.set(span)
        return span

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        s = self.start_span(name)
        s.attributes.update(attrs)
        try:
            yield s
        except Exception as exc:
            s.status = "ERROR"
            s.attributes.setdefault("error", repr(exc))
            raise
        finally:
            s.finish()

    def _on_finish(self, span: Span) -> None:
        if isinstance(self._exporter, (MemoryExporter, ConsoleExporter)):
            self._exporter.export([span])
        elif self._worker is not None and not self._closed:
            self._queue.put(span)

    def _run(self) -> None:
        batch: list[Span] = []
        deadline = time.monotonic() + self._flush_interval
        while True:
            timeout = max(0.01, deadline - time.monotonic())
            try:
                item = self._queue.get(timeout=timeout)
                if item is None:
                    break
                batch.append(item)
            except Exception:  # noqa: BLE001 - queue.Empty
                pass
            if batch and (len(batch) >= self._batch_size or time.monotonic() >= deadline):
                self._safe_export(batch)
                batch = []
                deadline = time.monotonic() + self._flush_interval
            elif time.monotonic() >= deadline:
                deadline = time.monotonic() + self._flush_interval
        if batch:
            self._safe_export(batch)

    def _safe_export(self, batch: list[Span]) -> None:
        # a faulty exporter must not kill the export thread (spans would then
        # accumulate unbounded in the queue with no consumer)
        try:
            self._exporter.export(batch)
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        self._closed = True
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join(timeout=5)
        self._exporter.shutdown()


def current_span() -> Span | None:
    return _current_span.get()


class RequestTrace:
    """Per-request engine span bundle, carried across the submit-thread →
    device-loop boundary on the request's kw context.

    contextvars do NOT cross threads — the HTTP/gRPC/pubsub server span is
    therefore propagated *explicitly* as ``parent`` and every engine child
    (``engine.queue_wait`` → ``engine.prefill`` → ``engine.decode`` →
    ``engine.finish``) starts with ``set_current=False``, so the device
    thread's contextvar state is never touched. Without an inbound parent a
    synthetic ``engine.request`` root is opened so direct ``engine.generate``
    callers still get a stitched timeline. Construct only behind
    ``Tracer.enabled`` — this object existing *is* the per-request cost."""

    __slots__ = ("tracer", "parent", "trace_id", "spans", "_root")

    def __init__(self, tracer: "Tracer", parent: Span | None = None):
        self.tracer = tracer
        if parent is None:
            parent = tracer.start_span("engine.request", set_current=False)
            self._root: Span | None = parent
        else:
            self._root = None
        self.parent = parent
        self.trace_id = parent.trace_id
        self.spans: dict[str, Span] = {}

    def begin(self, name: str, **attrs: Any) -> Span:
        span = self.tracer.start_span(name, parent=self.parent, set_current=False)
        if attrs:
            span.attributes.update(attrs)
        self.spans[name] = span
        return span

    def end(self, name: str, **attrs: Any) -> None:
        """Finish the named phase span; no-op when it was never begun or
        already ended (re-admission after preemption re-begins phases)."""
        span = self.spans.pop(name, None)
        if span is not None:
            if attrs:
                span.attributes.update(attrs)
            span.finish()

    def event(self, within: str, name: str, **attrs: Any) -> None:
        span = self.spans.get(within)
        if span is not None:
            span.add_event(name, **attrs)

    def close_all(self, error: Exception | None = None) -> None:
        """Finish every still-open span (and the synthetic root) — the
        request's done callback calls this so cancelled/timed-out/failed
        requests never leak open spans."""
        spans, self.spans = self.spans, {}
        for span in spans.values():
            if error is not None:
                span.status = "ERROR"
                span.attributes.setdefault("error", repr(error))
            span.finish()
        if self._root is not None:
            if error is not None:
                self._root.status = "ERROR"
            self._root.finish()


def parse_traceparent(header: str) -> tuple[str, str, bool] | None:
    """Parse a W3C traceparent ``00-<32hex traceid>-<16hex spanid>-<flags>``.

    Returns ``(trace_id, parent_span_id, sampled)`` — the sampled flag is
    preserved so an unsampled upstream trace is not upgraded on propagation.
    """
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        sampled = bool(int(flags, 16) & 0x01) if flags else True
    except ValueError:
        return None
    return trace_id, span_id, sampled


def tracer_from_config(config, logger, service_name: str) -> Tracer:
    """Exporter selected by TRACE_EXPORTER config (gofr `gofr.go:365-380`)."""
    exporter_name = (config.get("TRACE_EXPORTER") or "none").lower()
    if exporter_name in ("", "none"):
        return Tracer(NoopExporter())
    if exporter_name == "console":
        return Tracer(ConsoleExporter(logger))
    if exporter_name == "memory":
        # in-process collection for tests/debugging: assert on
        # container.tracer._exporter.spans with no network in the loop
        return Tracer(MemoryExporter())
    if exporter_name == "otlp":
        url = config.get("TRACER_URL") or config.get("TRACER_HOST")
        if not url:
            logger.warn("TRACE_EXPORTER=otlp but TRACER_URL missing; tracing disabled")
            return Tracer(NoopExporter())
        if not url.startswith("http"):
            port = config.get_or_default("TRACER_PORT", "4318") if hasattr(config, "get_or_default") else "4318"
            url = f"http://{url}:{port}"
        if "/v1/traces" not in url:
            url = url.rstrip("/") + "/v1/traces"
        return Tracer(OTLPExporter(url, service_name))
    if exporter_name in ("zipkin", "gofr"):
        url = config.get("TRACER_URL") or config.get("TRACER_HOST")
        if not url:
            logger.warn("TRACE_EXPORTER set but TRACER_URL missing; tracing disabled")
            return Tracer(NoopExporter())
        if not url.startswith("http"):
            port = config.get_or_default("TRACER_PORT", "9411") if hasattr(config, "get_or_default") else "9411"
            url = f"http://{url}:{port}/api/v2/spans"
        return Tracer(ZipkinExporter(url, service_name))
    logger.warnf("unknown TRACE_EXPORTER %r; tracing disabled", exporter_name)
    return Tracer(NoopExporter())
