"""Tracing: W3C-traceparent distributed tracing with pluggable span exporters.

Capability parity with the reference's tracing (gofr `pkg/gofr/gofr.go:307-422`,
`pkg/gofr/exporter.go`): a process-global tracer initialized from config
(``TRACE_EXPORTER`` = none|console|zipkin|otlp), per-request server spans with
traceparent extraction, child spans per datasource call and per user
``ctx.trace(name)``, and a background-batched HTTP span exporter (Zipkin JSON v2
— the format the reference's custom exporter also emits, `exporter.go:49-125`).

Self-contained by design: spans are plain objects + contextvars, so tracing adds
no hot-path dependency; the TPU engine reuses the same spans to stitch
enqueue → batch → device-step timelines.
"""

from __future__ import annotations

import contextvars
import json
import queue
import random
import threading
import time
import urllib.request
from typing import Any, Iterator
from contextlib import contextmanager

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gofr_tpu_current_span", default=None
)


def _rand_hex(nbytes: int) -> str:
    return "".join(f"{random.getrandbits(8):02x}" for _ in range(nbytes))


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attributes", "status", "kind", "sampled", "_tracer", "_token",
    )

    def __init__(self, name: str, trace_id: str, span_id: str, parent_id: str | None,
                 tracer: "Tracer | None", kind: str = "INTERNAL", sampled: bool = True):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.start = time.time()
        self.end: float | None = None
        self.attributes: dict[str, Any] = {}
        self.status: str = "OK"
        self.kind = kind
        self._tracer = tracer
        self._token: contextvars.Token | None = None

    def set_attribute(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def set_status(self, status: str) -> "Span":
        self.status = status
        return self

    def finish(self) -> None:
        if self.end is not None:
            return
        self.end = time.time()
        if self._token is not None:
            try:
                _current_span.reset(self._token)
            except ValueError:
                _current_span.set(None)
            self._token = None
        if self._tracer is not None:
            self._tracer._on_finish(self)

    # context-manager sugar
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = "ERROR"
            self.attributes.setdefault("error", repr(exc))
        self.finish()

    @property
    def duration_us(self) -> int:
        end = self.end if self.end is not None else time.time()
        return int((end - self.start) * 1e6)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


class SpanExporter:
    def export(self, spans: list[Span]) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class NoopExporter(SpanExporter):
    def export(self, spans: list[Span]) -> None:
        pass


class ConsoleExporter(SpanExporter):
    def __init__(self, logger):
        self._logger = logger

    def export(self, spans: list[Span]) -> None:
        for s in spans:
            self._logger.debug({
                "span": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
                "parent_id": s.parent_id, "duration_us": s.duration_us,
                "status": s.status, **{f"attr.{k}": v for k, v in s.attributes.items()},
            })


class MemoryExporter(SpanExporter):
    """Collects finished spans for test assertions."""

    def __init__(self):
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def export(self, spans: list[Span]) -> None:
        with self._lock:
            self.spans.extend(spans)

    def by_name(self, name: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]


class ZipkinExporter(SpanExporter):
    """POSTs Zipkin v2 JSON batches (the wire format the reference's hosted
    exporter also produces)."""

    def __init__(self, endpoint: str, service_name: str, timeout: float = 5.0):
        self.endpoint = endpoint
        self.service_name = service_name
        self.timeout = timeout

    def export(self, spans: list[Span]) -> None:
        payload = [self._to_zipkin(s) for s in spans]
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.endpoint, data=body, headers={"Content-Type": "application/json"}, method="POST"
        )
        try:
            urllib.request.urlopen(req, timeout=self.timeout).close()
        except Exception:  # noqa: BLE001 - tracing must never break serving
            pass

    def _to_zipkin(self, s: Span) -> dict[str, Any]:
        return {
            "id": s.span_id,
            "traceId": s.trace_id,
            "parentId": s.parent_id,
            "name": s.name,
            "timestamp": int(s.start * 1e6),
            "duration": s.duration_us,
            "kind": "SERVER" if s.kind == "SERVER" else "CLIENT" if s.kind == "CLIENT" else None,
            "localEndpoint": {"serviceName": self.service_name},
            "tags": {str(k): str(v) for k, v in s.attributes.items()},
        }


class Tracer:
    """Process tracer with background batch export."""

    def __init__(self, exporter: SpanExporter | None = None,
                 batch_size: int = 64, flush_interval: float = 2.0):
        self._exporter = exporter or NoopExporter()
        self._queue: queue.SimpleQueue[Span | None] = queue.SimpleQueue()
        self._batch_size = batch_size
        self._flush_interval = flush_interval
        self._worker: threading.Thread | None = None
        self._closed = False
        if not isinstance(self._exporter, (NoopExporter, MemoryExporter, ConsoleExporter)):
            self._worker = threading.Thread(target=self._run, name="gofr-span-export", daemon=True)
            self._worker.start()

    def start_span(self, name: str, parent: Span | None = None,
                   traceparent: str | None = None, kind: str = "INTERNAL",
                   set_current: bool = True) -> Span:
        if parent is None:
            parent = _current_span.get()
        trace_id: str | None = None
        parent_id: str | None = None
        sampled = True
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        elif traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed:
                trace_id, parent_id, sampled = parsed
        if trace_id is None:
            trace_id = _rand_hex(16)
        span = Span(name, trace_id, _rand_hex(8), parent_id, self, kind=kind, sampled=sampled)
        if set_current:
            span._token = _current_span.set(span)
        return span

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        s = self.start_span(name)
        s.attributes.update(attrs)
        try:
            yield s
        except Exception as exc:
            s.status = "ERROR"
            s.attributes.setdefault("error", repr(exc))
            raise
        finally:
            s.finish()

    def _on_finish(self, span: Span) -> None:
        if isinstance(self._exporter, (MemoryExporter, ConsoleExporter)):
            self._exporter.export([span])
        elif self._worker is not None and not self._closed:
            self._queue.put(span)

    def _run(self) -> None:
        batch: list[Span] = []
        deadline = time.monotonic() + self._flush_interval
        while True:
            timeout = max(0.01, deadline - time.monotonic())
            try:
                item = self._queue.get(timeout=timeout)
                if item is None:
                    break
                batch.append(item)
            except Exception:  # noqa: BLE001 - queue.Empty
                pass
            if batch and (len(batch) >= self._batch_size or time.monotonic() >= deadline):
                self._safe_export(batch)
                batch = []
                deadline = time.monotonic() + self._flush_interval
            elif time.monotonic() >= deadline:
                deadline = time.monotonic() + self._flush_interval
        if batch:
            self._safe_export(batch)

    def _safe_export(self, batch: list[Span]) -> None:
        # a faulty exporter must not kill the export thread (spans would then
        # accumulate unbounded in the queue with no consumer)
        try:
            self._exporter.export(batch)
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        self._closed = True
        if self._worker is not None:
            self._queue.put(None)
            self._worker.join(timeout=5)
        self._exporter.shutdown()


def current_span() -> Span | None:
    return _current_span.get()


def parse_traceparent(header: str) -> tuple[str, str, bool] | None:
    """Parse a W3C traceparent ``00-<32hex traceid>-<16hex spanid>-<flags>``.

    Returns ``(trace_id, parent_span_id, sampled)`` — the sampled flag is
    preserved so an unsampled upstream trace is not upgraded on propagation.
    """
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
        sampled = bool(int(flags, 16) & 0x01) if flags else True
    except ValueError:
        return None
    return trace_id, span_id, sampled


def tracer_from_config(config, logger, service_name: str) -> Tracer:
    """Exporter selected by TRACE_EXPORTER config (gofr `gofr.go:365-380`)."""
    exporter_name = (config.get("TRACE_EXPORTER") or "none").lower()
    if exporter_name in ("", "none"):
        return Tracer(NoopExporter())
    if exporter_name == "console":
        return Tracer(ConsoleExporter(logger))
    if exporter_name == "otlp":
        # OTLP/HTTP is a distinct wire format; silently POSTing Zipkin JSON at an
        # OTLP collector would drop every span with zero diagnostics.
        logger.warn("TRACE_EXPORTER=otlp is not implemented yet; use zipkin. Tracing disabled")
        return Tracer(NoopExporter())
    if exporter_name in ("zipkin", "gofr"):
        url = config.get("TRACER_URL") or config.get("TRACER_HOST")
        if not url:
            logger.warn("TRACE_EXPORTER set but TRACER_URL missing; tracing disabled")
            return Tracer(NoopExporter())
        if not url.startswith("http"):
            port = config.get_or_default("TRACER_PORT", "9411") if hasattr(config, "get_or_default") else "9411"
            url = f"http://{url}:{port}/api/v2/spans"
        return Tracer(ZipkinExporter(url, service_name))
    logger.warnf("unknown TRACE_EXPORTER %r; tracing disabled", exporter_name)
    return Tracer(NoopExporter())
