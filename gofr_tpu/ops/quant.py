"""Weight-only int8 quantization for serving, plus the int4 row primitives
the paged KV cache packs with.

Decode is HBM-bandwidth-bound on weight reads (every step re-reads the full
parameter set), so storing linear weights as int8 with per-output-channel
f32 scales halves the bytes the MXU pulls per step. Measured on TPU v5e
(round 3, 1B llama, 32 slots, chunk 32): 6554 tok/s int8 vs 4917 bf16 —
1.33x — with the usual weight-only accuracy profile (activations stay bf16;
the dequant multiply fuses into the matmul consumer).

``QTensor`` is a registered pytree, so quantized params flow through jit /
donation / sharding like plain arrays. Quantize AFTER sharding
(``build_engine`` does) so logical-axis rules apply to the original tree;
the quantized arrays inherit shardings from the computation.

The int4 helpers at the bottom (``quantize_row_int4`` / ``pack_int4`` /
``unpack_int4`` / ``fake_quant_row_int4``) are the single definition of the
packed-nibble format the int4 paged KV pool (ops/paged.Q4PagedKVCache), the
fused Pallas decode kernel (ops/pallas/paged_decode.py), and the XLA gather
fallback all share — any asymmetry between pack and unpack would silently
corrupt KV reads, so both directions live next to each other here and are
round-tripped by the unit tests.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """int8 weight + per-output-channel scale. Contraction happens over the
    second-to-last axis (matmul convention: x [.., in] @ w [in, out])."""

    q: jnp.ndarray  # int8, same shape as the original weight
    s: jnp.ndarray  # f32, shape = weight.shape with the contraction axis = 1

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim


def quantize(w: jnp.ndarray, *, axis: int = -2) -> QTensor:
    """Symmetric per-channel int8: scale = max|w| / 127 over ``axis`` (the
    contraction axis), so dequant is one multiply on the matmul OUTPUT."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def qdot(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain arrays OR QTensor — model code calls this at
    every linear site so one forward serves both representations. The int8
    operand converts at the matmul input (XLA fuses the convert into the
    operand read, so HBM traffic stays int8) and the scale applies to the
    output (valid because the scale is constant along the contraction)."""
    if isinstance(w, QTensor):
        out = x @ w.q.astype(x.dtype)
        return out * jnp.squeeze(w.s, axis=-2).astype(x.dtype)
    return x @ w


_DEFAULT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
                 "w_router", "w1", "w2", "w3", "w_fc", "w_proj")


def quantize_tree(params, keys: tuple[str, ...] = _DEFAULT_KEYS):
    """Quantize every >=2-D weight whose dict key is in ``keys`` (stacked
    [L, in, out] block weights quantize per-layer-per-channel automatically
    because the reduction axis is still -2). Norms, embeddings, and biases
    stay in their original dtype — embeddings are gathered per token (tiny
    reads) and norms are 1-D."""

    def walk(node):
        if isinstance(node, dict):
            return {k: (quantize(v) if k in keys and hasattr(v, "ndim") and v.ndim >= 2
                        else walk(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(params)


def quantized_bytes(params) -> int:
    """Actual parameter bytes after quantization (for HBM accounting)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


# -- int4 row quantization (packed KV pages; ops/paged.Q4PagedKVCache) ----------
#
# Symmetric per-row int4 over the last (head_dim) axis, mirroring
# kvcache.quantize_row's int8 contract but with the [-7, 7] range (the -8
# code is reserved so the symmetric scale max|x|/7 round-trips 0 exactly and
# negation stays lossless). Two values pack per byte in SPLIT-HALF order:
# byte j of a D-element row holds elements j (low nibble) and j + D/2 (high
# nibble), each stored biased by +8 so the byte is plain uint8 arithmetic —
# no sign-extension subtleties in either XLA or Mosaic. Split-half (rather
# than interleaved even/odd) keeps the unpack a single concatenate of two
# contiguous nibble planes, which lowers to cheap vector ops on both
# backends.


def quantize_row_int4(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int4 over the last axis: returns (q int8 in [-7, 7],
    scale[...] f32 without the reduced axis). Pack with ``pack_int4``."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 7.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -7, 7).astype(jnp.int8)
    return q, s


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """[..., D] int8 nibbles in [-8, 7] → [..., D//2] uint8. Byte j =
    (q[j] + 8) | ((q[j + D/2] + 8) << 4). The uint8 cast happens BEFORE the
    shift: a biased high nibble reaches 15 << 4 = 240, which would overflow
    int8 arithmetic."""
    d = q.shape[-1]
    lo = (q[..., : d // 2] + 8).astype(jnp.uint8)
    hi = (q[..., d // 2 :] + 8).astype(jnp.uint8)
    return lo | (hi << 4)


def unpack_int4(b: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``pack_int4``: [..., D//2] uint8 → [..., D] int8 in
    [-8, 7] (split-half order: low nibbles first, then high)."""
    bi = b.astype(jnp.int32)
    return jnp.concatenate(
        [(bi & 0xF) - 8, ((bi >> 4) & 0xF) - 8], axis=-1
    ).astype(jnp.int8)


def fake_quant_row_int4(x: jnp.ndarray, dtype=None,
                        scale_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Round-trip ``x`` through int4 row quantization exactly as the packed
    pool stores and the read path dequantizes it (scale through the cache's
    bf16 scale dtype) — the int4 analog of kvcache.fake_quant_row, used by
    whole-prompt paged prefill so cold prompts attend to what a later
    prefix-cache hit will read."""
    q, s = quantize_row_int4(x)
    out_dtype = dtype or x.dtype
    return q.astype(out_dtype) * s.astype(scale_dtype)[..., None].astype(out_dtype)
