"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound on weight reads (every step re-reads the full
parameter set), so storing linear weights as int8 with per-output-channel
f32 scales halves the bytes the MXU pulls per step. Measured on TPU v5e
(round 3, 1B llama, 32 slots, chunk 32): 6554 tok/s int8 vs 4917 bf16 —
1.33x — with the usual weight-only accuracy profile (activations stay bf16;
the dequant multiply fuses into the matmul consumer).

``QTensor`` is a registered pytree, so quantized params flow through jit /
donation / sharding like plain arrays. Quantize AFTER sharding
(``build_engine`` does) so logical-axis rules apply to the original tree;
the quantized arrays inherit shardings from the computation.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """int8 weight + per-output-channel scale. Contraction happens over the
    second-to-last axis (matmul convention: x [.., in] @ w [in, out])."""

    q: jnp.ndarray  # int8, same shape as the original weight
    s: jnp.ndarray  # f32, shape = weight.shape with the contraction axis = 1

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim


def quantize(w: jnp.ndarray, *, axis: int = -2) -> QTensor:
    """Symmetric per-channel int8: scale = max|w| / 127 over ``axis`` (the
    contraction axis), so dequant is one multiply on the matmul OUTPUT."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


def qdot(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain arrays OR QTensor — model code calls this at
    every linear site so one forward serves both representations. The int8
    operand converts at the matmul input (XLA fuses the convert into the
    operand read, so HBM traffic stays int8) and the scale applies to the
    output (valid because the scale is constant along the contraction)."""
    if isinstance(w, QTensor):
        out = x @ w.q.astype(x.dtype)
        return out * jnp.squeeze(w.s, axis=-2).astype(x.dtype)
    return x @ w


_DEFAULT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
                 "w_router", "w1", "w2", "w3", "w_fc", "w_proj")


def quantize_tree(params, keys: tuple[str, ...] = _DEFAULT_KEYS):
    """Quantize every >=2-D weight whose dict key is in ``keys`` (stacked
    [L, in, out] block weights quantize per-layer-per-channel automatically
    because the reduction axis is still -2). Norms, embeddings, and biases
    stay in their original dtype — embeddings are gathered per token (tiny
    reads) and norms are 1-D."""

    def walk(node):
        if isinstance(node, dict):
            return {k: (quantize(v) if k in keys and hasattr(v, "ndim") and v.ndim >= 2
                        else walk(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(params)


def quantized_bytes(params) -> int:
    """Actual parameter bytes after quantization (for HBM accounting)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
