"""Attention ops: batched GQA prefill and single-token decode.

TPU-first shape discipline: everything is [batch, seq, heads, head_dim]
with static shapes; grouped-query attention is computed by folding query
heads into groups ([B, S, Hkv, G, D]) so the contraction runs as one big
einsum on the MXU instead of repeating K/V in HBM.

``backend="xla"`` is plain einsum + masked softmax (XLA fuses this well at
serving sizes); ``backend="pallas"`` dispatches to the flash kernels in
``gofr_tpu.ops.pallas`` (blocked online-softmax; no S×S materialization).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def resolve_backend(backend: str, op: str | None = None) -> str:
    """'auto' resolves, in precedence order (docs/kernels.md): an explicit
    GOFR_PALLAS env value (0/1 — the operator override), then a pinned
    warmup-autotune decision for ``op`` (ops.autotune.decision_scope;
    engines pin measured winners for the decode ops around every trace
    they drive), then the legacy static default (XLA on hardware, Pallas
    under the interpreter — ops/pallas/__init__.flash_attention_available).
    An explicit 'pallas' is honored whenever the platform can lower
    kernels at all, degrading to 'xla' only off-TPU so one model code path
    serves the CPU test mesh and real chips."""
    if backend == "auto":
        import os

        from gofr_tpu.ops.pallas import flash_attention_available, kernel_platform

        if os.environ.get("GOFR_PALLAS", "") not in ("0", "1"):
            from gofr_tpu.ops.autotune import pinned_backend

            pinned = pinned_backend(op)
            if pinned is not None:
                return "pallas" if pinned == "pallas" and kernel_platform() else "xla"
        return "pallas" if flash_attention_available() else "xla"
    if backend == "pallas":
        from gofr_tpu.ops.pallas import kernel_platform

        return "pallas" if kernel_platform() else "xla"
    if backend != "xla":
        raise ValueError(f"unknown attention backend {backend!r}; use 'auto', 'xla' or 'pallas'")
    return backend


def _group_query_heads(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, S, Hq, D] → [B, S, Hkv, G, D]."""
    b, s, hq, d = q.shape
    if hq % num_kv_heads != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {num_kv_heads}")
    return q.reshape(b, s, num_kv_heads, hq // num_kv_heads, d)


def mha_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_offset: jnp.ndarray | int = 0,
    kv_lengths: jnp.ndarray | None = None,
    bias: jnp.ndarray | None = None,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    """Full (prefill) attention.

    q: [B, Sq, Hq, D]; k, v: [B, Skv, Hkv, D] → out [B, Sq, Hq, D].

    ``q_offset`` shifts query positions (per-batch int array or scalar) so a
    chunked prefill at cache offset t attends causally as positions t..t+Sq.
    ``kv_lengths`` [B] masks padded key positions. ``bias`` is an additive
    [B, 1|Hq, Sq, Skv] mask/ALiBi-style term.
    """
    backend = resolve_backend(backend)
    if backend == "pallas" and bias is None:  # kernel has no bias path
        if not isinstance(q_offset, jnp.ndarray):
            q_offset = jnp.asarray(q_offset, jnp.int32)
        if kv_lengths is None:
            kv_lengths = jnp.full((q.shape[0],), k.shape[1], jnp.int32)
        return _flash_mha(q, k, v, q_offset, kv_lengths, causal, scale)

    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    scale = scale if scale is not None else 1.0 / (d**0.5)

    qg = _group_query_heads(q, hkv)  # [B, Sq, Hkv, G, D]
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32) * scale

    mask = None
    if causal:
        if isinstance(q_offset, jnp.ndarray) and q_offset.ndim == 1:
            q_pos = jnp.arange(sq)[None, :] + q_offset[:, None]  # [B, Sq]
            causal_mask = q_pos[:, :, None] >= jnp.arange(skv)[None, None, :]  # [B, Sq, Skv]
            causal_mask = causal_mask[:, None, None]  # [B, 1, 1, Sq, Skv]
        else:
            q_pos = jnp.arange(sq)[:, None] + q_offset
            causal_mask = (q_pos >= jnp.arange(skv)[None, :])[None, None, None]
        mask = causal_mask
    if kv_lengths is not None:
        len_mask = jnp.arange(skv)[None, :] < kv_lengths[:, None]  # [B, Skv]
        len_mask = len_mask[:, None, None, None, :]
        mask = len_mask if mask is None else (mask & len_mask)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    if bias is not None:
        # bias [B, H, Sq, Skv] → regroup to [B, Hkv, G, Sq, Skv]
        bh = bias.shape[1]
        bias5 = bias.reshape(b, hkv, bh // hkv, *bias.shape[2:]) if bh > 1 else bias[:, :, None]
        scores = scores + bias5.astype(jnp.float32)

    probs = _softmax(scores)
    out = jnp.einsum("bkgst,btkd->bskgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash_mha(q, k, v, q_offset, kv_lengths, causal, scale):
    """Pallas flash forward with an XLA-recompute backward: pallas_call has
    no JVP rule, so gradients re-derive the attention via the einsum path
    (flash-style recompute — no S×S tensor is saved between fwd and bwd)."""
    from gofr_tpu.ops.pallas import interpret_mode
    from gofr_tpu.ops.pallas.flash_attention import flash_attention

    return flash_attention(
        q, k, v, causal=causal, q_offset=q_offset, kv_lengths=kv_lengths,
        scale=scale, interpret=interpret_mode(),
    )


def _flash_mha_fwd(q, k, v, q_offset, kv_lengths, causal, scale):
    return _flash_mha(q, k, v, q_offset, kv_lengths, causal, scale), (q, k, v, q_offset, kv_lengths)


def _flash_mha_bwd(causal, scale, res, g):
    q, k, v, q_offset, kv_lengths = res

    def ref(q, k, v):
        return mha_attention(
            q, k, v, causal=causal, q_offset=q_offset, kv_lengths=kv_lengths,
            scale=scale, backend="xla",
        )

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None, None


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def _softmax(scores: jnp.ndarray) -> jnp.ndarray:
    """Softmax in f32 that returns zeros (not NaN) for fully-masked rows —
    padded query rows have every key masked."""
    m = jnp.max(scores, axis=-1, keepdims=True)
    unnorm = jnp.exp(scores - jnp.maximum(m, NEG_INF / 2))
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    return unnorm / jnp.maximum(denom, 1e-20)


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    """Single-step decode: q [B, Hq, D] against a head-major cache
    [B, Hkv, Smax, D], attending to positions < lengths[b]. Returns
    [B, Hq, D]."""
    if resolve_backend(backend, op="decode") == "pallas":
        from gofr_tpu.ops.pallas import interpret_mode
        from gofr_tpu.ops.pallas.decode_attention import _pick_block
        from gofr_tpu.ops.pallas.decode_attention import decode_attention as pallas_decode

        smax = k_cache.shape[2]
        # An awkward Smax (e.g. prime) would degrade the kernel's kv block to
        # a sliver and serialize the grid; the XLA path is faster then. The
        # block must also be a multiple of 8 (f32 sublane tile) — Mosaic can
        # reject or degrade odd second-minor block dims on hardware, and only
        # the engine's 128-aligned caches are implicitly safe (ADVICE.md).
        bkv = _pick_block(smax, 512)
        if bkv >= min(smax, 128) and bkv % 8 == 0:
            return pallas_decode(
                q, k_cache, v_cache, lengths, scale=scale, interpret=interpret_mode()
            )
        if backend == "pallas":
            # Only 'auto' may degrade silently — an explicit request the
            # kernel cannot satisfy must not be ignored (ADVICE.md round 2;
            # paged_decode_attention already raises for its analog).
            raise ValueError(
                f"backend='pallas' requested but cache Smax {smax} yields kv "
                f"block {bkv} (need a block >= min(Smax, 128) that divides "
                f"Smax and is a multiple of 8); use a 128-aligned cache "
                f"length or backend='auto'"
            )
    b, hq, d = q.shape
    _, hkv, smax, _ = k_cache.shape
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qg = q.reshape(b, hkv, hq // hkv, d)  # head h groups under kv head h // G
    scores = jnp.einsum("bkgd,bktd->bkgt", qg, k_cache).astype(jnp.float32) * scale
    mask = jnp.arange(smax)[None, :] < lengths[:, None]  # [B, Smax]
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = _softmax(scores)
    out = jnp.einsum("bkgt,bktd->bkgd", probs.astype(v_cache.dtype), v_cache)
    return out.reshape(b, hq, d)


def decode_attention_q(
    q: jnp.ndarray,        # [B, Hq, D]
    k_cache: jnp.ndarray,  # int8 [B, Hkv, Smax, D]
    v_cache: jnp.ndarray,  # int8 [B, Hkv, Smax, D]
    k_scale: jnp.ndarray,  # [B, Hkv, Smax]
    v_scale: jnp.ndarray,  # [B, Hkv, Smax]
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """decode_attention over an int8 KV cache (ops.kvcache.QSlotKVCache).

    The int8 operands convert at the matmul input (XLA fuses the convert
    into the operand read — HBM traffic stays int8, the same mechanism as
    weight-only qdot, ops/quant.py:52). Per-position scales fold OUTSIDE
    the contractions: ``ks`` multiplies scores per key position (constant
    along the D reduction) and ``vs`` rides the probabilities (constant
    along the T reduction)."""
    b, hq, d = q.shape
    _, hkv, smax, _ = k_cache.shape
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qg = q.reshape(b, hkv, hq // hkv, d)
    scores = jnp.einsum("bkgd,bktd->bkgt", qg, k_cache.astype(q.dtype)).astype(jnp.float32)
    scores = scores * k_scale[:, :, None, :].astype(jnp.float32) * scale
    mask = jnp.arange(smax)[None, :] < lengths[:, None]
    scores = jnp.where(mask[:, None, None], scores, NEG_INF)
    probs = _softmax(scores)
    pv = (probs * v_scale[:, :, None, :].astype(jnp.float32)).astype(q.dtype)
    out = jnp.einsum("bkgt,bktd->bkgd", pv, v_cache.astype(q.dtype))
    return out.reshape(b, hq, d)


# -- tensor-parallel dispatch ------------------------------------------------
#
# When an engine pins a paged.KVShardCtx (pool planes sharded over the
# mesh's tp axis along KV heads), the three paged decode entry points wrap
# their single-device bodies in shard_map: each device runs the SAME kernel
# (Pallas or XLA gather) over its own Hkv/tp heads and Hq/tp query heads,
# block tables and lengths replicated. No collective is emitted here — the
# output stays head-sharded and the model's o-projection matmul (tp-sharded
# wo) supplies the single psum that already existed for the weights.


def _kv_shard_ctx(q: jnp.ndarray, pool: jnp.ndarray):
    """The pinned shard ctx, or None when the geometry can't split (head
    counts must divide evenly — sharding never pads heads)."""
    from gofr_tpu.ops.paged import current_kv_shard

    ctx = current_kv_shard()
    if ctx is None:
        return None
    if q.shape[1] % ctx.shards or pool.shape[1] % ctx.shards:
        return None
    return ctx


def _shard_paged_call(impl, ctx, q, pools, table, lengths):
    """Run ``impl(q, *pools, table, lengths)`` per-shard: q and every pool
    plane split on their head axis (dim 1), table/lengths replicated, output
    head-sharded (no reduce — see module note above)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    ax = ctx.axis
    pool_specs = tuple(
        P(None, ax, None, None) if p.ndim == 4 else P(None, ax, None)
        for p in pools
    )
    return shard_map(
        impl,
        mesh=ctx.mesh,
        in_specs=(P(None, ax, None),) + pool_specs + (P(), P()),
        out_specs=P(None, ax, None),
        check_rep=False,
    )(q, *pools, table, lengths)


def paged_decode_attention_q(
    q: jnp.ndarray,        # [N, Hq, D]
    kq_pool: jnp.ndarray,  # int8 [P, Hkv, page, D]
    vq_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # [P, Hkv, page]
    vs_pool: jnp.ndarray,
    table: jnp.ndarray,    # [N, MaxP]
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    ctx = _kv_shard_ctx(q, kq_pool)
    if ctx is not None:
        impl = partial(_paged_decode_attention_q_local, scale=scale, backend=backend)
        return _shard_paged_call(impl, ctx, q, (kq_pool, vq_pool, ks_pool, vs_pool),
                                 table, lengths)
    return _paged_decode_attention_q_local(
        q, kq_pool, vq_pool, ks_pool, vs_pool, table, lengths,
        scale=scale, backend=backend,
    )


def _paged_decode_attention_q_local(
    q: jnp.ndarray,
    kq_pool: jnp.ndarray,
    vq_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,
    vs_pool: jnp.ndarray,
    table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    """paged_decode_attention over an int8 pool (ops.paged.QPagedKVCache).

    'pallas' is the FUSED kernel (ops.pallas.paged_decode.paged_decode_
    attention_q): int8 pages + scale rows stream straight out of the pool
    through the scalar-prefetched block tables and dequantize in-kernel —
    no materialized logical view, HBM traffic stays int8. 'xla' gathers
    the int8 logical views + scales per slot (one extra HBM round trip for
    the copy) and reuses the folded-scale dense decode path — correct
    everywhere. 'auto' follows resolve_backend (autotune pin aware)."""
    page = kq_pool.shape[2]
    if resolve_backend(backend, op="paged_decode_q") == "pallas":
        if page % 8 == 0:
            from gofr_tpu.ops.pallas import interpret_mode
            from gofr_tpu.ops.pallas.paged_decode import (
                paged_decode_attention_q as pallas_paged_q,
            )

            return pallas_paged_q(
                q, kq_pool, vq_pool, ks_pool, vs_pool, table, lengths,
                scale=scale, interpret=interpret_mode(),
            )
        if backend == "pallas":
            # explicit requests never degrade silently (ADVICE.md round 2)
            raise ValueError(
                f"backend='pallas' requested but page size {page} is not a "
                f"multiple of 8 (f32 sublane tile); use a page_size % 8 == 0 "
                f"or backend='auto'"
            )
    from gofr_tpu.ops.paged import gather_kv_q

    gkq, gks = gather_kv_q(kq_pool, ks_pool, table)
    gvq, gvs = gather_kv_q(vq_pool, vs_pool, table)
    return decode_attention_q(q, gkq, gvq, gks, gvs, lengths, scale=scale)


def paged_decode_attention_q4(
    q: jnp.ndarray,        # [N, Hq, D]
    kq_pool: jnp.ndarray,  # uint8 [P, Hkv, page, D//2] packed nibbles
    vq_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # [P, Hkv, page]
    vs_pool: jnp.ndarray,
    table: jnp.ndarray,    # [N, MaxP]
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    ctx = _kv_shard_ctx(q, kq_pool)
    if ctx is not None:
        impl = partial(_paged_decode_attention_q4_local, scale=scale, backend=backend)
        return _shard_paged_call(impl, ctx, q, (kq_pool, vq_pool, ks_pool, vs_pool),
                                 table, lengths)
    return _paged_decode_attention_q4_local(
        q, kq_pool, vq_pool, ks_pool, vs_pool, table, lengths,
        scale=scale, backend=backend,
    )


def _paged_decode_attention_q4_local(
    q: jnp.ndarray,
    kq_pool: jnp.ndarray,
    vq_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,
    vs_pool: jnp.ndarray,
    table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    """paged_decode_attention over a PACKED int4 pool (ops.paged.
    Q4PagedKVCache; ops/quant.pack_int4 split-half nibble format).

    'pallas' is the FUSED kernel (ops.pallas.paged_decode.paged_decode_
    attention_q4): packed byte pages + scale rows stream straight out of
    the pool through the scalar-prefetched block tables; nibble unpack +
    dequant happen in-register, so the KV HBM read is half the int8
    kernel's. 'xla' gathers the packed views, unpacks after the gather
    (ops.paged.gather_kv_q4), and reuses the folded-scale dense decode
    path — correct everywhere, the parity reference for the kernel.
    'auto' follows resolve_backend (autotune pin aware, op key
    'paged_decode_q4' — tuned separately from int8 because the winner
    shifts with the unpack cost on each device generation)."""
    page = kq_pool.shape[2]
    if resolve_backend(backend, op="paged_decode_q4") == "pallas":
        if page % 8 == 0:
            from gofr_tpu.ops.pallas import interpret_mode
            from gofr_tpu.ops.pallas.paged_decode import (
                paged_decode_attention_q4 as pallas_paged_q4,
            )

            return pallas_paged_q4(
                q, kq_pool, vq_pool, ks_pool, vs_pool, table, lengths,
                scale=scale, interpret=interpret_mode(),
            )
        if backend == "pallas":
            # explicit requests never degrade silently (ADVICE.md round 2)
            raise ValueError(
                f"backend='pallas' requested but page size {page} is not a "
                f"multiple of 8 (f32 sublane tile); use a page_size % 8 == 0 "
                f"or backend='auto'"
            )
    from gofr_tpu.ops.paged import gather_kv_q4

    gkq, gks = gather_kv_q4(kq_pool, ks_pool, table)
    gvq, gvs = gather_kv_q4(vq_pool, vs_pool, table)
    return decode_attention_q(q, gkq, gvq, gks, gvs, lengths, scale=scale)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    ctx = _kv_shard_ctx(q, k_pool)
    if ctx is not None:
        impl = partial(_paged_decode_attention_local, scale=scale, backend=backend)
        return _shard_paged_call(impl, ctx, q, (k_pool, v_pool), table, lengths)
    return _paged_decode_attention_local(
        q, k_pool, v_pool, table, lengths, scale=scale, backend=backend,
    )


def _paged_decode_attention_local(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jnp.ndarray:
    """Single-step decode against a paged KV pool (ops.paged layout).

    q [N, Hq, D]; k_pool/v_pool [P, Hkv, page, D]; table [N, MaxP] block
    table (OOB entries == P); lengths [N] → out [N, Hq, D].

    'pallas' streams pages straight out of the pool through scalar-prefetched
    block tables (ops.pallas.paged_decode); 'xla' materializes each slot's
    logical view with one gather (ops.paged.gather_kv) and reuses the dense
    decode path — correct everywhere, but pays an extra HBM round trip.
    """
    page = k_pool.shape[2]
    if resolve_backend(backend, op="paged_decode") == "pallas":
        if page % 8 == 0:
            from gofr_tpu.ops.pallas import interpret_mode
            from gofr_tpu.ops.pallas.paged_decode import paged_decode_attention as pallas_paged

            return pallas_paged(
                q, k_pool, v_pool, table, lengths, scale=scale, interpret=interpret_mode()
            )
        if backend == "pallas":
            # Only 'auto' may degrade silently — an explicit request the
            # kernel cannot satisfy must not be ignored (ADVICE.md round 2).
            raise ValueError(
                f"backend='pallas' requested but page size {page} is not a "
                f"multiple of 8 (f32 sublane tile); use a page_size % 8 == 0 "
                f"or backend='auto'"
            )
    from gofr_tpu.ops.paged import gather_kv

    k_view, v_view = gather_kv(k_pool, v_pool, table)
    return decode_attention(q, k_view, v_view, lengths, scale=scale, backend="xla")
