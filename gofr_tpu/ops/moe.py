"""Mixture-of-experts routing: top-k gating with static capacity.

GShard/Switch-style dense dispatch, shaped for the TPU compiler: every
tensor is static — tokens route into a fixed [experts, capacity, dim]
buffer via one-hot dispatch/combine einsums, so the whole MoE layer is
three big MXU contractions regardless of routing decisions, and the same
compiled program serves every batch (no recompiles, no ragged shapes).
Tokens beyond an expert's capacity are *dropped* (their combine weight is
zero and the residual stream carries them through) — the standard
capacity-factor trade.

Expert parallelism falls out of sharding: the expert dimension of the
dispatch buffer and the expert weights carry the "expert" logical axis
(→ mesh ``ep``), and GSPMD turns the dispatch/combine einsums into
all-to-alls over ICI (SURVEY.md §2.9 — new subsystem, no reference analog).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class Routing(NamedTuple):
    dispatch: jnp.ndarray  # [T, E, C] one-hot-ish {0,1}
    combine: jnp.ndarray   # [T, E, C] gate probabilities at kept slots
    aux_loss: jnp.ndarray  # [] load-balance loss (Switch §2.2 style)
    router_probs: jnp.ndarray  # [T, E] full softmax (for metrics/tests)


def route_topk(
    router_logits: jnp.ndarray,  # [T, E]
    *,
    k: int,
    capacity: int,
    renormalize: bool = True,
    token_mask: jnp.ndarray | None = None,  # [T] 1 = real token
) -> Routing:
    """Top-k token→expert assignment with per-expert capacity ``C``.

    Priority is choice-major then token-major: every token's 1st choice
    beats any token's 2nd choice; ties break by token order — deterministic
    and batch-order stable.

    ``token_mask`` excludes padding tokens entirely: they take no capacity,
    get zero combine mass, and don't bias the aux loss — so a padded batch
    routes identically to its unpadded equivalent.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]
    top_probs, top_idx = lax.top_k(probs, k)  # [T, K]
    if renormalize:
        top_probs = top_probs / jnp.maximum(jnp.sum(top_probs, -1, keepdims=True), 1e-9)

    mask = jax.nn.one_hot(top_idx, e, dtype=jnp.int32)  # [T, K, E]
    if token_mask is not None:
        mask = mask * token_mask.astype(jnp.int32)[:, None, None]
    # choice-major flatten → positions within each expert's buffer
    mask_f = mask.transpose(1, 0, 2).reshape(k * t, e)
    pos_f = (jnp.cumsum(mask_f, axis=0) - 1) * mask_f  # [K*T, E]
    pos = pos_f.reshape(k, t, e).transpose(1, 0, 2)  # [T, K, E]
    kept = (pos < capacity) & (mask > 0)  # [T, K, E]

    slot = jax.nn.one_hot(jnp.where(kept, pos, -1), capacity, dtype=jnp.float32)  # [T,K,E,C]
    dispatch = jnp.sum(slot, axis=1)  # [T, E, C]
    combine = jnp.sum(slot * top_probs[:, :, None, None], axis=1)  # [T, E, C]

    # load balance: E * Σ_e (fraction of first-choice tokens to e) * (mean prob of e)
    first = jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32)
    if token_mask is not None:
        w = token_mask.astype(jnp.float32)[:, None]
        denom = jnp.maximum(jnp.sum(w), 1.0)
        frac = jnp.sum(first * w, axis=0) / denom
        mean_prob = jnp.sum(probs * w, axis=0) / denom
    else:
        frac = jnp.mean(first, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return Routing(dispatch=dispatch, combine=combine, aux_loss=aux, router_probs=probs)


def default_capacity(tokens: int, experts: int, k: int, factor: float = 1.25) -> int:
    """ceil(T*k/E * factor), at least 1 — static per (shape, config)."""
    return max(1, int((tokens * k + experts - 1) // experts * factor))


def moe_ffn(
    x: jnp.ndarray,          # [T, D] tokens (post-norm)
    router_w: jnp.ndarray,   # [D, E]
    w_gate: jnp.ndarray,     # [E, D, M]
    w_up: jnp.ndarray,       # [E, D, M]
    w_down: jnp.ndarray,     # [E, M, D]
    *,
    k: int,
    capacity_factor: float = 1.25,
    capacity: int | None = None,
    token_mask: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """SwiGLU expert FFN over routed tokens → ([T, D], aux_loss).

    The three einsums (dispatch, expert matmuls, combine) are where EP
    sharding bites: w_* carry the "expert" logical axis. ``capacity``
    overrides the factor-derived default (e.g. decode uses capacity == T so
    a skewed slot batch can never drop a live token).
    """
    t, d = x.shape
    e = router_w.shape[1]
    cap = capacity if capacity is not None else default_capacity(t, e, k, capacity_factor)
    routing = route_topk(
        (x @ router_w.astype(x.dtype)).astype(jnp.float32),
        k=k, capacity=cap, token_mask=token_mask,
    )

    xin = jnp.einsum("tec,td->ecd", routing.dispatch.astype(x.dtype), x)  # [E, C, D]
    gated = jax.nn.silu(jnp.einsum("ecd,edm->ecm", xin, w_gate)) * jnp.einsum(
        "ecd,edm->ecm", xin, w_up
    )
    out = jnp.einsum("ecm,emd->ecd", gated, w_down)  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", routing.combine.astype(x.dtype), out)
    return y, routing.aux_loss
