"""Paged (block) KV cache: fixed-size pages + per-slot block tables.

The slot cache (gofr_tpu.ops.kvcache) reserves ``max_len`` of HBM per slot,
so slot count x sequence length multiply into the HBM budget even when most
requests are short. Here the cache is one physical POOL of pages

    k, v: [L, P, Hkv, page_size, D]

and each serving slot owns an ordered list of page ids — its *block table*.
Logical position ``p`` of slot ``s`` lives at ``(table[s, p // page_size],
p % page_size)``. HBM now scales with TOKENS IN FLIGHT, not slots x max_len:
the engine admits more concurrent requests at equal HBM and reclaims pages
the moment a request completes (SURVEY.md §7 stage 4 — no reference analog;
this is the TPU-native subsystem the build plan orders).

Layout mirrors the slot cache's head-major discipline: the last two dims of
a page block are (page_size, D) = (128k, 128k)-alignable tiles, so both the
XLA gather path and the Pallas paged-decode kernel stream [page, D] tiles
straight out of HBM per (page, kv_head).

Out-of-bounds convention: table entries for unallocated logical pages (and
batch-padding rows) point at page id P (one past the pool). Scatter writes
there are DROPPED by XLA, and gather reads CLAMP to page P-1 but are always
masked by per-slot lengths — the same trick the slot engine uses for
padding rows (engine._admit docstring).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from gofr_tpu.ops.kvcache import quantize_row
from gofr_tpu.ops.quant import pack_int4, quantize_row_int4, unpack_int4

# The append-lowering choice (select | scatter | pallas). Engines resolve
# GOFR_PAGED_KV_WRITE ONCE at construction and pin it here for every trace
# they drive (engine._trace_scope); the env var is only read as a fallback
# for direct ops callers (unit tests, notebooks). jit caches traces
# process-globally, so A/B the lowerings across processes, not by flipping
# the env between engine builds in one process.
_WRITE_MODE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "gofr_paged_kv_write", default=None
)


def resolve_write_mode(explicit: str | None = None) -> str:
    """The lowering to trace with: explicit arg > engine pin > env."""
    if explicit:
        return explicit
    pinned = _WRITE_MODE.get()
    if pinned is not None:
        return pinned
    return os.environ.get("GOFR_PAGED_KV_WRITE", "select")


@contextlib.contextmanager
def write_mode_scope(mode: str | None):
    """Pin the paged-append lowering for traces inside the scope — the
    engine wraps its device loop / warmup / follower loop with this so the
    choice it resolved at construction is what every trace sees."""
    tok = _WRITE_MODE.set(mode)
    try:
        yield
    finally:
        _WRITE_MODE.reset(tok)


# -- tensor-parallel pool sharding ------------------------------------------
#
# The pool planes shard over the mesh's tp axis along the KV-head dimension
# (axis 2 of [L, P, Hkv, page, D]; axis 2 of the [L, P, Hkv, page] scale
# planes too). Block tables stay replicated — page ids are logical, not
# per-shard — and the decode attention ops run per-shard under shard_map
# when an engine pins a KVShardCtx for its traces (engine._trace_scope),
# mirroring the write-mode pin above.


@dataclass(frozen=True)
class KVShardCtx:
    """Trace-time pin describing how the paged pool is sharded: the mesh,
    the mesh axis the KV-head dimension is split over, and the shard count
    (= mesh.shape[axis]). Engines enter ``kv_shard_scope`` with this for
    every trace they drive so the paged decode ops wrap themselves in
    shard_map; direct ops callers (unit tests) enter it explicitly."""

    mesh: object  # jax.sharding.Mesh
    axis: str = "tp"
    shards: int = 1


_KV_SHARD: contextvars.ContextVar[KVShardCtx | None] = contextvars.ContextVar(
    "gofr_paged_kv_shard", default=None
)


def current_kv_shard() -> KVShardCtx | None:
    """The pinned pool-sharding context, or None (unsharded pool)."""
    ctx = _KV_SHARD.get()
    if ctx is not None and ctx.shards > 1:
        return ctx
    return None


@contextlib.contextmanager
def kv_shard_scope(ctx: KVShardCtx | None):
    """Pin the pool sharding for traces inside the scope (None = unsharded)."""
    tok = _KV_SHARD.set(ctx)
    try:
        yield
    finally:
        _KV_SHARD.reset(tok)


def plane_partition_spec(ndim: int, axis: str = "tp"):
    """PartitionSpec for one pool plane by rank: K/V planes are 5-D
    [L, P, Hkv, page, D], scale planes 4-D [L, P, Hkv, page] — the KV-head
    axis is dim 2 in both. Anything else (spec history planes, block
    tables) stays replicated."""
    from jax.sharding import PartitionSpec as P

    if ndim == 5:
        return P(None, None, axis, None, None)
    if ndim == 4:
        return P(None, None, axis, None)
    return P()


def pool_sharding(mesh, axis: str = "tp"):
    """NamedSharding for the 5-D K/V planes — what engines hand to the
    cache constructors. Scale planes derive their 4-D spec internally."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, plane_partition_spec(5, axis))


def _shard_for(sharding, ndim: int):
    """Re-rank a 5-D plane NamedSharding for an ndim-rank plane (the scale
    planes drop the trailing head_dim axis)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = tuple(sharding.spec) + (None,) * (5 - len(tuple(sharding.spec)))
    return NamedSharding(sharding.mesh, PartitionSpec(*spec[:ndim]))


def _zeros(shape, dtype, sharding=None) -> jnp.ndarray:
    """Zero-filled plane, allocated DIRECTLY under ``sharding`` when given —
    jit with out_shardings materializes each device's shard in place, so a
    sharded pool never exists replicated, not even transiently at create."""
    if sharding is None:
        return jnp.zeros(shape, dtype)
    return jax.jit(partial(jnp.zeros, shape, dtype),
                   out_shardings=_shard_for(sharding, len(shape)))()


def _locate(pages: jnp.ndarray, pos: jnp.ndarray, page: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(physical page, in-page offset) per logical position. ``pages``
    [B, MaxP] block-table rows, ``pos`` [B, S] logical positions. The
    logical-page clamp keeps chunked tails inside the table; true OOB rows
    drop through page id P (the pool-size sentinel)."""
    pp = jnp.take_along_axis(pages, jnp.minimum(pos // page, pages.shape[1] - 1), axis=1)
    return pp, pos % page


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    k: jnp.ndarray  # [L, P, Hkv, page, D]
    v: jnp.ndarray  # [L, P, Hkv, page, D]

    @classmethod
    def create(
        cls,
        layers: int,
        pages: int,
        page_size: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        sharding=None,
    ) -> "PagedKVCache":
        shape = (layers, pages, kv_heads, page_size, head_dim)
        return cls(k=_zeros(shape, dtype, sharding), v=_zeros(shape, dtype, sharding))

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


@jax.tree_util.register_dataclass
@dataclass
class QPagedKVCache:
    """int8 paged pool with per-(page, head, position) scales — the paged
    analog of kvcache.QSlotKVCache: cache reads halve and the scales fold
    outside the attention contractions. Prefix caching composes unchanged:
    a page's (int8, scale) content is still a deterministic function of
    the token prefix, so shared pages stay exact across chains."""

    k: jnp.ndarray   # int8 [L, P, Hkv, page, D]
    v: jnp.ndarray   # int8 [L, P, Hkv, page, D]
    ks: jnp.ndarray  # bf16 [L, P, Hkv, page]
    vs: jnp.ndarray  # bf16 [L, P, Hkv, page]

    @classmethod
    def create(cls, layers: int, pages: int, page_size: int, kv_heads: int,
               head_dim: int, dtype=None, sharding=None) -> "QPagedKVCache":
        del dtype
        shape = (layers, pages, kv_heads, page_size, head_dim)
        sshape = (layers, pages, kv_heads, page_size)
        return cls(
            k=_zeros(shape, jnp.int8, sharding), v=_zeros(shape, jnp.int8, sharding),
            ks=_zeros(sshape, jnp.bfloat16, sharding),
            vs=_zeros(sshape, jnp.bfloat16, sharding),
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


@jax.tree_util.register_dataclass
@dataclass
class Q4PagedKVCache:
    """Packed-int4 paged pool: two nibbles per byte in the head_dim axis
    (ops/quant.pack_int4 split-half order — byte j of a D-wide row holds
    elements j and j + D/2) with the same per-(page, head, position) bf16
    scale planes as the int8 layout. KV page reads quarter vs bf16 and
    halve vs int8; the scales still fold outside the attention
    contractions (in-kernel for the Pallas path, via the unpacked gather
    view for XLA). Zero-initialized bytes decode to the -8 nibble pair,
    but unwritten positions always sit behind the per-slot length mask and
    their scale planes are zero, so no read ever sees them. Prefix caching
    and handoff compose unchanged: a page's (packed, scale) content is a
    deterministic function of the token prefix."""

    k: jnp.ndarray   # uint8 [L, P, Hkv, page, D//2] packed nibbles
    v: jnp.ndarray   # uint8 [L, P, Hkv, page, D//2]
    ks: jnp.ndarray  # bf16 [L, P, Hkv, page]
    vs: jnp.ndarray  # bf16 [L, P, Hkv, page]

    @classmethod
    def create(cls, layers: int, pages: int, page_size: int, kv_heads: int,
               head_dim: int, dtype=None, sharding=None) -> "Q4PagedKVCache":
        del dtype
        if head_dim % 2:
            raise ValueError(f"int4 packing needs an even head_dim, got {head_dim}")
        shape = (layers, pages, kv_heads, page_size, head_dim // 2)
        sshape = (layers, pages, kv_heads, page_size)
        return cls(
            k=_zeros(shape, jnp.uint8, sharding), v=_zeros(shape, jnp.uint8, sharding),
            ks=_zeros(sshape, jnp.bfloat16, sharding),
            vs=_zeros(sshape, jnp.bfloat16, sharding),
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


def kv_plane_bytes_per_position(layers: int, kv_heads: int, head_dim: int,
                                kv_dtype: str = "bf16",
                                dense_bytes: int = 2,
                                shards: int = 1) -> int:
    """Analytic per-position pool footprint across every cache plane, by
    layout contract: dense pools carry k+v at ``dense_bytes`` per element
    (bf16 on TPU; pass 4 where the backend promotes to fp32, as CPU
    does), the int8 pool carries k+v int8 plus the two bf16 scale planes,
    and the packed-int4 pool halves the nibble planes. This is the
    cross-check for the EXACT accounting the live perf plane reads off
    the pool leaves (metrics/perf.py) and what bench archives as
    ``kv_bytes_per_decode_token`` — on the tiny CPU config the three
    layouts come out 512 / 144 / 80.

    ``shards`` > 1 reports the PER-DEVICE footprint of a tp-sharded pool
    (KV heads split over the mesh's tp axis): each device holds
    ``kv_heads // shards`` heads of every plane. Requires divisibility —
    sharding never pads heads."""
    if shards > 1:
        if kv_heads % shards:
            raise ValueError(
                f"kv_heads={kv_heads} not divisible by shards={shards}")
        kv_heads //= shards
    if kv_dtype == "int4":
        per = 2 * (head_dim // 2) + 4   # packed k+v nibbles + bf16 scales
    elif kv_dtype in ("int8", "q", "quant"):
        per = 2 * head_dim + 4          # int8 k+v + bf16 scale planes
    else:
        per = 2 * head_dim * int(dense_bytes)
    return layers * kv_heads * per


def write_prompts_paged_q(
    cache_q: jnp.ndarray,  # int8 [P, Hkv, page, D] (one of k/v)
    cache_s: jnp.ndarray,  # [P, Hkv, page]
    pages: jnp.ndarray,    # [B, S_pages]
    new: jnp.ndarray,      # [B, S, Hkv, D]
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized analog of write_prompts_paged for one k/v plane, with
    chunk offsets (logical positions offsets..offsets+S)."""
    b, s, hkv, _ = new.shape
    page = cache_q.shape[2]
    q, sc = quantize_row(new)  # [B,S,Hkv,D] int8, [B,S,Hkv]
    pos = jnp.arange(s)[None, :] + (offsets[:, None] if offsets is not None else 0)
    pp, off = _locate(pages, pos, page)  # [B,S] each
    rows = pp[:, :, None]
    heads = jnp.arange(hkv)[None, None, :]
    offs = off[:, :, None]
    cache_q = cache_q.at[rows, heads, offs].set(q)
    cache_s = cache_s.at[rows, heads, offs].set(sc.astype(cache_s.dtype))
    return cache_q, cache_s


def append_tokens_paged_q(
    cache_q: jnp.ndarray,   # int8 [P, Hkv, page, D]
    cache_s: jnp.ndarray,   # [P, Hkv, page]
    table: jnp.ndarray,     # [N, MaxP]
    positions: jnp.ndarray, # [N]
    new: jnp.ndarray,       # [N, Hkv, D]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized analog of append_tokens_paged for one k/v plane, honoring
    the same write-mode lowering switch (select default — the measured
    v5e winner; scatter optional). The one-hot fold runs in f32 and casts
    back: int8 magnitudes <= 127 are exact in f32."""
    n, hkv, d = new.shape
    p_total, _, page, _ = cache_q.shape
    q, sc = quantize_row(new)  # [N,Hkv,D] int8, [N,Hkv] f32
    pp, off = _locate(table, positions[:, None], page)
    pp, off = pp[:, 0], off[:, 0]

    if resolve_write_mode() != "scatter":
        flat = pp * page + off  # OOB rows land >= p_total*page
        grid = jnp.arange(p_total * page)
        m = flat[:, None] == grid[None, :]  # [N, P*page]
        any_m = m.reshape(n, p_total, page).any(axis=0)
        mf = m.astype(jnp.float32)
        upd = jnp.einsum("np,nhd->phd", mf, q.astype(jnp.float32))
        upd = upd.reshape(p_total, page, hkv, d).transpose(0, 2, 1, 3)
        cache_q = jnp.where(any_m[:, None, :, None], upd.astype(jnp.int8), cache_q)
        upd_s = jnp.einsum("np,nh->ph", mf, sc).reshape(p_total, page, hkv)
        cache_s = jnp.where(any_m[:, None, :],
                            upd_s.transpose(0, 2, 1).astype(cache_s.dtype), cache_s)
        return cache_q, cache_s

    rows = pp[:, None]
    heads = jnp.arange(hkv)[None, :]
    cache_q = cache_q.at[rows, heads, off[:, None]].set(q)
    cache_s = cache_s.at[rows, heads, off[:, None]].set(sc.astype(cache_s.dtype))
    return cache_q, cache_s


def _corrupt_scales(gs: jnp.ndarray) -> jnp.ndarray:
    """Chaos point ``quality.corrupt``: multiply the gathered dequant scales
    by ``factor`` (default 1.5). Evaluated at TRACE time, so an engine built
    under ``chaos.override("quality.corrupt:drop,factor=8")`` bakes the
    corruption into its compiled decode program — deterministic plausible
    wrong tokens, exactly the silent-numerics failure the quality plane
    exists to catch (and a different HLO hash, so the persistent compile
    cache can't serve a clean program). Unarmed: returns ``gs`` untouched."""
    from gofr_tpu.fleet import chaos

    pt = chaos.hook("quality.corrupt")
    if pt is not None and pt():
        factor = float(pt.params.get("factor", "1.5"))
        gs = gs * jnp.asarray(factor, gs.dtype)
    return gs


def gather_kv_q(
    cache_q: jnp.ndarray,  # int8 [P, Hkv, page, D]
    cache_s: jnp.ndarray,  # [P, Hkv, page]
    table: jnp.ndarray,    # [N, MaxP]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Logical ([N, Hkv, MaxP*page, D] int8, [N, Hkv, MaxP*page] scale)
    views of each slot's quantized cache (the XLA read path)."""
    n, maxp = table.shape
    _, hkv, page, d = cache_q.shape
    safe = jnp.minimum(table, cache_q.shape[0] - 1)

    gq = cache_q[safe].transpose(0, 2, 1, 3, 4).reshape(n, hkv, maxp * page, d)
    gs = cache_s[safe].transpose(0, 2, 1, 3).reshape(n, hkv, maxp * page)
    return gq, _corrupt_scales(gs)


def write_prompts_paged_q4(
    cache_q: jnp.ndarray,  # uint8 [P, Hkv, page, D//2] packed (one of k/v)
    cache_s: jnp.ndarray,  # [P, Hkv, page]
    pages: jnp.ndarray,    # [B, S_pages]
    new: jnp.ndarray,      # [B, S, Hkv, D]
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """int4 analog of write_prompts_paged_q for one k/v plane: quantize to
    nibbles, pack two-per-byte, write bytes through the block table."""
    b, s, hkv, _ = new.shape
    page = cache_q.shape[2]
    q, sc = quantize_row_int4(new)  # [B,S,Hkv,D] int8, [B,S,Hkv]
    packed = pack_int4(q)           # [B,S,Hkv,D//2] uint8
    pos = jnp.arange(s)[None, :] + (offsets[:, None] if offsets is not None else 0)
    pp, off = _locate(pages, pos, page)  # [B,S] each
    rows = pp[:, :, None]
    heads = jnp.arange(hkv)[None, None, :]
    offs = off[:, :, None]
    cache_q = cache_q.at[rows, heads, offs].set(packed)
    cache_s = cache_s.at[rows, heads, offs].set(sc.astype(cache_s.dtype))
    return cache_q, cache_s


def append_tokens_paged_q4(
    cache_q: jnp.ndarray,   # uint8 [P, Hkv, page, D//2] packed
    cache_s: jnp.ndarray,   # [P, Hkv, page]
    table: jnp.ndarray,     # [N, MaxP]
    positions: jnp.ndarray, # [N]
    new: jnp.ndarray,       # [N, Hkv, D]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """int4 analog of append_tokens_paged_q for one k/v plane, honoring the
    same write-mode lowering switch. The one-hot fold runs in f32 over the
    PACKED bytes and casts back — uint8 magnitudes <= 255 are exact in
    f32, so the byte round-trips losslessly."""
    n, hkv, d2 = new.shape[0], new.shape[1], cache_q.shape[3]
    p_total, _, page, _ = cache_q.shape
    q, sc = quantize_row_int4(new)  # [N,Hkv,D] int8, [N,Hkv] f32
    packed = pack_int4(q)           # [N,Hkv,D//2] uint8
    pp, off = _locate(table, positions[:, None], page)
    pp, off = pp[:, 0], off[:, 0]

    if resolve_write_mode() != "scatter":
        flat = pp * page + off  # OOB rows land >= p_total*page
        grid = jnp.arange(p_total * page)
        m = flat[:, None] == grid[None, :]  # [N, P*page]
        any_m = m.reshape(n, p_total, page).any(axis=0)
        mf = m.astype(jnp.float32)
        upd = jnp.einsum("np,nhd->phd", mf, packed.astype(jnp.float32))
        upd = upd.reshape(p_total, page, hkv, d2).transpose(0, 2, 1, 3)
        cache_q = jnp.where(any_m[:, None, :, None], upd.astype(jnp.uint8), cache_q)
        upd_s = jnp.einsum("np,nh->ph", mf, sc).reshape(p_total, page, hkv)
        cache_s = jnp.where(any_m[:, None, :],
                            upd_s.transpose(0, 2, 1).astype(cache_s.dtype), cache_s)
        return cache_q, cache_s

    rows = pp[:, None]
    heads = jnp.arange(hkv)[None, :]
    cache_q = cache_q.at[rows, heads, off[:, None]].set(packed)
    cache_s = cache_s.at[rows, heads, off[:, None]].set(sc.astype(cache_s.dtype))
    return cache_q, cache_s


def gather_kv_q4(
    cache_q: jnp.ndarray,  # uint8 [P, Hkv, page, D//2] packed
    cache_s: jnp.ndarray,  # [P, Hkv, page]
    table: jnp.ndarray,    # [N, MaxP]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Logical ([N, Hkv, MaxP*page, D] int8 in [-8, 7], [N, Hkv, MaxP*page]
    scale) views of each slot's packed cache — the XLA read path unpacks
    AFTER the gather so HBM reads stay packed; the unpacked view feeds the
    same ``decode_attention_q`` contraction the int8 layout uses."""
    n, maxp = table.shape
    _, hkv, page, d2 = cache_q.shape
    safe = jnp.minimum(table, cache_q.shape[0] - 1)

    gq = cache_q[safe].transpose(0, 2, 1, 3, 4).reshape(n, hkv, maxp * page, d2)
    gs = cache_s[safe].transpose(0, 2, 1, 3).reshape(n, hkv, maxp * page)
    return unpack_int4(gq), _corrupt_scales(gs)


def write_prompts_paged(
    k_layer: jnp.ndarray,  # [P, Hkv, page, D]
    v_layer: jnp.ndarray,
    pages: jnp.ndarray,    # [B, S_pages] physical page per logical page (P = dropped)
    k_new: jnp.ndarray,    # [B, S, Hkv, D] activation layout
    v_new: jnp.ndarray,
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write prefilled prompts (or prompt CHUNKS) through per-row block
    tables. ``pages[b, j]`` is the physical page holding positions
    j*page .. (j+1)*page of row b; ``offsets`` [B] places the chunk at
    logical positions offsets..offsets+S (None = 0)."""
    b, s, hkv, _ = k_new.shape
    page = k_layer.shape[2]
    pos = jnp.arange(s)[None, :] + (offsets[:, None] if offsets is not None else 0)
    pp, off = _locate(pages, pos, page)  # [B,S] each
    rows = pp[:, :, None]
    heads = jnp.arange(hkv)[None, None, :]
    offs = off[:, :, None]
    k_layer = k_layer.at[rows, heads, offs].set(k_new.astype(k_layer.dtype))
    v_layer = v_layer.at[rows, heads, offs].set(v_new.astype(v_layer.dtype))
    return k_layer, v_layer


def append_tokens_paged(
    k_layer: jnp.ndarray,   # [P, Hkv, page, D]
    v_layer: jnp.ndarray,
    table: jnp.ndarray,     # [N, MaxP] block table for every slot
    positions: jnp.ndarray, # [N] logical write position per slot
    k_new: jnp.ndarray,     # [N, Hkv, D]
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V per slot at its current logical position.

    Two lowerings, chosen by ``GOFR_PAGED_KV_WRITE`` (default ``select``;
    anything else means ``scatter``): ``select`` rebuilds the pool through
    a one-hot einsum + masked select — the same trick that beat XLA's
    scatter ~1.4-2x for the slot cache on v5e (ops/kvcache.append_tokens) —
    while ``scatter`` keeps the advanced-indexing scatter (cheaper
    asymptotically for very large pools, where the one-hot matmul and
    full-pool rewrite start to dominate). The choice comes from
    ``resolve_write_mode()``: engines resolve ``GOFR_PAGED_KV_WRITE``
    once at construction and pin it for their traces (``write_mode_scope``);
    the env var is only the fallback for direct callers. jit caches traces
    process-globally, so the choice is effectively FIXED FOR THE LIFE OF
    THE PROCESS — A/B the two lowerings across separate processes, not by
    flipping the var between engine builds. OOB semantics are
    preserved either way: OOB rows' flat position falls outside the one-hot
    range, producing an all-false mask row (the scatter path relies on XLA
    dropping OOB updates)."""
    n, hkv, d = k_new.shape
    p_total, _, page, _ = k_layer.shape

    mode = resolve_write_mode()
    if mode == "pallas":
        from gofr_tpu.ops.pallas import interpret_mode, kernel_platform

        if kernel_platform():
            from gofr_tpu.ops.pallas.kv_append import append_tokens_paged_inplace

            return append_tokens_paged_inplace(
                k_layer, v_layer, table, positions, k_new, v_new,
                interpret=interpret_mode(),
            )

    pp, off = _locate(table, positions[:, None], page)
    pp, off = pp[:, 0], off[:, 0]  # [N]

    if mode != "scatter":
        flat = pp * page + off  # [N]; OOB rows land >= p_total*page
        grid = jnp.arange(p_total * page)
        m = flat[:, None] == grid[None, :]  # [N, P*page]
        any_m = m.reshape(n, p_total, page).any(axis=0)[:, None, :, None]
        def fold(new, layer):
            upd = jnp.einsum("np,nhd->phd", m.astype(layer.dtype), new.astype(layer.dtype))
            upd = upd.reshape(p_total, page, hkv, d).transpose(0, 2, 1, 3)
            return jnp.where(any_m, upd, layer)
        return fold(k_new, k_layer), fold(v_new, v_layer)

    rows = pp[:, None]
    heads = jnp.arange(hkv)[None, :]
    k_layer = k_layer.at[rows, heads, off[:, None]].set(k_new.astype(k_layer.dtype))
    v_layer = v_layer.at[rows, heads, off[:, None]].set(v_new.astype(v_layer.dtype))
    return k_layer, v_layer


# -- hierarchical prefix cache: per-page host spill / swap-in -------------------
#
# The engine's host-DRAM cache tier (tpu/prefix.py, docs/serving.md) moves
# whole pages between the pool and host memory. Both helpers work on the
# cache PYTREE (PagedKVCache, QPagedKVCache, or Q4PagedKVCache), so one
# definition covers the bf16 layout (k/v planes), the int8 layout, and the
# packed-int4 layout (k/v bytes + ks/vs scale planes) — every plane is
# [L, P, ...page-slice dims...] and the page axis is always axis 1. Packed
# int4 pages spill/swap as opaque uint8 bytes; no repack is ever needed.


@jax.jit
def gather_page(cache, page_id):
    """Slice ONE page's content out of every plane of a paged cache pytree:
    each [L, P, ...] plane yields [L, ...]. ``page_id`` is a traced scalar,
    so one compiled program per cache type serves every spill. The engine
    reads the result back to host (``np.asarray``) at spill time — the page
    is an immutable cache leaf, so the latest ``engine.cache`` value is its
    authoritative content."""
    return jax.tree.map(lambda a: a[:, page_id], cache)


@partial(jax.jit, donate_argnums=(0,))
def swap_in_pages(cache, page_ids, payload):
    """Write host-staged page payloads back into the pool. ``page_ids`` [W]
    (padded with an out-of-bounds id — pool size — whose scatter writes XLA
    DROPS, the same convention block tables use); ``payload`` mirrors the
    cache pytree with per-plane [L, W, ...page-slice dims...] stacks.
    Returns ``(new_cache, marker)`` — the marker is a tiny output of the
    same executable, so reading it back (the unified pipeline's fold)
    blocks until the whole upload has landed without pulling the pool to
    host. The cache argument is donated, matching every other engine
    program that rewrites it (tpu/programs.py)."""
    new = jax.tree.map(
        lambda a, p: a.at[:, page_ids].set(p.astype(a.dtype)), cache, payload
    )
    return new, jnp.sum(page_ids)


def gather_kv(
    k_layer: jnp.ndarray,  # [P, Hkv, page, D]
    v_layer: jnp.ndarray,
    table: jnp.ndarray,    # [N, MaxP]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize the logical [N, Hkv, MaxP*page, D] view of each slot's
    cache (XLA fallback read path; the Pallas paged-decode kernel reads the
    pool directly instead). OOB table entries clamp — callers must mask by
    lengths, which the attention ops already do."""
    n, maxp = table.shape
    _, hkv, page, d = k_layer.shape

    def view(layer):
        g = layer[jnp.minimum(table, layer.shape[0] - 1)]  # [N, MaxP, Hkv, page, D]
        return g.transpose(0, 2, 1, 3, 4).reshape(n, hkv, maxp * page, d)

    return view(k_layer), view(v_layer)
