"""Token sampling with static shapes.

``top_k``/``top_p``/``do_sample`` are static (they change the compiled
program); ``temperature`` is a traced scalar so one compiled step serves
any temperature. Fully-batched: one call samples every decode slot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def truncate_logits(logits: jnp.ndarray, top_k: int = 0, top_p: float = 1.0) -> jnp.ndarray:
    """Mask (to NEG_INF) everything outside the top_k / nucleus-top_p set
    along the last axis; any leading dims. The top-1 is always kept (so
    top_p=0.0 degrades to greedy, not uniform garbage). This is THE
    truncation — sample_token and speculative_sample apply the identical
    mask, which is what makes truncated speculative sampling exact
    w.r.t. the truncated target."""
    if top_k > 0 and top_k < logits.shape[-1]:
        vals, _ = lax.top_k(logits, top_k)
        kth = vals[..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)

    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens while the cumulative prob BEFORE them is < top_p
        keep_sorted = (jnp.roll(cum, 1, axis=-1) < top_p).at[..., 0].set(True)
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, NEG_INF, logits)
    return logits


def sample_token(
    logits: jnp.ndarray,
    key: jax.Array,
    *,
    temperature: jnp.ndarray | float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    do_sample: bool = True,
) -> jnp.ndarray:
    """Sample next tokens from ``logits`` [B, V] → [B] int32.

    ``temperature`` may be a scalar or per-row [B] array; rows with
    temperature <= 0 decode greedily (the continuous-batching engine mixes
    greedy and sampled requests in one step this way).
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not do_sample:
        return greedy

    temp = jnp.asarray(temperature, jnp.float32)  # scalar or [B]; shape is static under jit
    logits = logits.astype(jnp.float32) / jnp.maximum(
        temp[:, None] if temp.ndim == 1 else temp, 1e-6
    )
    logits = truncate_logits(logits, top_k, top_p)
    sampled = jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
    # temperature <= 0 → greedy, for scalar and per-row alike
    return jnp.where(temp > 0, sampled, greedy)
