"""Flash attention (blocked online-softmax) as a Pallas TPU kernel.

Prefill-shaped attention without materializing the [Sq, Skv] score matrix in
HBM: the grid walks (batch, q_head, q_block, kv_block) with the kv dimension
innermost/sequential, carrying the running max / normalizer / output
accumulator in VMEM scratch across kv blocks. Q@K^T and P@V both hit the MXU
at [block_q, block_kv] x [block_kv, d] tiles; softmax bookkeeping runs on the
VPU in f32.

The public contract is activation layout [B, S, H, D]; internally tensors
are viewed head-major [B, H, S, D] because TPU block tiling needs the last
two block dims (8k, 128k)-aligned — one transpose XLA fuses into the
producing matmul. GQA is handled in the index maps: q head h reads kv head
h // group so K/V are never repeated in HBM. Causal masking supports a
per-batch ``q_offset`` so chunked prefill at cache offset t attends as
positions t..t+Sq; ``kv_lengths`` masks padded keys. Fully-masked query rows
produce zeros, not NaN (parity with ops.attention._softmax).

Reference capability map: SURVEY.md §2.9 / §7 stage 3 — the reference
(request-level Go framework) has no kernels; this is the TPU-native hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gofr_tpu.ops.pallas.common import (
    NEG_INF,
    CompilerParams,
    init_softmax_scratch,
    softmax_block_update,
    softmax_finish,
)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _flash_kernel(
    qo_ref,  # SMEM [B] per-batch q position offset
    kl_ref,  # SMEM [B] per-batch kv length
    q_ref,   # VMEM [1, 1, block_q, d]
    k_ref,   # VMEM [1, 1, block_kv, d]
    v_ref,   # VMEM [1, 1, block_kv, d]
    o_ref,   # VMEM [1, 1, block_q, d]
    acc_ref,  # scratch f32 [block_q, d]
    m_ref,    # scratch f32 [block_q, 128]
    l_ref,    # scratch f32 [block_q, 128]
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_kv: int,
    n_kvb: int,
):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    init_softmax_scratch(ki, acc_ref, m_ref, l_ref)

    # Causal block skip: a kv block starting past this q block's last global
    # position is fully masked — skip its matmuls entirely (~2x less MXU
    # work for square causal prefill; the DMA still streams, bounded by the
    # grid, but compute is the prefill bottleneck at these tile sizes).
    needed = True
    if causal:
        needed = ki * block_kv <= qi * block_q + block_q - 1 + qo_ref[bi]

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_kv] f32

        kv_pos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = kv_pos < kl_ref[bi]
        if causal:
            q_pos = (
                qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
                + qo_ref[bi]
            )
            mask = mask & (q_pos >= kv_pos)
        s = jnp.where(mask, s, NEG_INF)

        softmax_block_update(s, v, acc_ref, m_ref, l_ref)

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    softmax_finish(ki, n_kvb, acc_ref, l_ref, write)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_kv", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    q_offset: jnp.ndarray | int = 0,
    kv_lengths: jnp.ndarray | None = None,
    scale: float | None = None,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """q [B, Sq, Hq, D]; k, v [B, Skv, Hkv, D] → [B, Sq, Hq, D].

    Same contract as ops.attention.mha_attention (minus ``bias``).
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {hkv}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)

    if not isinstance(q_offset, jnp.ndarray) or q_offset.ndim == 0:
        q_offset = jnp.full((b,), q_offset, jnp.int32)
    q_offset = q_offset.astype(jnp.int32)
    if kv_lengths is None:
        kv_lengths = jnp.full((b,), skv, jnp.int32)
    kv_lengths = kv_lengths.astype(jnp.int32)

    # head-major views; the pads land on the (blocked) sequence dims
    qh = q.swapaxes(1, 2)  # [B, Hq, Sq, D]
    kh = k.swapaxes(1, 2)  # [B, Hkv, Skv, D]
    vh = v.swapaxes(1, 2)

    bq = min(block_q, _round_up(sq, 8))
    bkv = min(block_kv, _round_up(skv, 8))
    sq_p, skv_p = _round_up(sq, bq), _round_up(skv, bkv)
    if sq_p != sq:
        qh = jnp.pad(qh, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if skv_p != skv:
        # padded keys sit at positions >= skv >= kv_lengths → masked out
        kh = jnp.pad(kh, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    n_qb, n_kvb = sq_p // bq, skv_p // bkv

    kernel = functools.partial(
        _flash_kernel,
        scale=scale, causal=causal, block_q=bq, block_kv=bkv, n_kvb=n_kvb,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, hq, n_qb, n_kvb),
        in_specs=[
            pl.BlockSpec((b,), lambda bi, hi, qi, ki: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((b,), lambda bi, hi, qi, ki: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_offset, kv_lengths, qh, kh, vh)
    return out[:, :, :sq].swapaxes(1, 2)
