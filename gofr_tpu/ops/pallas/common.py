"""Shared pieces of the blocked online-softmax recurrence.

Both flash (prefill) and decode kernels carry (m, l, acc) scratch across
sequential kv-block grid steps; the numerics — the NEG_INF fully-masked-row
guard and the normalizer clamp — must stay identical between them, so they
live here once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as _pltpu

NEG_INF = -1e30

# jax renamed pltpu.TPUCompilerParams → CompilerParams (~0.5); same fields
# either way. One shim here so every kernel works across the pin range.
CompilerParams = getattr(_pltpu, "CompilerParams", None) or _pltpu.TPUCompilerParams


def init_softmax_scratch(ki, acc_ref, m_ref, l_ref) -> None:
    """Zero the accumulators at the first kv block of each output tile."""

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)


def softmax_block_update(s, v, acc_ref, m_ref, l_ref, v_scale=None) -> None:
    """One online-softmax step: fold masked scores ``s`` [rows, block_kv]
    (f32, masked entries == NEG_INF) and values ``v`` [block_kv, d] into the
    running (acc, m, l) scratch. Fully-masked-so-far rows keep l == 0 so the
    final divide yields zeros, not NaN.

    ``v_scale`` [block_kv] is the int8-KV dequant fold: per-position value
    scales ride the probabilities before the PV contraction — the same
    place the XLA path folds ``vs`` (ops.attention.decode_attention_q) —
    so a quantized ``v`` stays int8 in HBM/VMEM and converts only at the
    matmul input. The normalizer ``l`` is scale-free either way (it sums
    the unscaled probabilities)."""
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_next = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # exp against a safe 0 for all-masked rows keeps exp(NEG_INF) == 0
    # instead of exp(0) == 1.
    m_safe = jnp.where(m_next > NEG_INF / 2, m_next, 0.0)

    p = jnp.exp(s - m_safe)          # masked entries underflow to 0
    alpha = jnp.exp(m_prev - m_safe)  # rescale of previous blocks
    l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

    if v_scale is None:
        p_in, v_in = p.astype(v.dtype), v
    else:
        p_in = p * v_scale.astype(jnp.float32)[None, :]
        v_in = v.astype(jnp.float32)
    pv = jax.lax.dot_general(
        p_in, v_in, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)


def softmax_finish(ki, n_kvb, acc_ref, l_ref, write) -> None:
    """After the last kv block, normalize and hand the tile to ``write``."""

    @pl.when(ki == n_kvb - 1)
    def _():
        write(acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-20))
