"""Single-token decode attention over the slot KV cache as a Pallas kernel.

The decode hot loop is HBM-bandwidth bound: every step streams the whole
cache [Slots, Hkv, Smax, D] past one query token per slot. This kernel walks
the grid (slot, kv_head, kv_block) reading [block_kv, D] tiles straight out
of the head-major serving layout (see gofr_tpu.ops.kvcache docstring) — no
transpose, no repeat of K/V for grouped queries — and computes the G grouped
query heads of each kv head as the rows of one [G, block_kv] MXU tile, with
the online-softmax state in VMEM scratch across kv blocks (same recurrence
as flash_attention).

Positions >= lengths[slot] are masked, so freshly-recycled slots and the
zero-padded tail of the cache never leak into live requests
(gofr_tpu.ops.kvcache semantics; continuous-batching engine contract).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gofr_tpu.ops.pallas.common import (
    NEG_INF,
    CompilerParams,
    init_softmax_scratch,
    softmax_block_update,
    softmax_finish,
)


def _pick_block(total: int, desired: int) -> int:
    """Largest block <= desired that divides total (cache Smax is fixed at
    serving time, so we never pad-copy the cache)."""
    if total <= desired:
        return total
    for cand in range(desired, 0, -1):
        if total % cand == 0:
            return cand
    return total


def _decode_kernel(
    ln_ref,   # SMEM [B] per-slot live length
    q_ref,    # VMEM [1, 1, G, d]
    k_ref,    # VMEM [1, 1, block_kv, d]
    v_ref,    # VMEM [1, 1, block_kv, d]
    o_ref,    # VMEM [1, 1, G, d]
    acc_ref,  # scratch f32 [G, d]
    m_ref,    # scratch f32 [G, 128]
    l_ref,    # scratch f32 [G, 128]
    *,
    scale: float,
    block_kv: int,
    n_kvb: int,
    group: int,
):
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    init_softmax_scratch(ki, acc_ref, m_ref, l_ref)

    q = q_ref[0, 0]  # [G, d]
    k = k_ref[0, 0]  # [block_kv, d]
    v = v_ref[0, 0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, block_kv]

    kv_pos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, (group, block_kv), 1)
    s = jnp.where(kv_pos < ln_ref[bi], s, NEG_INF)

    softmax_block_update(s, v, acc_ref, m_ref, l_ref)

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    softmax_finish(ki, n_kvb, acc_ref, l_ref, write)


@functools.partial(jax.jit, static_argnames=("scale", "block_kv", "interpret"))
def decode_attention(
    q: jnp.ndarray,        # [B, Hq, D]
    k_cache: jnp.ndarray,  # [B, Hkv, Smax, D] head-major (kvcache layout)
    v_cache: jnp.ndarray,  # [B, Hkv, Smax, D]
    lengths: jnp.ndarray,  # [B]
    *,
    scale: float | None = None,
    block_kv: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Same contract as ops.attention.decode_attention → [B, Hq, D]."""
    b, hq, d = q.shape
    _, hkv, smax, _ = k_cache.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {hkv}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)
    bkv = _pick_block(smax, block_kv)
    n_kvb = smax // bkv

    # Head h groups under kv head h // G (ops.attention._group_query_heads).
    q4 = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_kv=bkv, n_kvb=n_kvb, group=group
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, hkv, n_kvb),
        in_specs=[
            pl.BlockSpec((b,), lambda bi, hi, ki: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, group, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q4, k_cache, v_cache)
    return out.reshape(b, hq, d)
