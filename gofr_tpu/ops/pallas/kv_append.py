"""In-place KV append as a Pallas kernel (decode-bandwidth lever).

The XLA lowerings of the per-step KV append both pay O(cache) HBM traffic:
the masked-select path rewrites the ENTIRE layer buffer every decode step
(read + write of [N, Hkv, Smax, D]), and the scatter path materializes a
non-aliased copy (BASELINE.md round-3 select-vs-scatter notes). But the
append itself only CHANGES one [Hkv, D] row per slot. This kernel writes in
place via ``input_output_aliases``: the grid walks slots, scalar-prefetched
positions pick the [block_s, D] tile containing each slot's write row
(data-dependent BlockSpec index_map), and the kernel copies that one tile
through with the new row patched in. Per-step traffic drops from
O(N·Hkv·Smax·D) to O(N·Hkv·block_s·D) — a (Smax/block_s)× reduction on the
axis long-context decode is bound by.

Out-of-bounds convention (engine padding/bubble rows): positions >= Smax
clamp to the last tile in the index_map and the row store is skipped, so
the tile is copied through unchanged — the same dropped-write semantics as
the XLA paths (ops/kvcache.append_tokens, ops/paged.append_tokens_paged).

The paged variant routes the tile pick through the slot's block table
(physical page = table[n, pos // page]), writing straight into the pool.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gofr_tpu.ops.pallas.common import CompilerParams


def _pick_block(total: int, desired: int) -> int:
    if total <= desired:
        return total
    for cand in range(desired, 0, -1):
        if total % cand == 0:
            return cand
    return total


def _append_kernel(pos_ref, knew_ref, vnew_ref, k_ref, v_ref, ko_ref, vo_ref,
                   *, block_s: int, smax: int):
    n = pl.program_id(0)
    pos = pos_ref[n]
    # copy the resident tile through (aliased output: same HBM buffer, but
    # the VMEM out block must be fully defined)
    ko_ref[0] = k_ref[0]
    vo_ref[0] = v_ref[0]

    @pl.when(pos < smax)
    def _():
        off = pos % block_s
        ko_ref[0, :, pl.ds(off, 1), :] = knew_ref[0][:, None, :].astype(ko_ref.dtype)
        vo_ref[0, :, pl.ds(off, 1), :] = vnew_ref[0][:, None, :].astype(vo_ref.dtype)


def append_tokens_inplace(
    k_layer: jnp.ndarray,   # [N, Hkv, Smax, D]
    v_layer: jnp.ndarray,
    positions: jnp.ndarray, # [N]
    k_new: jnp.ndarray,     # [N, Hkv, D]
    v_new: jnp.ndarray,
    *,
    block_s: int = 128,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Slot-cache append writing only the tile containing each row."""
    n, hkv, smax, d = k_layer.shape
    bs = _pick_block(smax, block_s)
    pos = positions.astype(jnp.int32)

    def cache_map(bi, pos_ref):
        return (bi, 0, jnp.minimum(pos_ref[bi] // bs, smax // bs - 1), 0)

    kernel = functools.partial(_append_kernel, block_s=bs, smax=smax)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, hkv, d), lambda bi, p: (bi, 0, 0)),
                pl.BlockSpec((1, hkv, d), lambda bi, p: (bi, 0, 0)),
                pl.BlockSpec((1, hkv, bs, d), cache_map),
                pl.BlockSpec((1, hkv, bs, d), cache_map),
            ],
            out_specs=[
                pl.BlockSpec((1, hkv, bs, d), cache_map),
                pl.BlockSpec((1, hkv, bs, d), cache_map),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k_layer.shape, k_layer.dtype),
            jax.ShapeDtypeStruct(v_layer.shape, v_layer.dtype),
        ],
        # inputs 3/4 are (k_layer, v_layer) AFTER the prefetch operand;
        # aliasing makes the untouched tiles true no-ops in HBM
        input_output_aliases={3: 0, 4: 1},
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(pos, k_new, v_new, k_layer, v_layer)


def append_tokens_paged_inplace(
    k_pool: jnp.ndarray,    # [P, Hkv, page, D]
    v_pool: jnp.ndarray,
    table: jnp.ndarray,     # [N, MaxP] (OOB entries == P)
    positions: jnp.ndarray, # [N]
    k_new: jnp.ndarray,     # [N, Hkv, D]
    v_new: jnp.ndarray,
    *,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Paged-pool append writing only the page holding each slot's row.

    OOB rows (table entry == P) redirect their tile fetch to page 0 and
    skip the row store. Page 0 is RESERVED as a never-allocated sink by
    the engine whenever this lowering is enabled (GOFR_PAGED_KV_WRITE=
    pallas), so an OOB copy-through can never revisit a tile that a real
    row writes in the same call — under Mosaic's double-buffered block
    pipelining such a revisit could write back a stale copy over the real
    row (ADVICE r4). Positions beyond the table span clamp to the lane's
    OWN last page (each lane appears in the grid once, so no cross-step
    tile sharing there either)."""
    n, hkv, d = k_new.shape
    pool, _, page, _ = k_pool.shape
    _, maxp = table.shape
    pos = positions.astype(jnp.int32)
    tbl = table.astype(jnp.int32)

    def pool_map(bi, pos_ref, table_ref):
        logical = jnp.minimum(pos_ref[bi] // page, maxp - 1)
        entry = table_ref[bi, logical]
        # OOB sentinel (== pool) -> the reserved sink page 0, never a
        # clamp onto a page another grid step may write
        return (jnp.where(entry < pool, entry, 0), 0, 0, 0)

    def _kernel(pos_ref, table_ref, knew_ref, vnew_ref, k_ref, v_ref, ko_ref, vo_ref):
        i = pl.program_id(0)
        p = pos_ref[i]
        logical = p // page
        valid = (logical < maxp) & (p >= 0)
        # OOB pages (table entry == pool size) must drop the write
        entry = table_ref[i, jnp.minimum(logical, maxp - 1)]
        ko_ref[0] = k_ref[0]
        vo_ref[0] = v_ref[0]

        @pl.when(valid & (entry < pool))
        def _():
            off = p % page
            ko_ref[0, :, pl.ds(off, 1), :] = knew_ref[0][:, None, :].astype(ko_ref.dtype)
            vo_ref[0, :, pl.ds(off, 1), :] = vnew_ref[0][:, None, :].astype(vo_ref.dtype)

    return pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, hkv, d), lambda bi, p, t: (bi, 0, 0)),
                pl.BlockSpec((1, hkv, d), lambda bi, p, t: (bi, 0, 0)),
                pl.BlockSpec((1, hkv, page, d), pool_map),
                pl.BlockSpec((1, hkv, page, d), pool_map),
            ],
            out_specs=[
                pl.BlockSpec((1, hkv, page, d), pool_map),
                pl.BlockSpec((1, hkv, page, d), pool_map),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ],
        input_output_aliases={4: 0, 5: 1},
        compiler_params=CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(pos, tbl, k_new, v_new, k_pool, v_pool)
