"""Decode attention over the PAGED KV cache as a Pallas kernel.

Same HBM-bound hot loop as decode_attention.py, but K/V tiles come out of
the physical page pool [P, Hkv, page, D] through each slot's block table
instead of a contiguous [Smax] row. The table and per-slot lengths ride in
as SCALAR-PREFETCH operands (pltpu.PrefetchScalarGridSpec), so the BlockSpec
index_map can resolve ``grid step (slot, head, logical_page) -> physical
page`` BEFORE the DMA is issued — the kernel streams exactly the pages a
slot owns, never a gather-materialized copy of the logical view (that copy
is the XLA fallback, ops.paged.gather_kv).

Grid: (slot, kv_head, logical_page); the page axis is ``arbitrary`` so the
online-softmax scratch (common.py recurrence) carries across pages of one
(slot, head). Unallocated logical pages (table entry == P) clamp to P-1 and
are fully position-masked, contributing nothing.

``paged_decode_attention_q`` is the fused int8-KV variant (ISSUE 6 /
ROADMAP O3): quantized K/V pages plus their per-position scale planes
stream straight out of the pool through the SAME scalar-prefetched block
tables and dequantize in-kernel — ``ks`` multiplies the scores, ``vs``
rides the probabilities inside the online-softmax recurrence (common.py),
exactly where the XLA path folds them (ops.attention.decode_attention_q).
No gather-materialized logical view exists anywhere: HBM traffic for the
most bandwidth-bound op in the system stays int8 end to end, where the
XLA fallback pays a full extra int8 round trip for the gather copy.

``paged_decode_attention_q4`` (ISSUE 13) extends the same discipline to
PACKED int4 pages: the pool stores two nibbles per byte ([P, Hkv, page,
D//2] uint8, ops/quant.pack_int4 split-half order), the kernel streams the
packed bytes through the identical scalar-prefetched block tables, and the
nibble unpack + dequant happen in-register — HBM reads per KV token halve
again vs int8. The scale folds are byte-for-byte the int8 kernel's: ks on
the scores after the QK matmul, vs inside the online-softmax recurrence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gofr_tpu.ops.pallas.common import (
    NEG_INF,
    CompilerParams,
    init_softmax_scratch,
    softmax_block_update,
    softmax_finish,
)


def _paged_decode_kernel(
    ln_ref,    # SMEM [N] per-slot live length (scalar prefetch)
    table_ref, # SMEM [N, MaxP] block table (scalar prefetch)
    q_ref,     # VMEM [1, 1, G, d]
    k_ref,     # VMEM [1, 1, page, d] — the physical page picked by index_map
    v_ref,     # VMEM [1, 1, page, d]
    o_ref,     # VMEM [1, 1, G, d]
    acc_ref,   # scratch f32 [G, d]
    m_ref,     # scratch f32 [G, 128]
    l_ref,     # scratch f32 [G, 128]
    *,
    scale: float,
    page: int,
    n_pages: int,
    group: int,
):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    init_softmax_scratch(pi, acc_ref, m_ref, l_ref)

    q = q_ref[0, 0]  # [G, d]
    k = k_ref[0, 0]  # [page, d]
    v = v_ref[0, 0]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, page]

    kv_pos = pi * page + jax.lax.broadcasted_iota(jnp.int32, (group, page), 1)
    s = jnp.where(kv_pos < ln_ref[bi], s, NEG_INF)

    softmax_block_update(s, v, acc_ref, m_ref, l_ref)

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    softmax_finish(pi, n_pages, acc_ref, l_ref, write)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention(
    q: jnp.ndarray,        # [N, Hq, D]
    k_pool: jnp.ndarray,   # [P, Hkv, page, D]
    v_pool: jnp.ndarray,   # [P, Hkv, page, D]
    table: jnp.ndarray,    # [N, MaxP] int32, OOB entries == P
    lengths: jnp.ndarray,  # [N] live length per slot
    *,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Single-step decode against the paged pool → [N, Hq, D]."""
    n, hq, d = q.shape
    pool, hkv, page, _ = k_pool.shape
    _, maxp = table.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {hkv}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)

    q4 = q.reshape(n, hkv, group, d)
    safe_table = jnp.minimum(table, pool - 1).astype(jnp.int32)

    def kv_map(bi, hi, pi, ln_ref, table_ref):
        return (table_ref[bi, pi], hi, 0, 0)

    kernel = functools.partial(
        _paged_decode_kernel, scale=scale, page=page, n_pages=maxp, group=group
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n, hkv, maxp),
            in_specs=[
                pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, page, d), kv_map),
                pl.BlockSpec((1, 1, page, d), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, d), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, hkv, group, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), safe_table, q4, k_pool, v_pool)
    return out.reshape(n, hq, d)


def _paged_decode_q_kernel(
    ln_ref,    # SMEM [N] per-slot live length (scalar prefetch)
    table_ref, # SMEM [N, MaxP] block table (scalar prefetch)
    q_ref,     # VMEM [1, 1, G, d]
    k_ref,     # VMEM int8 [1, 1, page, d] — the physical page from index_map
    v_ref,     # VMEM int8 [1, 1, page, d]
    ks_ref,    # VMEM [1, 1, page] per-position K scales (same page pick)
    vs_ref,    # VMEM [1, 1, page]
    o_ref,     # VMEM [1, 1, G, d]
    acc_ref,   # scratch f32 [G, d]
    m_ref,     # scratch f32 [G, 128]
    l_ref,     # scratch f32 [G, 128]
    *,
    scale: float,
    page: int,
    n_pages: int,
    group: int,
):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    init_softmax_scratch(pi, acc_ref, m_ref, l_ref)

    q = q_ref[0, 0]                      # [G, d]
    k = k_ref[0, 0].astype(q.dtype)      # int8 → compute dtype, in VMEM
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, page]
    # K-scale fold: constant along the d reduction, so it multiplies the
    # finished scores per key position (decode_attention_q order: scale
    # before the mask, where a masked position's value is irrelevant).
    s = s * ks_ref[0, 0].astype(jnp.float32)[None, :]

    kv_pos = pi * page + jax.lax.broadcasted_iota(jnp.int32, (group, page), 1)
    s = jnp.where(kv_pos < ln_ref[bi], s, NEG_INF)

    # V-scale fold happens inside the recurrence (common.py): probabilities
    # pick up vs before the PV matmul, v converts from int8 at the input.
    softmax_block_update(s, v_ref[0, 0], acc_ref, m_ref, l_ref,
                         v_scale=vs_ref[0, 0])

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    softmax_finish(pi, n_pages, acc_ref, l_ref, write)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention_q(
    q: jnp.ndarray,        # [N, Hq, D]
    kq_pool: jnp.ndarray,  # int8 [P, Hkv, page, D]
    vq_pool: jnp.ndarray,  # int8 [P, Hkv, page, D]
    ks_pool: jnp.ndarray,  # [P, Hkv, page] per-position K scales
    vs_pool: jnp.ndarray,  # [P, Hkv, page]
    table: jnp.ndarray,    # [N, MaxP] int32, OOB entries == P
    lengths: jnp.ndarray,  # [N] live length per slot
    *,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused single-step decode against the int8 paged pool → [N, Hq, D].

    Same contract as ops.attention.paged_decode_attention_q, without the
    gather: int8 pages and their scale rows are block-streamed per
    (slot, head, logical page) and dequantized in-register."""
    n, hq, d = q.shape
    pool, hkv, page, _ = kq_pool.shape
    _, maxp = table.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {hkv}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)

    q4 = q.reshape(n, hkv, group, d)
    safe_table = jnp.minimum(table, pool - 1).astype(jnp.int32)

    def kv_map(bi, hi, pi, ln_ref, table_ref):
        return (table_ref[bi, pi], hi, 0, 0)

    def sc_map(bi, hi, pi, ln_ref, table_ref):
        return (table_ref[bi, pi], hi, 0)

    kernel = functools.partial(
        _paged_decode_q_kernel, scale=scale, page=page, n_pages=maxp, group=group
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n, hkv, maxp),
            in_specs=[
                pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, page, d), kv_map),
                pl.BlockSpec((1, 1, page, d), kv_map),
                pl.BlockSpec((1, 1, page), sc_map),
                pl.BlockSpec((1, 1, page), sc_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, d), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, hkv, group, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), safe_table, q4, kq_pool, vq_pool, ks_pool, vs_pool)
    return out.reshape(n, hq, d)


def _paged_decode_q4_kernel(
    ln_ref,    # SMEM [N] per-slot live length (scalar prefetch)
    table_ref, # SMEM [N, MaxP] block table (scalar prefetch)
    q_ref,     # VMEM [1, 1, G, d]
    k_ref,     # VMEM uint8 [1, 1, page, d//2] packed nibbles (index_map page)
    v_ref,     # VMEM uint8 [1, 1, page, d//2]
    ks_ref,    # VMEM [1, 1, page] per-position K scales (same page pick)
    vs_ref,    # VMEM [1, 1, page]
    o_ref,     # VMEM [1, 1, G, d]
    acc_ref,   # scratch f32 [G, d]
    m_ref,     # scratch f32 [G, 128]
    l_ref,     # scratch f32 [G, 128]
    *,
    scale: float,
    page: int,
    n_pages: int,
    group: int,
):
    bi = pl.program_id(0)
    pi = pl.program_id(2)
    init_softmax_scratch(pi, acc_ref, m_ref, l_ref)

    def unpack(b):
        # split-half nibble unpack (ops/quant.unpack_int4, inlined on the
        # int32 VPU): byte j holds elements j (low) and j + d/2 (high),
        # each biased +8 — so the unpacked [page, d] tile is a concatenate
        # of two contiguous nibble planes, no interleave shuffle needed
        bi32 = b.astype(jnp.int32)
        return jnp.concatenate([(bi32 & 0xF) - 8, ((bi32 >> 4) & 0xF) - 8], axis=-1)

    q = q_ref[0, 0]                              # [G, d]
    k = unpack(k_ref[0, 0]).astype(q.dtype)      # packed → [page, d] nibbles
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, page]
    # K-scale fold: identical order to the int8 kernel — constant along the
    # d reduction, multiplies the finished scores per key position.
    s = s * ks_ref[0, 0].astype(jnp.float32)[None, :]

    kv_pos = pi * page + jax.lax.broadcasted_iota(jnp.int32, (group, page), 1)
    s = jnp.where(kv_pos < ln_ref[bi], s, NEG_INF)

    # V-scale fold inside the recurrence (common.py), with V unpacked from
    # nibbles in-register — the PV matmul input converts to f32 there.
    softmax_block_update(s, unpack(v_ref[0, 0]), acc_ref, m_ref, l_ref,
                         v_scale=vs_ref[0, 0])

    def write(out):
        o_ref[0, 0] = out.astype(o_ref.dtype)

    softmax_finish(pi, n_pages, acc_ref, l_ref, write)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention_q4(
    q: jnp.ndarray,        # [N, Hq, D]
    kq_pool: jnp.ndarray,  # uint8 [P, Hkv, page, D//2] packed nibbles
    vq_pool: jnp.ndarray,  # uint8 [P, Hkv, page, D//2]
    ks_pool: jnp.ndarray,  # [P, Hkv, page] per-position K scales
    vs_pool: jnp.ndarray,  # [P, Hkv, page]
    table: jnp.ndarray,    # [N, MaxP] int32, OOB entries == P
    lengths: jnp.ndarray,  # [N] live length per slot
    *,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused single-step decode against the PACKED int4 pool → [N, Hq, D].

    Same contract as ops.attention.paged_decode_attention_q4, without the
    gather: packed nibble pages and their scale rows are block-streamed per
    (slot, head, logical page); unpack + dequant happen in-register, so HBM
    traffic for the KV read is the packed byte stream — half the int8
    kernel's, a quarter of bf16's."""
    n, hq, d = q.shape
    pool, hkv, page, d2 = kq_pool.shape
    _, maxp = table.shape
    if hq % hkv != 0:
        raise ValueError(f"query heads {hq} not divisible by kv heads {hkv}")
    if d2 * 2 != d:
        raise ValueError(f"packed head_dim {d2}*2 != query head_dim {d}")
    group = hq // hkv
    scale = scale if scale is not None else 1.0 / (d**0.5)

    q4 = q.reshape(n, hkv, group, d)
    safe_table = jnp.minimum(table, pool - 1).astype(jnp.int32)

    def kv_map(bi, hi, pi, ln_ref, table_ref):
        return (table_ref[bi, pi], hi, 0, 0)

    def sc_map(bi, hi, pi, ln_ref, table_ref):
        return (table_ref[bi, pi], hi, 0)

    kernel = functools.partial(
        _paged_decode_q4_kernel, scale=scale, page=page, n_pages=maxp, group=group
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n, hkv, maxp),
            in_specs=[
                pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
                pl.BlockSpec((1, 1, page, d2), kv_map),
                pl.BlockSpec((1, 1, page, d2), kv_map),
                pl.BlockSpec((1, 1, page), sc_map),
                pl.BlockSpec((1, 1, page), sc_map),
            ],
            out_specs=pl.BlockSpec((1, 1, group, d), lambda bi, hi, pi, ln, tb: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, d), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
                pltpu.VMEM((group, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n, hkv, group, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), safe_table, q4, kq_pool, vq_pool, ks_pool, vs_pool)
    return out.reshape(n, hq, d)
