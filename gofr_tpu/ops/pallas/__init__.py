"""Hand-written Pallas TPU kernels (flash attention, slot-cache decode).

Kernels target the TPU memory hierarchy (HBM→VMEM blocks, MXU-sized
tiles). On CPU they run only under the Pallas interpreter — set
``GOFR_PALLAS_INTERPRET=1``, as tests/test_pallas.py does for its parity
cases (the rest of the suite runs the XLA path) — otherwise callers go
through ``flash_attention_available()`` and fall back to the XLA path, so
the same model code runs on the test mesh and real chips.

``GOFR_PALLAS=0`` force-disables the kernels even on TPU (escape hatch /
A-B benchmarking).
"""

from __future__ import annotations

import contextlib
import contextvars
import os

import jax

# Where the computation being *traced* will actually run. jax.default_backend()
# lies when a TPU is attached but the target mesh is CPU (the multichip dryrun,
# CPU test meshes), so mesh-aware callers (make_train_step, engines) pin it.
_PLATFORM: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "gofr_pallas_platform", default=None
)


@contextlib.contextmanager
def platform_hint(platform: str | None):
    """Pin the target platform for backend resolution while tracing, e.g.
    ``with platform_hint(mesh.devices.flat[0].platform): step_fn(...)``."""
    tok = _PLATFORM.set(platform)
    try:
        yield
    finally:
        _PLATFORM.reset(tok)


def interpret_mode() -> bool:
    """True when kernels should run under the Pallas interpreter (CPU tests)."""
    return os.environ.get("GOFR_PALLAS_INTERPRET", "") == "1"


def kernel_platform() -> bool:
    """True when the traced computation targets hardware (or the
    interpreter) that can actually lower the Pallas kernels."""
    if interpret_mode():
        return True
    platform = _PLATFORM.get()
    try:
        if platform is None:
            platform = jax.default_backend()
    except Exception:  # noqa: BLE001
        return False
    return platform in ("tpu", "axon")


def flash_attention_available() -> bool:
    """Should ``backend='auto'`` pick the hand-written kernels, absent a
    per-op autotune decision?

    This is the LAST stop in resolve_backend's precedence chain
    (ops/attention.py): the decode ops prefer a warmup-autotune pin
    (ops/autotune.py — measured per (op, shape, kv dtype, device_kind) on
    the engine's real serving shapes) whenever one is in scope, and
    GOFR_PALLAS, when explicitly set, overrides both. The static default
    here encodes the round-3 v5e measurement: XLA beat the then-current
    kernels on BOTH paths — decode 6.4k vs 4.6k tok/s @64 slots,
    prefill(512) 34.7k vs 27.2k tok/s — so 'auto' falls back to XLA on
    hardware. Interpreter tests still exercise the kernels
    (GOFR_PALLAS_INTERPRET=1), and an explicit ``backend='pallas'``
    bypasses this gate entirely."""
    if os.environ.get("GOFR_PALLAS", "") == "0":
        return False
    if interpret_mode():
        return True
    return os.environ.get("GOFR_PALLAS", "") == "1" and kernel_platform()


__all__ = ["flash_attention_available", "interpret_mode", "kernel_platform", "platform_hint"]
