"""Hand-written Pallas TPU kernels (flash attention, paged decode).

Kernels target the TPU memory hierarchy (HBM→VMEM blocks, MXU-sized
tiles) and are unavailable on CPU — callers go through
``flash_attention_available()`` and fall back to the XLA path, so the
same model code runs on the test mesh and real chips.
"""

from __future__ import annotations

import jax


def flash_attention_available() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False
