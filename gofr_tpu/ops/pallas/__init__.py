"""Hand-written Pallas TPU kernels (flash attention, slot-cache decode).

Kernels target the TPU memory hierarchy (HBM→VMEM blocks, MXU-sized
tiles). On CPU they run only under the Pallas interpreter — set
``GOFR_PALLAS_INTERPRET=1``, as tests/test_pallas.py does for its parity
cases (the rest of the suite runs the XLA path) — otherwise callers go
through ``flash_attention_available()`` and fall back to the XLA path, so
the same model code runs on the test mesh and real chips.

``GOFR_PALLAS=0`` force-disables the kernels even on TPU (escape hatch /
A-B benchmarking).
"""

from __future__ import annotations

import os

import jax


def interpret_mode() -> bool:
    """True when kernels should run under the Pallas interpreter (CPU tests)."""
    return os.environ.get("GOFR_PALLAS_INTERPRET", "") == "1"


def flash_attention_available() -> bool:
    if os.environ.get("GOFR_PALLAS", "") == "0":
        return False
    if interpret_mode():
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


__all__ = ["flash_attention_available", "interpret_mode"]
