"""Warmup-time Pallas-vs-XLA backend autotuner for the decode attention ops.

The static ``GOFR_PALLAS=1`` gate encoded one round-3 measurement ("XLA
faster on v5e") as policy. This module replaces it with the same
measure-then-pin philosophy GSPMD applies to sharding (PAPERS.md,
2105.04663): at ``engine.warmup()`` each decode op in play — ``decode``
(slot bf16), ``paged_decode`` (paged bf16), ``paged_decode_q`` (paged
int8) and ``paged_decode_q4`` (paged packed-int4; both fused kernels live
in ops/pallas/paged_decode.py) — is timed with BOTH
backends on the engine's real post-sharding serving shapes, the winner is
pinned via :func:`decision_scope`, and every trace the engine drives
(warmup + device loop, ``engine._trace_scope``) resolves ``backend="auto"``
to the pinned winner.

Precedence, highest first (docs/kernels.md):

1. an explicit ``backend=`` argument at an op call site;
2. an explicit ``GOFR_PALLAS`` env value (``0`` or ``1``) — the operator
   override; when it is set the autotuner does not even run;
3. a pinned autotune decision for the op (this module);
4. the legacy default (``pallas.flash_attention_available()``: XLA on
   hardware, Pallas under the interpreter).

Decisions persist to a JSON cache file (``GOFR_AUTOTUNE_CACHE``) keyed by
``device_kind|op|shape|kv_dtype`` so fleet restarts (PR5 epochs, the
Supervisor runbook) skip re-timing: a restarted engine's warmup finds its
exact key and pins without touching the device. Corrupt files, version
mismatches and malformed entries are ignored (re-measured), never fatal.

``GOFR_AUTOTUNE=0`` is the escape hatch: no timing, no pins — today's
static resolution, bit-for-bit. The autotuner also stands down under the
Pallas interpreter (interpreter timings say nothing about hardware) and
under lockstep (engine-side gate: a leader-only pin would desynchronize
follower traces).

Caveat shared with ``GOFR_PAGED_KV_WRITE``: jit caches traces
process-globally, so the first engine to trace a given program signature
fixes that signature's backend for the life of the process — A/B across
processes, not by re-tuning in one.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import time
from typing import Any, Callable

FORMAT_VERSION = 1
BACKENDS = ("pallas", "xla")

# {op: backend} pinned for the traces inside a decision_scope — consulted
# by ops.attention.resolve_backend for backend="auto". Same engine-pins-
# for-its-traces pattern as paged.write_mode_scope / pallas.platform_hint.
_PINS: contextvars.ContextVar[dict[str, str] | None] = contextvars.ContextVar(
    "gofr_autotune_pins", default=None
)

# Most recent report produced by an Autotuner in this process — bench.py
# records it in the bench JSON after the headline engine is gone.
_LAST_REPORT: dict[str, Any] | None = None


def enabled() -> bool:
    """Should warmup measure and pin? ``GOFR_AUTOTUNE=0`` disables; an
    explicit ``GOFR_PALLAS`` (0/1) is an operator override that makes
    timing pointless; interpreter-mode timings are meaningless for
    hardware (and the CPU test suite relies on 'auto' → interpreter)."""
    from gofr_tpu.ops.pallas import interpret_mode

    if os.environ.get("GOFR_AUTOTUNE", "") == "0":
        return False
    if os.environ.get("GOFR_PALLAS", "") in ("0", "1"):
        return False
    return not interpret_mode()


def cache_path() -> str | None:
    return os.environ.get("GOFR_AUTOTUNE_CACHE") or None


@contextlib.contextmanager
def decision_scope(pins: dict[str, str] | None):
    """Pin ``{op: backend}`` decisions for every trace inside the scope."""
    tok = _PINS.set(pins)
    try:
        yield
    finally:
        _PINS.reset(tok)


def pinned_backend(op: str | None) -> str | None:
    """The pinned backend for ``op`` in the current decision scope, or None
    (no scope / no decision for this op → caller falls back to defaults)."""
    if op is None:
        return None
    pins = _PINS.get()
    if not pins:
        return None
    return pins.get(op)


def shape_key(*dims: int) -> str:
    return "x".join(str(int(d)) for d in dims)


def entry_key(device_kind: str, op: str, shape: str, kv_dtype: str,
              role: str = "", sharding: str = "") -> str:
    """Cache key. ``role`` (ENGINE_ROLE, disaggregated serving) and
    ``sharding`` (pool mesh sharding, e.g. ``"tp4"``) are appended only
    when they narrow the decision — ``""``/``"both"`` role and ``""``
    sharding keep the exact pre-feature key, so existing cache files stay
    valid and an unsharded engine never reads a sharded pin (or vice
    versa: per-shard shapes change the winner, so pins must not leak
    across mesh geometries)."""
    key = "|".join((str(device_kind), op, shape, str(kv_dtype)))
    if role and role != "both":
        key += f"|role={role}"
    if sharding:
        key += f"|shard={sharding}"
    return key


def set_last_report(report: dict[str, Any] | None) -> None:
    global _LAST_REPORT
    _LAST_REPORT = report


def last_report() -> dict[str, Any] | None:
    return _LAST_REPORT


def _default_timer(fn: Callable[[], Any], repeats: int = 3) -> float:
    """Seconds for one call, best-of-``repeats`` with the compile paid
    outside the timed window (the candidate closures are jitted on real
    device-shaped inputs, so call 0 is the XLA/Mosaic compile)."""
    import jax

    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _load_cache(path: str | None, logger: Any = None) -> dict[str, dict]:
    """Entries from the cache file; {} for missing/corrupt/stale files —
    a bad cache must cost one re-measure, never a failed warmup."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("version") != FORMAT_VERSION:
            raise ValueError(f"version {doc.get('version')!r} != {FORMAT_VERSION}")
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            raise ValueError("no entries dict")
        out = {}
        for key, rec in entries.items():
            if isinstance(rec, dict) and rec.get("backend") in BACKENDS:
                out[key] = rec
        return out
    except Exception as e:  # noqa: BLE001 - corrupt/stale cache is re-measured
        if logger is not None:
            logger.warn(f"ignoring autotune cache {path}: {e}")
        return {}


class Autotuner:
    """Times backend candidates per (op, shape, kv dtype) and records the
    winner. ``timer`` is injectable (tests pin deterministic fake timings
    without lowering any kernel); ``cache_file`` round-trips decisions
    across process restarts."""

    def __init__(self, device_kind: str = "cpu", cache_file: str | None = None,
                 timer: Callable[[Callable[[], Any]], float] | None = None,
                 logger: Any = None, role: str = "", sharding: str = ""):
        self.device_kind = device_kind
        self.cache_file = cache_file
        self.timer = timer or _default_timer
        self.logger = logger
        # role-scoped keys (disaggregation): a decode-role spare's pins live
        # under their own cache keys, so its warmup neither waits on nor
        # clobbers a colocated engine's measurements for the same shapes
        self.role = role if role not in ("", "both") else ""
        # sharding-scoped keys (tp pool sharding): per-shard shapes are
        # 1/tp the replicated ones, so a pin measured on one mesh geometry
        # is stale for another; "" (unsharded) keeps pre-feature keys
        self.sharding = sharding or ""
        self.decisions: dict[str, dict] = {}  # op -> decision record
        self._cache = _load_cache(cache_file, logger)  # lookups only
        self._own: dict[str, dict] = {}  # keys THIS tuner decided (persisted)

    def measure(self, op: str, shape: str, kv_dtype: str,
                candidates: dict[str, Callable[[], Any]]) -> str:
        """Pin a backend for ``op``: cache hit > timed winner > the single
        candidate (no timing when there is nothing to compare — the CPU
        fallback path costs zero device work). A candidate that raises
        (e.g. Mosaic rejects the shape) loses by disqualification; if every
        candidate fails, 'xla' — the everywhere-correct path — is pinned."""
        key = entry_key(self.device_kind, op, shape, kv_dtype, self.role,
                        self.sharding)
        cached = self._cache.get(key)
        if cached is not None and cached.get("backend") in candidates:
            rec = {"backend": cached["backend"], "shape": shape, "kv_dtype": kv_dtype,
                   "timings_ms": cached.get("timings_ms", {}), "source": "cache"}
            self.decisions[op] = rec
            return rec["backend"]

        if len(candidates) == 1:
            backend = next(iter(candidates))
            rec = {"backend": backend, "shape": shape, "kv_dtype": kv_dtype,
                   "timings_ms": {}, "source": "only_candidate"}
        else:
            timings: dict[str, float] = {}
            errors: dict[str, str] = {}
            for name, fn in candidates.items():
                try:
                    timings[name] = round(self.timer(fn) * 1000.0, 4)
                except Exception as e:  # noqa: BLE001 - a failing candidate loses
                    errors[name] = str(e)[:200]
            if timings:
                backend = min(timings, key=lambda n: timings[n])
            else:
                backend = "xla" if "xla" in candidates else next(iter(candidates))
            rec = {"backend": backend, "shape": shape, "kv_dtype": kv_dtype,
                   "timings_ms": timings, "source": "measured"}
            if errors:
                rec["errors"] = errors
        self.decisions[op] = rec
        self._persist(key, rec)
        return rec["backend"]

    def _persist(self, key: str, rec: dict) -> None:
        entry = {"backend": rec["backend"],
                 "timings_ms": rec.get("timings_ms", {}),
                 "at": time.time()}
        self._cache[key] = entry
        self._own[key] = entry
        if not self.cache_file:
            return
        try:
            # read-merge-write, merging ONLY the keys this tuner decided:
            # re-writing the whole init-time snapshot could revert another
            # process's fresher measurement for a key we never touched.
            # Atomic rename so a crash never leaves a torn file.
            merged = _load_cache(self.cache_file, self.logger)
            merged.update(self._own)
            tmp = f"{self.cache_file}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": FORMAT_VERSION, "entries": merged}, f, indent=1)
            os.replace(tmp, self.cache_file)
        except Exception as e:  # noqa: BLE001 - persistence is an optimization
            if self.logger is not None:
                self.logger.warn(f"could not persist autotune cache {self.cache_file}: {e}")

    def pins(self) -> dict[str, str]:
        return {op: rec["backend"] for op, rec in self.decisions.items()}

    def report(self) -> dict[str, Any]:
        out: dict[str, Any] = {"device_kind": self.device_kind,
                               "decisions": dict(self.decisions)}
        if self.role:
            out["role"] = self.role
        if self.sharding:
            out["sharding"] = self.sharding
        return out


__all__ = [
    "Autotuner", "cache_path", "decision_scope", "enabled", "entry_key",
    "last_report", "pinned_backend", "set_last_report", "shape_key",
]
