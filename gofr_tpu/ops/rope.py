"""Rotary position embeddings (RoPE), Llama-style half-split layout.

The cos/sin table is precomputed once per model (static shapes keep it out
of the per-step compile) and gathered by position ids — decode steps index
it with the current sequence offsets, so prefill and decode share one
implementation.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_table(
    max_len: int,
    head_dim: int,
    theta: float = 10000.0,
    scaling: float = 1.0,
    dtype=jnp.float32,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(cos, sin) tables of shape [max_len, head_dim//2]. ``scaling`` > 1
    is linear position-interpolation context extension."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    positions = jnp.arange(max_len, dtype=jnp.float32) / scaling
    angles = jnp.outer(positions, inv_freq)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    cos_table: jnp.ndarray,
    sin_table: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by the angles at
    ``positions`` [..., seq]. Uses the "half-split" convention (x1 = first
    half, x2 = second half) matching Llama/HF `rotate_half`."""
    cos = cos_table[positions]  # [..., seq, half]
    sin = sin_table[positions]
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)
