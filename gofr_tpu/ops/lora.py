"""Batched multi-LoRA logits delta for mixed-adapter decode.

One device call serves many adapters: every lane (slot) in a decode /
prefill / verify step carries an ``adapter slot id`` into the program, the
program gathers that lane's low-rank factors out of the device-resident
adapter pool (adapters/pool.py), and the per-lane delta

    delta = scale[sel] * (x @ a[sel]) @ b[sel]

is added to the base-model logits at the (single, uniform) lm_head site.
Applying LoRA at the head only — rather than per-layer q/k/v/o — is the v1
contract that keeps the rest of the serving plane valid: the KV cache stays
adapter-independent, so the prefix cache, paged handoff, and ring affinity
all keep working unchanged across adapters.

Exactness contract (tested by tests/test_adapters.py):

- Pool slot 0 is the reserved BASE slot: zero factors, zero scale. A lane
  with ``adapter_id=None`` selects slot 0 and its delta is exactly 0.0 in
  f32, so base-lane logits are bit-identical to the pre-adapter engine
  (adding 0.0 is exact; the only representable difference would be -0.0,
  which is invisible to argmax and softmax alike).
- Ranks below the pool's Rmax are zero-padded; padded columns contribute
  exact zeros, so a rank-4 adapter in a rank-16 pool produces the same
  delta as in a rank-4 pool.
- Lanes are independent (the gather + two einsums never mix the lane
  axis), so a mixed-adapter batch is token-exact vs running each adapter
  in isolation.

The math runs in f32 regardless of the base dtype: deltas are small and
the head matmul already casts logits to f32, so this adds no precision
cliff relative to the base path.
"""

from __future__ import annotations

import jax.numpy as jnp


def lora_logits_delta(x: jnp.ndarray, adapters) -> jnp.ndarray:
    """Per-lane low-rank logits delta, gathered from the adapter pool.

    ``x`` is the hidden state entering the lm_head: ``[N, E]`` (prefill
    last-token rows / decode) or ``[N, T, E]`` (verify: T speculative
    positions per lane). ``adapters`` is the 4-tuple the engine threads
    through the packed program call:

    - ``sel``   int32 ``[N]``   — per-lane pool slot id (0 = base)
    - ``a``     ``[S, E, R]``   — down-projection pool (R = Rmax)
    - ``b``     ``[S, R, V]``   — up-projection pool
    - ``scale`` f32 ``[S]``     — per-slot alpha/r scaling (0 for slot 0)

    Returns an f32 delta shaped like the logits (``x.shape[:-1] + (V,)``).
    """
    sel, a, b, scale = adapters
    aw = a[sel].astype(jnp.float32)        # [N, E, R]
    bw = b[sel].astype(jnp.float32)        # [N, R, V]
    xf = x.astype(jnp.float32)
    # "..." spans the optional verify T axis; lanes never mix.
    low = jnp.einsum("n...e,ner->n...r", xf, aw)
    delta = jnp.einsum("n...r,nrv->n...v", low, bw)
    s = scale[sel].astype(jnp.float32)
    return delta * s.reshape(s.shape + (1,) * (delta.ndim - 1))


__all__ = ["lora_logits_delta"]
