"""Normalization ops.

Computed in float32 regardless of input dtype (bfloat16 accumulation loses
too much precision for variance), cast back on exit — the standard TPU
recipe; XLA fuses the whole thing into neighboring matmuls.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) / jnp.sqrt(var + eps)
    return (normed * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
