"""Slot-based KV cache for continuous batching.

One static buffer of shape [layers, slots, kv_heads, max_len, head_dim]
per K and V. The serving engine owns slot assignment: an arriving request
claims a free slot, prefill writes its prompt at offset 0, each decode
step appends one token at ``positions[slot]``, and the slot is recycled on
completion. Static shapes mean XLA compiles exactly one decode program for
the whole serving lifetime — the continuous-batching analog of the
reference's goroutine-per-request hot path (SURVEY.md §3.2).

Layout note: layers lead so a ``lax.scan`` over layers can carry the cache
as its xs/ys. Heads sit AHEAD of sequence (head-major) so the decode/flash
Pallas kernels can block one [block_kv, head_dim] tile per (slot, kv_head)
straight out of HBM — TPU tiling requires the last two dims of a block to
be (8k, 128k)-aligned, which a seq-major layout cannot satisfy per-head.
Activations stay [B, S, H, D]; the helpers below transpose at the write,
which XLA fuses into the scatter.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SlotKVCache:
    k: jnp.ndarray  # [L, B, Hkv, Smax, D]
    v: jnp.ndarray  # [L, B, Hkv, Smax, D]

    @classmethod
    def create(
        cls,
        layers: int,
        slots: int,
        max_len: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "SlotKVCache":
        shape = (layers, slots, kv_heads, max_len, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


@jax.tree_util.register_dataclass
@dataclass
class QSlotKVCache:
    """int8 KV cache with per-(slot, head, position) symmetric scales.

    Decode attention reads dominate HBM traffic at long context; storing
    K/V as int8 halves them. Scales live per cached ROW (reduction over D),
    so dequantization folds into the attention matmuls the same way weight
    scales fold into qdot (ops/quant.py): scores pick up ``ks`` per key
    position (constant along the D contraction), and the value matmul picks
    up ``vs`` on the probabilities (constant along its T contraction) —
    the int8 buffers convert at the matmul input and HBM traffic stays
    int8. Scale overhead: 2/D of the cache bytes (bf16 scales)."""

    k: jnp.ndarray   # int8 [L, B, Hkv, Smax, D]
    v: jnp.ndarray   # int8 [L, B, Hkv, Smax, D]
    ks: jnp.ndarray  # bf16 [L, B, Hkv, Smax]
    vs: jnp.ndarray  # bf16 [L, B, Hkv, Smax]

    @classmethod
    def create(cls, layers: int, slots: int, max_len: int, kv_heads: int,
               head_dim: int, dtype=jnp.bfloat16) -> "QSlotKVCache":
        del dtype  # storage is int8 by definition; arg kept for API parity
        shape = (layers, slots, kv_heads, max_len, head_dim)
        sshape = (layers, slots, kv_heads, max_len)
        return cls(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(sshape, jnp.bfloat16), vs=jnp.zeros(sshape, jnp.bfloat16),
        )

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


def quantize_row(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 over the last (head_dim) axis: returns (q int8,
    scale[...] f32 without the reduced axis)."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def write_prompts_q(
    cache_q: jnp.ndarray,   # int8 [Slots, Hkv, Smax, D] (one of k/v)
    cache_s: jnp.ndarray,   # [Slots, Hkv, Smax] scales
    slots: jnp.ndarray,
    new: jnp.ndarray,       # [B, S, Hkv, D] activation layout
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized analog of write_prompts for ONE of the k/v planes."""
    b, s, hkv, _ = new.shape
    q, sc = quantize_row(new)  # [B,S,Hkv,D] int8, [B,S,Hkv]
    rows = slots[:, None, None]
    heads = jnp.arange(hkv)[None, :, None]
    pos = jnp.arange(s)[None, None, :]
    if offsets is not None:
        pos = pos + offsets[:, None, None]
    cache_q = cache_q.at[rows, heads, pos].set(q.swapaxes(1, 2))
    cache_s = cache_s.at[rows, heads, pos].set(sc.swapaxes(1, 2).astype(cache_s.dtype))
    return cache_q, cache_s


def append_tokens_q(
    cache_q: jnp.ndarray,   # int8 [B, Hkv, Smax, D]
    cache_s: jnp.ndarray,   # [B, Hkv, Smax]
    positions: jnp.ndarray,
    new: jnp.ndarray,       # [B, Hkv, D]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantized analog of append_tokens (masked-select lowering; OOB
    positions drop) for one of the k/v planes."""
    smax = cache_q.shape[2]
    q, sc = quantize_row(new)  # [B,Hkv,D] int8, [B,Hkv]
    mask = (positions[:, None] == jnp.arange(smax)[None, :])  # [B, Smax]
    cache_q = jnp.where(mask[:, None, :, None], q[:, :, None, :], cache_q)
    cache_s = jnp.where(mask[:, None, :], sc[:, :, None].astype(cache_s.dtype), cache_s)
    return cache_q, cache_s


def fake_quant_row(x: jnp.ndarray, dtype=None, scale_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Round-trip ``x`` through int8 row quantization EXACTLY as the cache
    stores and the read path dequantizes it: the scale goes through the
    cache's scale dtype (bf16) and the multiply/cast order mirrors
    ``dequantize_view``. Prefill attention in the quantized branches uses
    this for the CURRENT chunk's k/v so cold prompts attend to exactly
    what a later prefix-cache hit will read — any representation mismatch
    (e.g. an f32 scale here vs the stored bf16 scale) would let hit and
    cold runs diverge near a logit tie."""
    q, s = quantize_row(x)
    out_dtype = dtype or x.dtype
    return q.astype(out_dtype) * s.astype(scale_dtype)[..., None].astype(out_dtype)


def dequantize_view(cache_q: jnp.ndarray, cache_s: jnp.ndarray, dtype) -> jnp.ndarray:
    """[.., Smax, D] int8 × [.., Smax] scales → dense dtype view (the
    chunked-prefill gather path; attention proper keeps int8 reads)."""
    return cache_q.astype(dtype) * cache_s[..., None].astype(dtype)


def write_prompts(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    slots: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write prefilled prompts [B, S, Hkv, D] (activation layout) into rows
    ``slots`` [B] at positions ``offsets``..``offsets``+S (0..S when
    offsets is None — whole-prompt prefill; nonzero for chunked prefill).
    ``k_layer``/``v_layer`` are per-layer views [Slots, Hkv, Smax, D]."""
    b, s, hkv, _ = k_new.shape
    rows = slots[:, None, None]
    heads = jnp.arange(hkv)[None, :, None]
    pos = jnp.arange(s)[None, None, :]
    if offsets is not None:
        pos = pos + offsets[:, None, None]
    k_layer = k_layer.at[rows, heads, pos].set(k_new.swapaxes(1, 2).astype(k_layer.dtype))
    v_layer = v_layer.at[rows, heads, pos].set(v_new.swapaxes(1, 2).astype(v_layer.dtype))
    return k_layer, v_layer


def write_prompt(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    slot: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Single-slot write of one prompt [S, Hkv, D] at offset 0."""
    slot = jnp.asarray(slot)[None]
    return write_prompts(k_layer, v_layer, slot, k_new[None], v_new[None])


def append_tokens(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    positions: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V per slot: k_new [B, Hkv, D] written at
    ``positions`` [B] in each slot's sequence dimension.

    Two lowerings, chosen by ``GOFR_KV_WRITE`` (read at TRACE time; jit
    caches traces process-globally, so A/B across processes):

    - ``select`` (default): masked full-buffer select — beat XLA's scatter
      ~1.4-2x on v5e round 3 (6429 scatter vs 8893 select tok/s at
      Smax=256, 2123 vs 4074 at Smax=1024) but still rewrites the whole
      layer buffer every step: O(N*Hkv*Smax*D) HBM traffic.
    - ``pallas``: in-place tile-patch kernel (ops/pallas/kv_append) —
      O(N*Hkv*block*D) traffic via input/output aliasing; requires a TPU
      (or the Pallas interpreter), falls back to select elsewhere."""
    import os

    if os.environ.get("GOFR_KV_WRITE", "select") == "pallas":
        from gofr_tpu.ops.pallas import interpret_mode, kernel_platform

        if kernel_platform():
            from gofr_tpu.ops.pallas.kv_append import append_tokens_inplace

            return append_tokens_inplace(
                k_layer, v_layer, positions, k_new, v_new,
                interpret=interpret_mode(),
            )
    smax = k_layer.shape[2]
    mask = (positions[:, None] == jnp.arange(smax)[None, :])[:, None, :, None]
    k_layer = jnp.where(mask, k_new.astype(k_layer.dtype)[:, :, None, :], k_layer)
    v_layer = jnp.where(mask, v_new.astype(v_layer.dtype)[:, :, None, :], v_layer)
    return k_layer, v_layer
