"""Slot-based KV cache for continuous batching.

One static buffer of shape [layers, slots, max_len, kv_heads, head_dim]
per K and V. The serving engine owns slot assignment: an arriving request
claims a free slot, prefill writes its prompt at offset 0, each decode
step appends one token at ``positions[slot]``, and the slot is recycled on
completion. Static shapes mean XLA compiles exactly one decode program for
the whole serving lifetime — the continuous-batching analog of the
reference's goroutine-per-request hot path (SURVEY.md §3.2).

Layout note: layers lead so a ``lax.scan`` over layers can carry the cache
as its xs/ys; [slots, max_len] next so per-slot scatters are contiguous.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SlotKVCache:
    k: jnp.ndarray  # [L, B, Smax, Hkv, D]
    v: jnp.ndarray  # [L, B, Smax, Hkv, D]

    @classmethod
    def create(
        cls,
        layers: int,
        slots: int,
        max_len: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "SlotKVCache":
        shape = (layers, slots, max_len, kv_heads, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def write_prompt(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    slot: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a prefilled prompt [S, Hkv, D] into one slot at offset 0.
    ``k_layer``/``v_layer`` are per-layer views [B, Smax, Hkv, D]."""
    k_layer = jax.lax.dynamic_update_slice(k_layer, k_new[None], (slot, 0, 0, 0))
    v_layer = jax.lax.dynamic_update_slice(v_layer, v_new[None], (slot, 0, 0, 0))
    return k_layer, v_layer


def append_tokens(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    positions: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V per slot: k_new [B, Hkv, D] written at
    ``positions`` [B] in each slot's sequence dimension."""
    b = k_layer.shape[0]
    idx = jnp.arange(b)
    k_layer = k_layer.at[idx, positions].set(k_new)
    v_layer = v_layer.at[idx, positions].set(v_new)
    return k_layer, v_layer
