"""Slot-based KV cache for continuous batching.

One static buffer of shape [layers, slots, kv_heads, max_len, head_dim]
per K and V. The serving engine owns slot assignment: an arriving request
claims a free slot, prefill writes its prompt at offset 0, each decode
step appends one token at ``positions[slot]``, and the slot is recycled on
completion. Static shapes mean XLA compiles exactly one decode program for
the whole serving lifetime — the continuous-batching analog of the
reference's goroutine-per-request hot path (SURVEY.md §3.2).

Layout note: layers lead so a ``lax.scan`` over layers can carry the cache
as its xs/ys. Heads sit AHEAD of sequence (head-major) so the decode/flash
Pallas kernels can block one [block_kv, head_dim] tile per (slot, kv_head)
straight out of HBM — TPU tiling requires the last two dims of a block to
be (8k, 128k)-aligned, which a seq-major layout cannot satisfy per-head.
Activations stay [B, S, H, D]; the helpers below transpose at the write,
which XLA fuses into the scatter.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SlotKVCache:
    k: jnp.ndarray  # [L, B, Hkv, Smax, D]
    v: jnp.ndarray  # [L, B, Hkv, Smax, D]

    @classmethod
    def create(
        cls,
        layers: int,
        slots: int,
        max_len: int,
        kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "SlotKVCache":
        shape = (layers, slots, kv_heads, max_len, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def num_layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]


def write_prompts(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    slots: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    offsets: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write prefilled prompts [B, S, Hkv, D] (activation layout) into rows
    ``slots`` [B] at positions ``offsets``..``offsets``+S (0..S when
    offsets is None — whole-prompt prefill; nonzero for chunked prefill).
    ``k_layer``/``v_layer`` are per-layer views [Slots, Hkv, Smax, D]."""
    b, s, hkv, _ = k_new.shape
    rows = slots[:, None, None]
    heads = jnp.arange(hkv)[None, :, None]
    pos = jnp.arange(s)[None, None, :]
    if offsets is not None:
        pos = pos + offsets[:, None, None]
    k_layer = k_layer.at[rows, heads, pos].set(k_new.swapaxes(1, 2).astype(k_layer.dtype))
    v_layer = v_layer.at[rows, heads, pos].set(v_new.swapaxes(1, 2).astype(v_layer.dtype))
    return k_layer, v_layer


def write_prompt(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    slot: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Single-slot write of one prompt [S, Hkv, D] at offset 0."""
    slot = jnp.asarray(slot)[None]
    return write_prompts(k_layer, v_layer, slot, k_new[None], v_new[None])


def append_tokens(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    positions: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Append one token's K/V per slot: k_new [B, Hkv, D] written at
    ``positions`` [B] in each slot's sequence dimension.

    Implemented as a masked full-buffer select, NOT a scatter. Measured on
    TPU v5e (round 3, 1B llama decode chunk, 64 slots): XLA lowers the
    advanced-indexing scatter inside the decode scan to something that
    scales with Smax and dominates the step — 6429 tok/s (scatter) vs 8893
    (select) at Smax=256, 2123 vs 4074 at Smax=1024. The select rewrites
    the whole layer buffer but fuses into one bandwidth-shaped pass, which
    the scatter evidently also pays (a non-aliased copy) without the fusion."""
    smax = k_layer.shape[2]
    mask = (positions[:, None] == jnp.arange(smax)[None, :])[:, None, :, None]
    k_layer = jnp.where(mask, k_new.astype(k_layer.dtype)[:, :, None, :], k_layer)
    v_layer = jnp.where(mask, v_new.astype(v_layer.dtype)[:, :, None, :], v_layer)
    return k_layer, v_layer
