"""Device-side ops: the compute kernels of the serving/training stack.

Everything here is pure-functional jax designed around TPU constraints
(SURVEY.md §7 design stance): static shapes, batched matmuls that tile onto
the MXU, elementwise work left to XLA fusion. ``ops.attention`` has a
backend switch — "xla" (einsum + softmax, fused by XLA) or "pallas"
(hand-written flash kernels in gofr_tpu.ops.pallas) — selected per call or
via the ``TPU_ATTENTION_BACKEND`` config.
"""

from gofr_tpu.ops.norms import layer_norm, rms_norm
from gofr_tpu.ops.rope import apply_rope, rope_table
from gofr_tpu.ops.attention import decode_attention, mha_attention, paged_decode_attention
from gofr_tpu.ops.kvcache import SlotKVCache
from gofr_tpu.ops.paged import PagedKVCache
from gofr_tpu.ops.sampling import sample_token

__all__ = [
    "layer_norm",
    "rms_norm",
    "apply_rope",
    "rope_table",
    "mha_attention",
    "decode_attention",
    "paged_decode_attention",
    "SlotKVCache",
    "PagedKVCache",
    "sample_token",
]
