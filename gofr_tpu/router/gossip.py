"""Replica-side health/epoch gossip over the pubsub backbone.

Every replica process runs one ``GossipReporter``: a daemon thread that
publishes a compact liveness snapshot to ``ROUTER_GOSSIP_TOPIC`` every
``ROUTER_GOSSIP_INTERVAL_S`` — the feed ``Router``'s registry consumes
(router/registry.py has the ring-membership state machine). The message
rides the same broker the app already uses for work distribution
(``PUBSUB_BACKEND``: inmemory for tests, file for multi-process on one
host, kafka/gcp beyond), so the router tier needs no new transport.

Snapshot schema (one JSON object per message):

    replica     stable replica name (defaults to APP_NAME)
    url         base URL the router proxies to
    status      UP | DEGRADED | DOWN — worst engine health
    epoch       max fleet/restart epoch over engines (fleet.epoch_of)
    restarting  any engine inside its PR 5 crash-recovery window
    draining    any engine in its scale-in drain (fleet/autoscaler.py):
                the registry moves the replica's keys to ring successors
    shedding    QoS shed within its window (AdmissionController.shedding)
    role        ENGINE_ROLE when role-split (disaggregated serving); the
                key is absent for colocated ("both") members
    handoff_addr decode-role KV handoff listener (host:port), role-split only
    retry_after backoff hint (s) for router-side sheds while unavailable
    seq, ts     per-reporter sequence + wall clock (debug only)
    digest      compact metrics/SLO digest (metrics/federation.py) for the
                router's fleet ``/metrics`` + ``/debug/fleet``; attached
                every ``ROUTER_GOSSIP_DIGEST_EVERY``-th publish (default
                every publish; 0 disables the digest entirely)

``stop()`` publishes a terminal ``DOWN`` so graceful shutdown leaves the
ring immediately instead of waiting out the router's gossip TTL.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from gofr_tpu.fleet import epoch_of

DEFAULT_TOPIC = "gofr.router.gossip"


class GossipReporter:
    def __init__(self, container, name: str | None = None, url: str = "", *,
                 topic: str | None = None, interval_s: float | None = None,
                 retry_after_s: float = 1.0):
        self.container = container
        conf = container.config
        self.name = name or container.app_name
        self.url = url
        self.topic = topic or conf.get_or_default("ROUTER_GOSSIP_TOPIC", DEFAULT_TOPIC)
        self.interval_s = (float(interval_s) if interval_s is not None
                           else conf.get_float("ROUTER_GOSSIP_INTERVAL_S", 1.0))
        self.retry_after_s = float(retry_after_s)
        self.digest_every = conf.get_int("ROUTER_GOSSIP_DIGEST_EVERY", 1)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- snapshot --------------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        status = "UP"
        restarting = False
        draining = False
        epoch = 0
        role = "both"
        handoff_addr = ""
        for engine in self.container.engines.values():
            er = str(getattr(engine, "role", "both") or "both")
            if er != "both":
                # role-split member (disaggregated serving): the router's
                # registry needs the role for stage-aware planning, and the
                # decode side's handoff listener for operator visibility
                role = er
                handoff_addr = handoff_addr or str(
                    getattr(engine, "handoff_addr", "") or "")
            try:
                h = (engine.health_check()
                     if hasattr(engine, "health_check") else {"status": "UP"})
            except Exception:  # noqa: BLE001 - a broken probe is a DOWN engine
                h = {"status": "DOWN"}
            s = str(h.get("status", "UP")).upper()
            if s == "DOWN":
                status = "DOWN"
            elif s != "UP" and status == "UP":
                status = "DEGRADED"
            restarting = restarting or bool(getattr(engine, "_restarting", False))
            draining = draining or bool(getattr(engine, "_draining", False))
            epoch = max(epoch, epoch_of(engine))
        qos = self.container.qos
        shedding = bool(qos.shedding) if qos is not None else False
        self._seq += 1
        snap: dict[str, Any] = {
            "replica": self.name, "url": self.url, "status": status,
            "epoch": epoch, "restarting": restarting, "draining": draining,
            "shedding": shedding,
            "retry_after": self.retry_after_s, "seq": self._seq,
            "ts": time.time(),
        }
        if role != "both":
            # only role-split members carry the keys — a colocated fleet's
            # gossip schema stays byte-identical to the pre-role wire format
            snap["role"] = role
            if handoff_addr:
                snap["handoff_addr"] = handoff_addr
            try:
                for engine in self.container.engines.values():
                    if hasattr(engine, "handoff_stats"):
                        snap["handoff"] = engine.handoff_stats()
                        break
            except Exception:  # noqa: BLE001 - liveness outranks the stats
                pass
        if self.digest_every > 0 and self._seq % self.digest_every == 0:
            try:
                from gofr_tpu.metrics import federation

                perf_fn = getattr(self.container, "perf_totals", None)
                knobs_fn = getattr(self.container, "knob_vectors", None)
                snap["digest"] = federation.digest(
                    self.container.metrics,
                    slo=getattr(self.container, "slo", None),
                    perf=perf_fn() if callable(perf_fn) else None,
                    knobs=knobs_fn() if callable(knobs_fn) else None,
                    inflight=sum(
                        int(getattr(e, "_inflight_requests", 0))
                        for e in self.container.engines.values()))
            except Exception as e:  # noqa: BLE001 - liveness gossip outranks the digest
                self.container.logger.warnf("gossip digest build failed: %r", e)
        return snap

    def publish_once(self, status: str | None = None) -> None:
        snap = self.snapshot()
        if status is not None:
            snap["status"] = status
        self.container.publish(self.topic, snap)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "GossipReporter":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"gofr-gossip-{self.name}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception as e:  # noqa: BLE001 - gossip must outlive broker blips
                self.container.logger.warnf("gossip publish failed: %r", e)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1.0)
        try:
            # terminal DOWN: leave the ring now, not at gossip-TTL expiry
            self.publish_once(status="DOWN")
        except Exception:  # noqa: BLE001 - broker may already be closed
            pass
