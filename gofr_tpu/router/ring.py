"""Consistent-hash ring over replica names (the router's placement function).

Each replica contributes ``vnodes`` virtual points (blake2b of
``"{name}#{i}"``) on a 64-bit circle; a request's shard key — the stable
prompt-prefix chain key from ``tpu.prefix.chain_key`` — lands on the first
point clockwise, and that point's replica is the HOME replica. Removing a
replica moves only the keys that lived on its points (≈1/N of the space)
onto their successors; every other key keeps its home — the property that
makes a restart window survivable without a full cache reshuffle
(GSPMD's shard-by-key framing, PAPERS.md 2105.04663, applied to the
request plane).

``lookup`` returns the DISTINCT replicas in ring order from the key's
successor: ``[0]`` is the home replica, the tail is the deterministic
spillover order the QoS policy walks when the home replica is shedding or
restarting (docs/routing.md).

Thread-safety: membership is mutated by the router's gossip thread while
request handler threads look keys up, so every method takes the ring's
own lock. A lookup racing a membership change may see the pre- or
post-change ring — either is a valid routing decision; what the lock
rules out is tearing (indexing a points list the mutator just rebound
shorter mid-iteration).
"""

from __future__ import annotations

import bisect
import hashlib
import threading

_MASK = (1 << 64) - 1


def hash_point(data: bytes) -> int:
    """Uniform 64-bit ring position (blake2b, process-stable)."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "little")


class HashRing:
    def __init__(self, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._points: list[tuple[int, str]] = []  # sorted (point, name)
        self._members: set[str] = set()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._members

    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def add(self, name: str) -> None:
        with self._lock:
            if name in self._members:
                return
            self._members.add(name)
            for i in range(self.vnodes):
                bisect.insort(self._points, (hash_point(f"{name}#{i}".encode()), name))

    def remove(self, name: str) -> None:
        with self._lock:
            if name not in self._members:
                return
            self._members.discard(name)
            self._points = [(p, n) for p, n in self._points if n != name]

    def lookup(self, key: int, n: int | None = None) -> list[str]:
        """Distinct replicas in ring order from ``key``'s successor point:
        ``[0]`` is the home replica, the rest the spillover order. ``n``
        caps the list (None = every member, home first)."""
        with self._lock:
            if not self._points:
                return []
            want = len(self._members) if n is None else max(0, min(int(n), len(self._members)))
            out: list[str] = []
            seen: set[str] = set()
            i = bisect.bisect_left(self._points, (key & _MASK,))
            for step in range(len(self._points)):
                _, name = self._points[(i + step) % len(self._points)]
                if name not in seen:
                    seen.add(name)
                    out.append(name)
                    if len(out) >= want:
                        break
            return out
