"""Replica registry: gossip-fed membership + the ring-admission state machine.

One ``Replica`` record per known replica, updated by health/epoch gossip
messages published on the pubsub backbone (router/gossip.py emits them,
``Router`` subscribes and feeds ``observe``). The registry owns the
consistent-hash ring's membership:

- ``UP`` (and not restarting) → in the ring, routable.
- ``shedding`` (QoS 429/503 within its shed window) → STAYS in the ring —
  shedding is a per-request spillover signal, not a membership change, so
  one overloaded replica never shifts every key.
- restart window (PR 5: the engine's crash-recovery backoff, gossiped as
  ``restarting``) or ``DOWN`` or gossip silence past ``ttl_s`` → dropped
  from the ring; its keys move to ring successors.
- ``draining`` (scale-in: fleet/autoscaler.py flipped the replica's engine
  into its drain state) → dropped from BOTH rings: unlike a restart
  window the member is leaving on purpose, so every class's keys migrate
  to ring successors immediately and nothing sheds. A drain abort (the
  autoscaler re-admitting after a failed scale-in) gossips ``UP`` with
  ``draining`` clear and re-enters through the normal jittered admission
  — no epoch gate, because the replica's device state was never torn
  down.
- re-admission: after the replica gossips ``UP`` again — and, when the
  drop was a restart window, at a STRICTLY BUMPED epoch (the engine's
  restart/fleet-epoch counter; a replica whose device state was rebuilt
  must prove it finished the rebuild) — plus a deterministic per-(replica,
  epoch) anti-stampede jitter, so several replicas restarting near each
  other re-shift the ring at different instants instead of as one step.

Thread-safety: ``observe``/``sweep``/readers all take one lock; callers are
the router's gossip thread and its request handlers.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from gofr_tpu.router.ring import HashRing, hash_point


@dataclass
class Replica:
    name: str
    url: str = ""
    status: str = "UNKNOWN"        # UP | DEGRADED | DOWN | STALE | UNKNOWN
    epoch: int = 0
    shedding: bool = False
    restarting: bool = False
    draining: bool = False         # scale-in drain in progress (autoscaler)
    role: str = "both"             # ENGINE_ROLE (disaggregated serving)
    handoff_addr: str = ""         # decode-role KV handoff listener (host:port)
    retry_after: float = 0.0       # replica-suggested backoff hint (s)
    static: bool = False           # seeded by config, exempt from gossip TTL
    last_seen: float = 0.0
    in_ring: bool = False
    drop_reason: str = ""          # restart | down | stale | draining ('' = never dropped)
    healthy_epoch: int = -1        # last epoch gossiped while UP and in the ring
    drop_epoch: int = -1           # healthy_epoch at drop time (epoch-gate base)
    drop_at: float = 0.0
    readmit_at: float = 0.0
    # last metrics/SLO digest gossiped by the replica (federation.digest
    # schema); kept across digest-less publishes so a throttled
    # ROUTER_GOSSIP_DIGEST_EVERY still leaves the fleet views populated
    digest: dict[str, Any] | None = field(default=None, repr=False)
    # last KV-handoff transfer counters (role-split members only; engine
    # handoff_stats schema) — surfaced in the router's /debug/fleet view
    handoff: dict[str, Any] | None = field(default=None, repr=False)

    def to_dict(self) -> dict[str, Any]:
        out = {
            "name": self.name, "url": self.url, "status": self.status,
            "epoch": self.epoch, "shedding": self.shedding,
            "restarting": self.restarting, "draining": self.draining,
            "in_ring": self.in_ring,
            "drop_reason": self.drop_reason or None,
        }
        if self.role != "both":  # role-split member (disaggregated serving)
            out["role"] = self.role
            if self.handoff_addr:
                out["handoff_addr"] = self.handoff_addr
        return out


class ReplicaRegistry:
    def __init__(self, ring: HashRing, metrics=None, logger=None, *,
                 ttl_s: float = 3.0, jitter_s: float = 2.0,
                 now: Callable[[], float] = time.monotonic):
        self.ring = ring
        # the FULL ring also holds restart-window members: a key whose home
        # is mid-restart still BELONGS to that home (low classes shed, high
        # classes spill) — only a down/stale/graceful-DOWN replica gives its
        # keys up for every class (Router.plan reads both rings)
        self.full = HashRing(ring.vnodes)
        self.metrics = metrics
        self.logger = logger
        self.ttl_s = float(ttl_s)
        self.jitter_s = float(jitter_s)
        self._now = now
        self._replicas: dict[str, Replica] = {}
        self._lock = threading.Lock()

    # -- feeds -----------------------------------------------------------------

    def add_static(self, name: str, url: str) -> None:
        """Seed a replica from config (``ROUTER_REPLICAS``): in the ring
        immediately, never TTL-expired — gossip, when it arrives, still
        moves it through the normal state machine (a static replica's
        restart window drops it like any other)."""
        with self._lock:
            r = self._replicas.setdefault(name, Replica(name))
            r.url = url or r.url
            r.static = True
            r.status = "UP"
            r.last_seen = self._now()
            if not r.in_ring:
                self._admit(r)
            self._gauges()

    def observe(self, msg: dict) -> None:
        """Apply one gossip message (see GossipReporter.snapshot for the
        schema). Malformed fields degrade to safe defaults rather than
        poisoning the registry."""
        name = str(msg.get("replica") or "")
        if not name:
            return
        with self._lock:
            r = self._replicas.setdefault(name, Replica(name))
            r.url = str(msg.get("url") or r.url)
            r.status = str(msg.get("status") or "UP").upper()
            try:
                # assigned, not max()ed: a fully-replaced process (Supervisor
                # respawn without FLEET_EPOCH) legitimately restarts its
                # epoch count, and per-publisher broker ordering already
                # rules out stale reorderings
                r.epoch = int(msg.get("epoch") or 0)
            except (TypeError, ValueError):
                pass
            r.shedding = bool(msg.get("shedding"))
            r.restarting = bool(msg.get("restarting"))
            r.draining = bool(msg.get("draining"))
            r.role = str(msg.get("role") or "both")
            r.handoff_addr = str(msg.get("handoff_addr") or "")
            try:
                r.retry_after = float(msg.get("retry_after") or 0.0)
            except (TypeError, ValueError):
                r.retry_after = 0.0
            dig = msg.get("digest")
            if isinstance(dig, dict):
                r.digest = dig
            ho = msg.get("handoff")
            if isinstance(ho, dict):
                r.handoff = ho
            r.last_seen = self._now()
            if r.in_ring and r.status == "UP" and not r.restarting and not r.draining:
                # the epoch-gate base: the engine bumps its restart counter
                # BEFORE its window opens, so the drop-triggering gossip
                # already carries the post-rebuild epoch — only an epoch
                # seen while healthy proves nothing was mid-rebuild
                r.healthy_epoch = r.epoch
            self._apply(r)
            self._gauges()

    def sweep(self) -> None:
        """Time-driven transitions: TTL-expire silent replicas, finish
        jitter-delayed re-admissions. Called on every routing decision and
        every gossip message — cheap (one pass over a handful of records)."""
        with self._lock:
            now = self._now()
            for r in self._replicas.values():
                stale = (not r.static and self.ttl_s > 0
                         and now - r.last_seen > self.ttl_s)
                if r.in_ring and stale:
                    r.status = "STALE"
                    self._drop(r, "stale")
                elif not r.in_ring and stale and r.status != "STALE":
                    # a restart-window member that went silent: it no longer
                    # owns its keys for ANY class
                    r.status = "STALE"
                    r.drop_reason = "stale"
                    self.full.remove(r.name)
                else:
                    self._apply(r)
            self._gauges()

    # -- state machine ---------------------------------------------------------

    def _apply(self, r: Replica) -> None:
        healthy = r.status == "UP" and not r.restarting and not r.draining
        if r.in_ring:
            # DOWN outranks restarting: a terminal DOWN gossiped while an
            # engine is mid-restart-window (graceful stop during a crash
            # recovery) must give the keys up NOW, not look transient
            if r.status in ("DOWN", "STALE"):
                self._drop(r, "down")
            elif r.draining:
                # scale-in: out of BOTH rings (reason != "restart" removes
                # full-ring membership in _drop) — every class's keys move
                # to successors, nothing sheds against a leaving member
                self._drop(r, "draining")
            elif r.restarting:
                self._drop(r, "restart")
        elif healthy and self._readmittable(r):
            self._admit(r)
        elif r.status == "DOWN" and r.drop_reason == "restart":
            # a restart window that ended in persistent DOWN (engine out of
            # restart budget, app alive and still gossiping): the member
            # gives up its keys after all — otherwise non-spillable classes
            # homed on it would shed 503 forever
            self.full.remove(r.name)
            r.drop_reason = "down"

    def _readmittable(self, r: Replica) -> bool:
        if r.drop_reason == "restart" and r.epoch <= r.drop_epoch:
            # the restart window ends with an epoch bump (engine restart
            # counter / fleet epoch); an UP at the old epoch is the dying
            # gossip tick racing the drop, not a completed rebuild. Escape
            # hatch: a replica steadily UP well past the gossip TTL is
            # demonstrably serving (e.g. a replaced process whose epoch
            # count restarted) — re-admit it rather than strand it.
            if self._now() - r.drop_at < max(self.ttl_s, 3 * self.jitter_s):
                return False
        return self._now() >= r.readmit_at

    def _drop(self, r: Replica, reason: str) -> None:
        if r.in_ring:
            self.ring.remove(r.name)
            r.in_ring = False
        if reason != "restart":
            # restart windows are transient: the member keeps its keys (low
            # classes shed, high spill); down/stale gives them up entirely
            self.full.remove(r.name)
        r.drop_epoch = r.healthy_epoch
        r.drop_at = self._now()
        r.drop_reason = reason
        r.readmit_at = self._now() + self._jitter(r)
        if self.logger is not None:
            self.logger.warnf("router: replica %s left the ring (%s, epoch %d)",
                              r.name, reason, r.epoch)

    def _jitter(self, r: Replica) -> float:
        """Deterministic per-(replica, drop epoch) fraction of ``jitter_s``:
        replicas desynchronize their ring re-entry with no coordination, and
        a test with ``jitter_s=0`` is exact."""
        if self.jitter_s <= 0:
            return 0.0
        return (hash_point(f"{r.name}:{r.drop_epoch}".encode()) % 1000) / 1000.0 * self.jitter_s

    def _admit(self, r: Replica) -> None:
        self.ring.add(r.name)
        self.full.add(r.name)
        r.in_ring = True
        r.drop_reason = ""
        r.healthy_epoch = r.epoch
        if self.logger is not None:
            self.logger.infof("router: replica %s joined the ring (epoch %d)",
                              r.name, r.epoch)

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge("app_router_ring_size", len(self.ring))
            self.metrics.set_gauge("app_router_replicas_known", len(self._replicas))

    # -- readers ---------------------------------------------------------------

    def get(self, name: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(name)

    def replicas(self) -> dict[str, Replica]:
        with self._lock:
            return dict(self._replicas)

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return [r.to_dict() for _, r in sorted(self._replicas.items())]
