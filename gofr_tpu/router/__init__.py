"""Prefix-affinity data-plane router: one endpoint in front of N replicas.

ROADMAP O2's "millions of users" tier. Each replica is a full App+engine
process; the router is a thin front-end App whose handlers proxy
admissions to replicas chosen by a consistent hash over the request's
prompt-prefix CHAIN KEY — computed router-side with the exact
page-granular token-bytes hashing the replica's prefix cache uses
(``tpu.prefix.chain_key``; stable blake2b, so router and replicas agree
across processes). Repeat tenants therefore land on the replica that
already holds their cached prefix pages (PR 4's hierarchical cache), and
a replica's warm state compounds instead of being sprayed away.

Pieces:

- :mod:`gofr_tpu.router.ring` — the consistent-hash ring (vnode points;
  removal moves only the leaving replica's keys);
- :mod:`gofr_tpu.router.registry` — replica records + the ring-membership
  state machine, fed by health/epoch gossip;
- :mod:`gofr_tpu.router.gossip` — the replica-side reporter
  (``app.enable_router_gossip()``) publishing over the pubsub backbone;
- this module — ``RouterPolicy`` (ROUTER_* config) and ``Router``: the
  routing decision (``plan``) plus the HTTP data plane (``handle``/
  ``bind``): header-preserving proxying, SSE streaming passthrough via
  ``service.HTTPService(stream=True)`` → ``RawStreamingResponse``,
  traceparent forwarding so the replica span parents under the router
  span, ``app_router_*`` metrics and the ``/debug/router`` flight view.

QoS-aware spillover (docs/routing.md): when a request's HOME replica is
shedding (QoS 429/503 from PR 1) or inside its restart window (PR 5),
classes in ``ROUTER_SPILL_CLASSES`` spill to the next replicas in ring
order; lower classes are shed AT the router with 503 + Retry-After —
the home replica's own Retry-After hint when it answered, the gossiped
hint otherwise — so overload semantics survive the extra hop end to end.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from gofr_tpu import deadline as _deadline
from gofr_tpu.http.errors import DeadlineExceeded, ServiceUnavailable
from gofr_tpu.http.responses import Passthrough, Raw
from gofr_tpu.http.streaming import RawStreamingResponse
from gofr_tpu.qos import QoSPolicy
from gofr_tpu.router.gossip import DEFAULT_TOPIC, GossipReporter
from gofr_tpu.router.registry import Replica, ReplicaRegistry
from gofr_tpu.router.ring import HashRing, hash_point
from gofr_tpu.service import ServiceError
from gofr_tpu.service.budget import RetryBudget
from gofr_tpu.tpu import prefix

__all__ = ["GossipReporter", "HashRing", "Replica", "ReplicaRegistry",
           "Router", "RouterPolicy"]

# hop-by-hop / transport-owned headers, never proxied in either direction
_HOP_HEADERS = {"host", "content-length", "connection", "keep-alive",
                "transfer-encoding", "upgrade", "accept-encoding",
                "content-encoding", "te", "trailer", "proxy-connection",
                "date", "server"}


@dataclass
class RouterPolicy:
    """Declarative router policy (config keys in docs/configs.md)."""

    page_size: int = 128                 # ROUTER_PAGE_SIZE — MUST match the replicas'
    key_pages: int = 1                   # ROUTER_KEY_PAGES (shard-key chain depth)
    key_field: str = "prompt"            # ROUTER_KEY_FIELD (JSON body field)
    vnodes: int = 64                     # ROUTER_VNODES
    mode: str = "affinity"               # ROUTER_MODE: affinity | random (A/B arm)
    spill_classes: tuple = ("interactive", "default")  # ROUTER_SPILL_CLASSES
    max_spill: int = 1                   # ROUTER_MAX_SPILL (ring successors tried)
    retry_after_s: float = 1.0           # ROUTER_RETRY_AFTER_S (shed hint fallback)
    ttl_s: float = 3.0                   # ROUTER_TTL_S (gossip silence → out of ring)
    jitter_s: float = 2.0                # ROUTER_REJOIN_JITTER_S (anti-stampede)
    proxy_timeout_s: float = 120.0       # ROUTER_PROXY_TIMEOUT_S
    topic: str = DEFAULT_TOPIC           # ROUTER_GOSSIP_TOPIC
    group: str = ""                      # ROUTER_GOSSIP_GROUP ('' = unique per router)
    replicas: dict[str, str] = field(default_factory=dict)  # ROUTER_REPLICAS static seed
    seed: int = 0                        # ROUTER_SEED (random-mode determinism)
    # request-lifetime plane (docs/resilience.md)
    hedge_after_ms: float = 0.0          # ROUTER_HEDGE_AFTER_MS (0 = hedging off)
    hop_margin_ms: float = 50.0          # DEADLINE_HOP_MARGIN_MS (per-hop shrink)

    @classmethod
    def from_config(cls, config, **overrides: Any) -> "RouterPolicy":
        kw: dict[str, Any] = {
            "page_size": config.get_int("ROUTER_PAGE_SIZE", 128),
            "key_pages": max(1, config.get_int("ROUTER_KEY_PAGES", 1)),
            "key_field": config.get_or_default("ROUTER_KEY_FIELD", "prompt"),
            "vnodes": config.get_int("ROUTER_VNODES", 64),
            "mode": config.get_or_default("ROUTER_MODE", "affinity"),
            "max_spill": config.get_int("ROUTER_MAX_SPILL", 1),
            "retry_after_s": config.get_float("ROUTER_RETRY_AFTER_S", 1.0),
            "ttl_s": config.get_float("ROUTER_TTL_S", 3.0),
            "jitter_s": config.get_float("ROUTER_REJOIN_JITTER_S", 2.0),
            "proxy_timeout_s": config.get_float("ROUTER_PROXY_TIMEOUT_S", 120.0),
            "topic": config.get_or_default("ROUTER_GOSSIP_TOPIC", DEFAULT_TOPIC),
            "group": config.get_or_default("ROUTER_GOSSIP_GROUP", ""),
            "seed": config.get_int("ROUTER_SEED", 0),
            "hedge_after_ms": config.get_float("ROUTER_HEDGE_AFTER_MS", 0.0),
            "hop_margin_ms": config.get_float("DEADLINE_HOP_MARGIN_MS", 50.0),
        }
        spill = config.get_or_default("ROUTER_SPILL_CLASSES", "interactive,default")
        kw["spill_classes"] = tuple(s.strip() for s in spill.split(",") if s.strip())
        reps = config.get_or_default("ROUTER_REPLICAS", "")
        if reps:
            # "name=http://host:port,name2=..." — static seed for ringless
            # bring-up; gossip refines health once it flows
            kw["replicas"] = dict(part.split("=", 1) for part in reps.split(",") if "=" in part)
        kw.update(overrides)
        if kw["mode"] not in ("affinity", "random"):
            raise ValueError(f"ROUTER_MODE {kw['mode']!r}: use 'affinity' or 'random'")
        return cls(**kw)


@dataclass
class RoutePlan:
    """One admission's routing decision (pure — no I/O): the replicas to
    try in order, or the router-side shed verdict."""

    key: int
    qos_class: str
    spillable: bool
    home: str | None                    # affinity home (full ring), if any
    targets: list[Replica]              # try order; empty iff shed is set
    shed: tuple[str, float] | None = None  # (reason, retry_after_s)
    spill_reason: str | None = None     # why the home was excluded upfront


class Router:
    """The front-end tier: decision plane + HTTP data plane. Create one per
    router process, ``bind()`` it to an App (or call ``handle`` from your
    own routes), and point replicas' ``enable_router_gossip()`` at the same
    pubsub backbone."""

    def __init__(self, container, policy: RouterPolicy | None = None,
                 qos_policy: QoSPolicy | None = None, **overrides: Any):
        self.container = container
        self.policy = policy if policy is not None else RouterPolicy.from_config(
            container.config, **overrides)
        self.qos_policy = qos_policy or QoSPolicy.from_config(container.config)
        self.ring = HashRing(self.policy.vnodes)
        self.registry = ReplicaRegistry(
            self.ring, metrics=container.metrics, logger=container.logger,
            ttl_s=self.policy.ttl_s, jitter_s=self.policy.jitter_s)
        for name, url in self.policy.replicas.items():
            self.registry.add_static(name, url)
        self._rng = random.Random(self.policy.seed)
        # shared retry budget (service/budget.py): spills and hedges both
        # spend from it, so a fleet-wide 5xx blip decays instead of the
        # router amplifying it with one extra attempt per request
        self.budget = RetryBudget.from_config(container.config,
                                              metrics=container.metrics)
        self._clients: dict[str, Any] = {}
        self._retired: list[Any] = []  # displaced clients, closed at stop()
        self._lock = threading.Lock()
        self._decisions: deque = deque(maxlen=256)
        self._stats = {"requests": 0, "home": 0, "spill": 0, "shed": 0}
        # per-replica decision counts for /debug/fleet + the federated
        # app_router_decisions_total metric (ISSUE 9: the affinity ratio
        # used to live only in the /debug/router JSON view)
        self._per_replica: dict[str, dict[str, int]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- keys ------------------------------------------------------------------

    def shard_key(self, tokens) -> int:
        """Stable shard key of a token prompt: the chain key of its
        ``key_pages``-th full page (the same value the replica's prefix
        cache stores for that node — tpu/prefix.py), falling back to a
        digest of the raw token bytes for sub-page prompts."""
        # truncate BEFORE hashing: only the first key_pages pages feed the
        # shard key, and this runs per request on the proxy's hot path —
        # digesting a long prompt's remaining pages would be pure waste
        arr = np.asarray(tokens)[: self.policy.key_pages * self.policy.page_size]
        keys = prefix.chain_keys(arr, self.policy.page_size)
        if keys:
            return keys[-1]
        return prefix.chain_key(
            prefix._ROOT, np.ascontiguousarray(arr, dtype=np.int32).tobytes())

    def request_key(self, req) -> int:
        """Shard key of an HTTP request: token-prefix chain key when the
        JSON body carries ``key_field`` (ids or text), else a digest of the
        raw body — unkeyable requests still distribute uniformly. Requests
        naming an adapter (``adapter_id`` body field or ``X-Adapter-ID``
        header) mix it into the key, so ring affinity is effectively on
        (prefix, adapter): one adapter's traffic converges on replicas
        whose device pool already holds its weights — the adapter-cache
        analog of the prefix-affinity argument above."""
        body = getattr(req, "body", b"") or b""
        try:
            data = json.loads(body) if body else None
        except (ValueError, UnicodeDecodeError):
            data = None
        adapter = data.get("adapter_id") if isinstance(data, dict) else None
        if not adapter:
            for k, v in (getattr(req, "headers", None) or {}).items():
                if k.lower() == "x-adapter-id":
                    adapter = v
                    break
        mix = (hash_point(f"adapter:{adapter}".encode())
               if isinstance(adapter, str) and adapter else 0)
        val = data.get(self.policy.key_field) if isinstance(data, dict) else None
        if isinstance(val, str) and val:
            # bounded text prefix (≈4 chars/token), mirroring the token
            # path's key_pages truncation: prompts sharing a long preamble
            # but differing tails must still share a shard key
            return mix ^ hash_point(
                val[: self.policy.key_pages * self.policy.page_size * 4].encode())
        if isinstance(val, (list, tuple)) and val:
            try:
                return mix ^ self.shard_key(val)
            except (ValueError, TypeError, OverflowError):
                pass
        return mix ^ hash_point(body or getattr(req, "path", "/").encode())

    # -- decision plane --------------------------------------------------------

    def plan(self, key: int, cls_name: str | None = None,
             stage: str = "any") -> RoutePlan:
        qos_class = self.qos_policy.resolve(cls_name).name
        spillable = qos_class in self.policy.spill_classes
        self.registry.sweep()
        # affinity home comes from the FULL ring (live + restart-window
        # members): a key whose home is mid-restart must shed its low
        # classes rather than silently pile onto the successor
        full = self.registry.full.lookup(key, 1)
        home = full[0] if full else None
        if self.policy.mode == "random":
            live = self.ring.members()
            if live:
                live = self._rng.sample(live, len(live))
        else:
            # hot path: only home + spill candidates are ever used — the
            # +2 slack absorbs shedding-filtered candidates without paying
            # a full vnode walk under the ring lock per admission
            live = self.ring.lookup(key, n=1 + self.policy.max_spill + 2)
        # role-aware planning (disaggregated serving, docs/routing.md):
        # when the fleet is role-split, a stage-specific plan only
        # considers replicas whose ENGINE_ROLE serves the stage —
        # admissions land on the prefill pool, token streams on the
        # decode pool. With no eligible member the filter stands down
        # (colocated fallback) rather than shed a servable request.
        if stage != "any" and self._role_split():
            eligible = [n for n in live
                        if self._stage_ok(self.registry.get(n), stage)]
            if eligible:
                live = eligible
                if home is None or not self._stage_ok(
                        self.registry.get(home), stage):
                    # the stage pool's first ring candidate is the
                    # effective home: deterministic per key, so affinity
                    # inside the pool still compounds warm state
                    home = eligible[0]
        home_r = self.registry.get(home) if home else None
        home_live = home_r is not None and home_r.in_ring and not home_r.shedding
        if home_live and self.policy.mode == "affinity":
            targets = [home_r]
            if spillable:
                spares = [self.registry.get(n) for n in live if n != home]
                targets += [r for r in spares
                            if r is not None and not r.shedding][: self.policy.max_spill]
            return RoutePlan(key, qos_class, spillable, home, targets)
        if self.policy.mode == "random":
            targets = [self.registry.get(n) for n in live[: 1 + self.policy.max_spill]]
            targets = [r for r in targets if r is not None]
            if targets:
                return RoutePlan(key, qos_class, spillable, home, targets)
        else:
            # home shedding / restarting / absent
            if spillable:
                spares = [self.registry.get(n) for n in live if n != home]
                targets = [r for r in spares if r is not None and not r.shedding]
                if not targets:  # everyone advisory-shedding: their own QoS decides
                    targets = [r for r in spares if r is not None]
                if not targets and home_r is not None and home_r.in_ring:
                    targets = [home_r]  # home shedding but alive: let it answer
                if targets:
                    return RoutePlan(key, qos_class, spillable, home,
                                     targets[: self.policy.max_spill + 1],
                                     spill_reason=self._home_reason(home_r))
        # nothing to try — or a LOW class whose home is shedding/restarting:
        # shed AT the router (tentpole policy), with the home's gossiped
        # Retry-After hint riding out so backpressure survives the hop
        reason = self._home_reason(home_r) or "no_replicas"
        retry_after = self.policy.retry_after_s
        if home_r is not None:
            retry_after = home_r.retry_after or retry_after
        return RoutePlan(key, qos_class, spillable, home, [],
                         shed=(reason, retry_after))

    def _role_split(self) -> bool:
        """Is any known replica running a split ENGINE_ROLE? Colocated
        fleets answer False, keeping plan() byte-identical to pre-role."""
        return any(getattr(r, "role", "both") not in ("", "both")
                   for r in self.registry.replicas().values())

    @staticmethod
    def _stage_ok(r: Replica | None, stage: str) -> bool:
        """Does the replica's role serve the stage? ``both`` serves all."""
        if r is None:
            return False
        role = getattr(r, "role", "both") or "both"
        return role == "both" or role == stage

    @staticmethod
    def _home_reason(home_r: Replica | None) -> str | None:
        """Why a request could not go to its home replica (None = it can)."""
        if home_r is None:
            return None
        if home_r.restarting or home_r.drop_reason == "restart":
            return "restart"
        if home_r.draining or home_r.drop_reason == "draining":
            # scale-in: normally invisible here (a draining member left the
            # full ring, so the successor already IS the home); this only
            # names the race where a drain gossip lands mid-plan
            return "draining"
        if home_r.shedding:
            return "shedding"
        if not home_r.in_ring:
            return "down"
        return None

    # -- data plane ------------------------------------------------------------

    def handle(self, ctx):
        """Proxy one admission (register as an App handler via ``bind``).
        Raises typed HTTP errors for router-side sheds; returns
        ``Passthrough`` (buffered) or ``RawStreamingResponse`` (SSE) for
        replica answers — headers, Retry-After included, preserved."""
        req = ctx.request
        cls_name = ctx.header(self.qos_policy.class_header)
        key = self.request_key(req)
        # stage from the route shape (disaggregated serving): SSE streams
        # read tokens off the decode pool, buffered admissions land on the
        # prefill pool (whose handoff ships the KV to decode). Colocated
        # fleets ignore the stage entirely (_role_split is False).
        path_only = (req.path or b"/")
        if isinstance(path_only, bytes):
            path_only = path_only.decode("utf-8", "replace")
        stage = "decode" if path_only.rstrip("/").endswith("/stream") else "prefill"
        p = self.plan(key, cls_name, stage=stage)
        m = self.container.metrics
        m.increment_counter("app_router_requests_total", 1, qos_class=p.qos_class)
        self.budget.note_request()  # originals fund the retry/hedge budget
        with self._lock:
            self._stats["requests"] += 1
        # request-lifetime plane: a request whose propagated deadline is
        # already spent is shed HERE — proxying it would only make a
        # replica compute an answer nobody can receive
        req_ctx = req.context() if hasattr(req, "context") else {}
        dl = _deadline.deadline_of(req_ctx)
        if dl is not None and dl - time.monotonic() <= 0:
            m.increment_counter("app_request_deadline_exceeded_total", 1,
                                where="router")
            self._record(p, sent=None, outcome="shed:deadline_exceeded")
            with self._lock:
                self._stats["shed"] += 1
            raise DeadlineExceeded("request deadline expired at the router")
        if p.shed is not None:
            reason, retry_after = p.shed
            m.increment_counter("app_router_shed_total", 1,
                                qos_class=p.qos_class, reason=reason)
            self._record(p, sent=None, outcome=f"shed:{reason}")
            with self._lock:
                self._stats["shed"] += 1
            raise ServiceUnavailable(
                f"home replica unavailable ({reason}); retry later",
                retry_after=retry_after)
        headers = self._forward_headers(req, ctx.span, deadline_at=dl)
        path = req.path + (f"?{req.query_string}" if getattr(req, "query_string", "") else "")
        if (self.policy.hedge_after_ms > 0 and p.spillable
                and len(p.targets) >= 2):
            return self._handle_hedged(p, req, path, headers)
        last_error: Exception | None = None
        moved_reason: str | None = None  # why the HOME was abandoned mid-loop
        budget_spent = False  # ran out of retry budget mid-spill
        for i, rep in enumerate(p.targets):
            client = self._client(rep)
            try:
                resp = client.request(req.method, path, body=req.body or None,
                                      headers=headers, stream=True)
            except ServiceError as e:
                last_error = e
                if rep.name == p.home:
                    moved_reason = "error"
                if i + 1 < len(p.targets) and not self.budget.try_spend():
                    # a spill is a retry: without budget, fail fast instead
                    # of feeding the storm one extra attempt per request
                    budget_spent = True
                    break
                continue
            if resp.status_code == 429 or resp.status_code >= 500:
                if i + 1 < len(p.targets):
                    if not self.budget.try_spend():
                        # budget exhausted: the replica's own 429/5xx
                        # (Retry-After intact) passes through unspilled
                        return self._finish(p, rep, resp, moved_reason)
                    # replica-side overload/failure: spill to the next ring
                    # replica (spillable classes have successors planned)
                    resp.close()
                    if rep.name == p.home:
                        moved_reason = "busy"
                    continue
                # terminal target: the replica's own 429/503 (Retry-After
                # intact) or 5xx passes through — never remapped
            return self._finish(p, rep, resp, moved_reason)
        reason = "retry_budget" if budget_spent else "error"
        self._record(p, sent=None,
                     outcome="shed:retry_budget" if budget_spent else "error")
        with self._lock:
            self._stats["shed"] += 1
        m.increment_counter("app_router_shed_total", 1,
                            qos_class=p.qos_class, reason=reason)
        raise ServiceUnavailable(
            f"no replica accepted the request ({last_error})",
            retry_after=self.policy.retry_after_s)

    def _handle_hedged(self, p: RoutePlan, req, path, headers):
        """Hedged dispatch for spillable classes (ROUTER_HEDGE_AFTER_MS):
        fire the home replica; when it stays silent past the hedge window
        — or answers 429/5xx — fire the ring successor, budget allowing.
        First good responder wins; the loser's response is closed as it
        arrives, which aborts its upstream transfer so the replica's
        disconnect path cancels the generation and frees the slot/pages
        (cooperative cancellation, docs/resilience.md)."""
        import queue as _q

        m = self.container.metrics
        results: _q.Queue = _q.Queue()

        def fire(idx: int, rep: Replica) -> None:
            try:
                resp = self._client(rep).request(
                    req.method, path, body=req.body or None,
                    headers=headers, stream=True)
            except Exception as e:  # noqa: BLE001 - reported via the queue
                results.put((idx, rep, None, e))
            else:
                results.put((idx, rep, resp, None))

        def spawn(idx: int) -> None:
            threading.Thread(target=fire, args=(idx, p.targets[idx]),
                             daemon=True, name="gofr-router-hedge").start()

        spawn(0)
        outstanding, next_idx = 1, 1
        hedged = False          # did a hedge/spill actually fire?
        budget_denied = False
        hedge_wait = self.policy.hedge_after_ms / 1000.0
        last_error: Exception | None = None
        winner = None
        while outstanding:
            can_fire = next_idx < len(p.targets) and not budget_denied
            try:
                # only the FIRST silent window triggers a hedge; once all
                # candidates are in flight we wait for whoever answers
                wait = hedge_wait if (can_fire and not hedged) else None
                idx, rep, resp, err = results.get(timeout=wait)
            except _q.Empty:
                if self.budget.try_spend():
                    spawn(next_idx)
                    next_idx += 1
                    outstanding += 1
                    hedged = True
                else:
                    budget_denied = True
                continue
            outstanding -= 1
            if err is not None or resp.status_code == 429 or resp.status_code >= 500:
                last_error = err if err is not None else ServiceError(
                    f"server error {resp.status_code}")
                if resp is not None:
                    resp.close()
                # a failed candidate is also a reason to try the successor
                if can_fire and self.budget.try_spend():
                    spawn(next_idx)
                    next_idx += 1
                    outstanding += 1
                    hedged = True
                continue
            winner = (idx, rep, resp)
            break
        if hedged:
            m.increment_counter(
                "app_router_hedged_total", 1,
                winner=("none" if winner is None
                        else "primary" if winner[0] == 0 else "hedge"))
        if winner is None:
            self._record(p, sent=None, outcome="error")
            with self._lock:
                self._stats["shed"] += 1
            m.increment_counter("app_router_shed_total", 1,
                                qos_class=p.qos_class, reason="error")
            raise ServiceUnavailable(
                f"no replica accepted the request ({last_error})",
                retry_after=self.policy.retry_after_s)
        if outstanding:
            # the loser is cancelled the moment it answers: close() aborts
            # the upstream transfer mid-stream, so the losing replica's
            # client-disconnect path reclaims its slot and pages
            def drain(n: int) -> None:
                for _ in range(n):
                    _i, _rep, lresp, _e = results.get()
                    if lresp is not None:
                        lresp.close()

            threading.Thread(target=drain, args=(outstanding,), daemon=True,
                             name="gofr-router-hedge-drain").start()
        idx, rep, resp = winner
        moved = "hedge" if (hedged and idx > 0) else None
        return self._finish(p, rep, resp, moved)

    def _finish(self, p: RoutePlan, rep: Replica, resp, moved_reason: str | None = None):
        m = self.container.metrics
        affinity = "home" if rep.name == p.home else "spill"
        m.increment_counter("app_router_routed_total", 1,
                            replica=rep.name, affinity=affinity)
        if affinity == "spill" and self.policy.mode == "affinity" and p.home:
            # counted ONCE, at the landing: the replica label is the home
            # the request left, the reason why it left (plan-time exclusion
            # or the home's in-band 429/5xx/transport answer)
            m.increment_counter(
                "app_router_spilled_total", 1, replica=p.home,
                reason=p.spill_reason or moved_reason or "out_of_ring")
        with self._lock:
            self._stats["home" if affinity == "home" else "spill"] += 1
        self._record(p, sent=rep.name, outcome=str(resp.status_code))
        ctype = resp.headers.get("content-type", "application/octet-stream")
        # the replica's Content-Type rides in the headers VERBATIM — its
        # parameters (charset, multipart boundary) must survive the hop;
        # the bare type below is only for the SSE/buffered routing decision
        out_headers = {k: v for k, v in resp.headers.items()
                       if k.lower() not in _HOP_HEADERS}
        bare_type = ctype.split(";")[0].strip()
        if bare_type == "text/event-stream":
            # streaming passthrough: upstream SSE bytes flow through as
            # produced; a client disconnect closes the upstream transfer
            return RawStreamingResponse(
                resp.iter_content(), status=resp.status_code,
                headers=out_headers, content_type=bare_type, close=resp.close)
        return Passthrough(resp.read(), status_code=resp.status_code,
                           content_type=bare_type, headers=out_headers)

    def _forward_headers(self, req, span, deadline_at: float | None = None) -> dict[str, str]:
        headers = {k: v for k, v in (getattr(req, "headers", None) or {}).items()
                   if k.lower() not in _HOP_HEADERS}
        if deadline_at is not None:
            # re-stamp the absolute deadline SHRUNK by the hop margin: the
            # replica must answer early enough for this proxy to still
            # relay the response inside the client's budget
            for k in [k for k in headers
                      if k.lower() == _deadline.DEADLINE_HEADER.lower()]:
                headers.pop(k)
            headers[_deadline.DEADLINE_HEADER] = _deadline.header_value(
                deadline_at, self.policy.hop_margin_ms / 1000.0)
        remote = getattr(req, "remote", "")
        if remote:
            # scan case-insensitively: HTTPRequest stores lowercase keys,
            # other callers may not — the chain must merge, not duplicate
            prior = ""
            for k in [k for k in headers if k.lower() == "x-forwarded-for"]:
                prior = headers.pop(k)
            headers["X-Forwarded-For"] = f"{prior}, {remote}".lstrip(", ")
        if span is not None:
            # the replica's server span must parent under THIS hop's span,
            # not the client's original — one trace, correctly nested
            headers["traceparent"] = span.traceparent()
        return headers

    def _client(self, rep: Replica):
        from gofr_tpu.service import HTTPService

        base = (rep.url or "").rstrip("/")
        with self._lock:
            c = self._clients.get(rep.name)
            if c is None or c.base_url != base:
                if c is not None:
                    # NOT closed here: another handler thread may still be
                    # proxying a stream through it — retire it and close at
                    # router stop() instead of aborting in-flight transfers
                    self._retired.append(c)
                c = HTTPService(base, self.container.logger, self.container.metrics,
                                timeout=self.policy.proxy_timeout_s)
                self._clients[rep.name] = c
            return c

    def _record(self, p: RoutePlan, sent: str | None, outcome: str) -> None:
        if outcome.startswith("shed"):
            decision = "shed"
        elif outcome == "error":
            decision = "error"
        else:
            decision = "home" if sent == p.home else "spill"
        with self._lock:  # debug_view iterates this deque under the lock
            self._decisions.append({
                "t": round(time.time(), 3), "key": f"{p.key:016x}",
                "qos_class": p.qos_class, "home": p.home, "sent": sent,
                "outcome": outcome,
            })
            counts = self._per_replica.setdefault(
                sent or p.home or "none",
                {"home": 0, "spill": 0, "shed": 0, "error": 0})
            counts[decision] += 1
            home = self._stats["home"]
            routed = home + self._stats["spill"]
        m = self.container.metrics
        m.increment_counter("app_router_decisions_total", 1,
                            replica=sent or p.home or "none", decision=decision)
        if routed:
            m.set_gauge("app_router_affinity_hit_ratio", home / routed)

    # -- gossip subscription ---------------------------------------------------

    def start(self) -> "Router":
        """Subscribe to replica gossip on the container's pubsub backbone
        (no-op without one — static ROUTER_REPLICAS still route)."""
        if self._thread is not None or self.container.pubsub is None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._gossip_loop, daemon=True,
                                        name="gofr-router-gossip")
        self._thread.start()
        return self

    def _gossip_loop(self) -> None:
        ps = self.container.pubsub
        # unique default group: EVERY router instance sees every gossip
        # message (consumer groups split a topic; health must not be split)
        group = self.policy.group or f"router-{os.getpid()}-{id(self) & 0xFFFF:04x}"
        while not self._stop.is_set():
            try:
                msg = ps.subscribe(self.policy.topic, group=group, timeout=0.5)
            except Exception as e:  # noqa: BLE001 - broker blip; keep the ring serving
                self.container.logger.warnf("router gossip subscribe failed: %r", e)
                self._stop.wait(1.0)
                continue
            if msg is None:
                self.registry.sweep()  # TTL expiry needs no traffic
                # a CLOSED broker returns None immediately instead of
                # blocking out its timeout — without this wait the loop
                # would spin a full core from broker close to stop()
                self._stop.wait(0.05)
                continue
            try:
                data = msg.bind(dict)
                ts = data.get("ts")
                # durable brokers (pubsub/file.py) replay the topic's history
                # to a fresh consumer group: snapshots much older than any
                # liveness window are boot-time replay, not current state —
                # applying them would admit dead URLs until fresh gossip
                # lands. Threshold is generous (3×TTL, ≥30s) so ordinary
                # publisher/router clock skew cannot mute live gossip.
                if (isinstance(ts, (int, float))
                        and time.time() - ts > max(3 * self.policy.ttl_s, 30.0)):
                    msg.commit()
                    continue
                self.registry.observe(data)
            except Exception as e:  # noqa: BLE001 - malformed gossip is dropped
                self.container.logger.warnf("router gossip message ignored: %r", e)
            msg.commit()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=3.0)
        with self._lock:
            clients = list(self._clients.values()) + self._retired
            self._clients, self._retired = {}, []
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass

    # -- app binding / observability -------------------------------------------

    def bind(self, app, routes: list[tuple[str, str]] | None = None) -> "Router":
        """Register the proxy on ``app``: every (method, path) in
        ``routes`` (default: POST /generate and POST /generate/stream)
        proxies through ``handle``; APP_ENV=DEBUG adds the /debug/router
        flight view. Starts the gossip subscription."""
        for method, route_path in routes or (("POST", "/generate"),
                                             ("POST", "/generate/stream")):
            app.add_route(method, route_path, self.handle)
        # fleet-aggregated Prometheus exposition (metrics/federation.py):
        # one scrape answers for the whole fleet — per-replica labels +
        # correctly-merged aggregates (the router app's own registry still
        # serves its local /metrics on METRICS_PORT as usual)
        fleet = lambda _ctx: Passthrough(  # noqa: E731
            self.fleet_metrics_text().encode(),
            status_code=200, content_type="text/plain")
        app.get("/metrics", fleet)
        app.get("/metrics/fleet", fleet)
        if app._debug_env():
            # same envelope as /debug/requests and /debug/engine
            app.get("/debug/router", lambda _ctx: Raw({"data": self.debug_view()}))
            app.get("/debug/fleet", lambda _ctx: Raw({"data": self.fleet_view()}))
        app.on_cleanup(self.stop)  # the gossip thread dies with the app
        return self.start()

    def digests(self) -> dict[str, dict[str, Any]]:
        """Last known metrics/SLO digest per replica (gossip-fed)."""
        return {name: r.digest for name, r in self.registry.replicas().items()
                if isinstance(r.digest, dict)}

    def fleet_metrics_text(self) -> str:
        """Fleet-aggregated Prometheus exposition over the gossiped
        digests: aggregate series without a replica label, per-replica
        series with one; counters summed, histogram buckets merged,
        percentiles never averaged (read them off the merged buckets)."""
        from gofr_tpu.metrics import federation

        self.registry.sweep()
        states = {name: {"status": r.status, "epoch": r.epoch,
                         "role": getattr(r, "role", "both")}
                  for name, r in self.registry.replicas().items()}
        return federation.fleet_text(self.digests(), states)

    def fleet_view(self) -> dict[str, Any]:
        """The /debug/fleet payload: registry state (UP/shedding/restart,
        epoch) joined with each replica's gossiped attainment, burn rate
        and inflight, plus the exact fleet-level per-class SLO roll-up and
        the router's own decision counters — one endpoint answering "is
        the fleet healthy and who is burning budget"."""
        from gofr_tpu.metrics import federation

        self.registry.sweep()
        with self._lock:
            stats = dict(self._stats)
            per_replica = {n: dict(c) for n, c in self._per_replica.items()}
        routed = stats["home"] + stats["spill"]
        stats["affinity_hit_ratio"] = (
            round(stats["home"] / routed, 4) if routed else None)
        digests = {}
        replicas = []
        for name, r in sorted(self.registry.replicas().items()):
            d = r.to_dict()
            if isinstance(r.digest, dict):
                digests[name] = r.digest
                d["inflight"] = r.digest.get("inflight")
                d["slo"] = _slo_brief(r.digest.get("slo"))
                if r.digest.get("perf"):
                    from gofr_tpu.metrics import perf as perf_mod

                    d["perf"] = perf_mod.derive(r.digest["perf"])
                if r.digest.get("knobs"):
                    # who runs which tuning (the online controller's knob
                    # vector per engine): a replica drifting from the
                    # fleet's pins shows up right next to its attainment
                    d["knobs"] = r.digest["knobs"]
            counts = per_replica.get(name)
            if counts:
                sent = counts["home"] + counts["spill"]
                d["decisions"] = counts
                d["affinity_hit_ratio"] = (
                    round(counts["home"] / sent, 4) if sent else None)
            if isinstance(r.handoff, dict):
                # role-split member: KV-handoff transfer counters ride the
                # gossip (disaggregated serving, docs/serving.md)
                d["handoff"] = r.handoff
            replicas.append(d)
        out: dict[str, Any] = {
            "replicas": replicas,
            "classes": federation.aggregate_slo(digests),
            "stats": stats,
        }
        if any(d.get("perf") for d in digests.values()):
            from gofr_tpu.metrics import perf as perf_mod

            totals = federation.aggregate_perf(digests)
            # fleet MFU/MBU recomputed from the summed windows — the same
            # sum-of-parts discipline as the SLO roll-up above
            out["perf"] = {"totals": totals, **perf_mod.derive(totals)}
        return out

    def debug_view(self) -> dict[str, Any]:
        """The /debug/router payload: ring membership, per-replica state,
        decision counters (affinity hit ratio), recent routing decisions."""
        with self._lock:
            stats = dict(self._stats)
            decisions = list(self._decisions)
        routed = stats["home"] + stats["spill"]
        stats["affinity_hit_ratio"] = (
            round(stats["home"] / routed, 4) if routed else None)
        return {
            "mode": self.policy.mode,
            "ring": self.ring.members(),
            "ring_size": len(self.ring),
            "replicas": self.registry.snapshot(),
            "stats": stats,
            "decisions": decisions,
        }


def _slo_brief(snap: dict | None) -> dict[str, Any] | None:
    """Compact per-replica SLO summary for /debug/fleet: fast-window
    attainment/burn + remaining budget per (class, objective) — the full
    windows stay available on the replica's own /metrics."""
    if not isinstance(snap, dict):
        return None
    out: dict[str, Any] = {}
    for cname, objs in snap.items():
        for oname, entry in (objs or {}).items():
            fast = entry.get("fast") or {}
            if fast.get("total"):
                out.setdefault(cname, {})[oname] = {
                    "attainment": fast.get("attainment"),
                    "burn_rate": fast.get("burn_rate"),
                    "budget_remaining": entry.get("budget_remaining"),
                }
    return out or None
