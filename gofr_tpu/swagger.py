"""OpenAPI serving (gofr `pkg/gofr/swagger.go`).

Serves ``./static/openapi.json`` at ``/.well-known/openapi.json`` when present;
otherwise generates a minimal spec from the registered routes.
``/.well-known/swagger`` serves API docs. The reference EMBEDS the Swagger-UI
bundle (`swagger.go:13-14` ``//go:embed static/*``) so docs work air-gapped;
this build ships an in-tree, dependency-free docs UI with the same property —
spec rendering plus try-it-out via ``fetch`` — with zero external assets.
Set ``SWAGGER_UI=cdn`` to serve the full Swagger-UI from unpkg instead
(requires egress).
"""

from __future__ import annotations

import json
import os

from aiohttp import web

_CDN_HTML = """<!DOCTYPE html>
<html>
<head>
  <title>{title} — API docs</title>
  <link rel="stylesheet" href="https://unpkg.com/swagger-ui-dist@5/swagger-ui.css">
</head>
<body>
  <div id="swagger-ui"></div>
  <script src="https://unpkg.com/swagger-ui-dist@5/swagger-ui-bundle.js"></script>
  <script>
    SwaggerUIBundle({{url: "/.well-known/openapi.json", dom_id: "#swagger-ui"}});
  </script>
</body>
</html>"""

# Self-contained docs page: no external JS/CSS, works in air-gapped
# deployments (the property go:embed gives the reference).
_OFFLINE_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>{title} — API docs</title>
<style>
  :root {{ --fg:#1a1a2e; --muted:#667; --line:#e2e4ea; --bg:#fff; --chip:#f2f4f8; }}
  body {{ font: 15px/1.5 system-ui, sans-serif; color: var(--fg); background: var(--bg);
         margin: 0 auto; max-width: 960px; padding: 24px; }}
  h1 {{ font-size: 22px; }} h1 small {{ color: var(--muted); font-weight: 400; }}
  .op {{ border: 1px solid var(--line); border-radius: 8px; margin: 10px 0; }}
  .op > summary {{ cursor: pointer; padding: 10px 14px; display: flex; gap: 12px;
                   align-items: center; list-style: none; }}
  .op > summary::-webkit-details-marker {{ display: none; }}
  .method {{ font: 600 12px/1 monospace; padding: 4px 8px; border-radius: 4px;
             color: #fff; min-width: 52px; text-align: center; }}
  .get {{ background:#2f855a }} .post {{ background:#2b6cb0 }} .put {{ background:#b7791f }}
  .delete {{ background:#c53030 }} .patch {{ background:#6b46c1 }}
  .path {{ font-family: monospace; }}
  .summary {{ color: var(--muted); margin-left: auto; }}
  .body {{ border-top: 1px solid var(--line); padding: 12px 14px; }}
  textarea, input {{ width: 100%; box-sizing: border-box; font-family: monospace;
                     border: 1px solid var(--line); border-radius: 6px; padding: 8px; }}
  button {{ background: var(--fg); color: #fff; border: 0; border-radius: 6px;
            padding: 8px 16px; cursor: pointer; margin-top: 8px; }}
  pre {{ background: var(--chip); border-radius: 6px; padding: 10px; overflow: auto; }}
  .param {{ margin: 6px 0; }} .param label {{ font-family: monospace; font-size: 13px; }}
</style>
</head>
<body>
<h1>{title} <small>API documentation</small></h1>
<p><a href="/.well-known/openapi.json">openapi.json</a></p>
<div id="ops">loading spec…</div>
<script>
(async () => {{
  const spec = await (await fetch("/.well-known/openapi.json")).json();
  const root = document.getElementById("ops");
  root.textContent = "";
  for (const [path, methods] of Object.entries(spec.paths || {{}})) {{
    for (const [method, op] of Object.entries(methods)) {{
      const d = document.createElement("details"); d.className = "op";
      const params = (path.match(/\\{{([^}}]+)\\}}/g) || []).map(p => p.slice(1, -1));
      d.innerHTML = `
        <summary><span class="method ${{method}}">${{method.toUpperCase()}}</span>
          <span class="path">${{path}}</span>
          <span class="summary">${{(op.summary || "")}}</span></summary>
        <div class="body">
          ${{params.map(p => `<div class="param"><label>${{p}}</label>
            <input data-param="${{p}}" placeholder="path parameter ${{p}}"></div>`).join("")}}
          ${{method !== "get" ? '<textarea rows="4" placeholder="request body (JSON)"></textarea>' : ""}}
          <button>Send request</button>
          <pre hidden></pre>
        </div>`;
      const out = d.querySelector("pre");
      d.querySelector("button").onclick = async () => {{
        let url = path;
        d.querySelectorAll("input[data-param]").forEach(i =>
          url = url.replace(`{{${{i.dataset.param}}}}`, encodeURIComponent(i.value)));
        const ta = d.querySelector("textarea");
        const init = {{ method: method.toUpperCase(), headers: {{}} }};
        if (ta && ta.value) {{
          init.body = ta.value; init.headers["Content-Type"] = "application/json";
        }}
        out.hidden = false; out.textContent = "…";
        try {{
          const r = await fetch(url, init);
          const text = await r.text();
          let shown = text;
          try {{ shown = JSON.stringify(JSON.parse(text), null, 2); }} catch {{}}
          out.textContent = `HTTP ${{r.status}}\\n` + shown;
        }} catch (e) {{ out.textContent = "request failed: " + e; }}
      }};
      root.appendChild(d);
    }}
  }}
  if (!root.children.length) root.textContent = "no routes registered";
}})();
</script>
</body>
</html>"""


def generate_spec(app) -> dict:
    paths: dict[str, dict] = {}
    for method, path, handler in app._routes:
        openapi_path = path  # aiohttp {param} syntax == OpenAPI syntax
        entry = paths.setdefault(openapi_path, {})
        entry[method.lower()] = {
            "summary": (handler.__doc__ or "").strip().split("\n")[0] or handler.__name__,
            "responses": {"200": {"description": "JSON envelope {\"data\": ...}"}},
        }
    return {
        "openapi": "3.0.0",
        "info": {"title": app.container.app_name, "version": app.container.app_version},
        "paths": paths,
    }


def openapi_handler(app):
    async def handler(_request: web.Request) -> web.Response:
        path = os.path.join("static", "openapi.json")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return web.Response(body=f.read(), content_type="application/json")
        return web.json_response(generate_spec(app))

    return handler


def swagger_ui_handler(app):
    async def handler(_request: web.Request) -> web.Response:
        mode = app.container.config.get_or_default("SWAGGER_UI", "offline")
        template = _CDN_HTML if mode == "cdn" else _OFFLINE_HTML
        html = template.format(title=app.container.app_name)
        return web.Response(text=html, content_type="text/html")

    return handler
