"""OpenAPI serving (gofr `pkg/gofr/swagger.go`).

Serves ``./static/openapi.json`` at ``/.well-known/openapi.json`` when present;
otherwise generates a minimal spec from the registered routes. ``/.well-known/
swagger`` serves a self-contained Swagger-UI page loading assets from a CDN
(the reference embeds the bundle; a CDN reference keeps the repo lean).
"""

from __future__ import annotations

import json
import os

from aiohttp import web

_SWAGGER_HTML = """<!DOCTYPE html>
<html>
<head>
  <title>{title} — API docs</title>
  <link rel="stylesheet" href="https://unpkg.com/swagger-ui-dist@5/swagger-ui.css">
</head>
<body>
  <div id="swagger-ui"></div>
  <script src="https://unpkg.com/swagger-ui-dist@5/swagger-ui-bundle.js"></script>
  <script>
    SwaggerUIBundle({{url: "/.well-known/openapi.json", dom_id: "#swagger-ui"}});
  </script>
</body>
</html>"""


def generate_spec(app) -> dict:
    paths: dict[str, dict] = {}
    for method, path, handler in app._routes:
        openapi_path = path  # aiohttp {param} syntax == OpenAPI syntax
        entry = paths.setdefault(openapi_path, {})
        entry[method.lower()] = {
            "summary": (handler.__doc__ or "").strip().split("\n")[0] or handler.__name__,
            "responses": {"200": {"description": "JSON envelope {\"data\": ...}"}},
        }
    return {
        "openapi": "3.0.0",
        "info": {"title": app.container.app_name, "version": app.container.app_version},
        "paths": paths,
    }


def openapi_handler(app):
    async def handler(_request: web.Request) -> web.Response:
        path = os.path.join("static", "openapi.json")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return web.Response(body=f.read(), content_type="application/json")
        return web.json_response(generate_spec(app))

    return handler


def swagger_ui_handler(app):
    async def handler(_request: web.Request) -> web.Response:
        html = _SWAGGER_HTML.format(title=app.container.app_name)
        return web.Response(text=html, content_type="text/html")

    return handler
