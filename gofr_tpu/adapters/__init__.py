"""Multi-LoRA adapter multiplexing: the host registry + device pool tier.

Thousands of fine-tuned variants of one base model share one engine
(ROADMAP O4): adapter weights live as a refcounted paged side-cache next
to the KV pool, and decode gathers each lane's adapter out of the pool so
ONE device call serves a mixed-adapter batch (ops/lora.py holds the math
and the exactness contract). Two tiers, mirroring the prefix cache's
HBM/host-DRAM split (tpu/prefix.py):

- **Host tier** — :class:`AdapterRegistry`. The source of truth: numpy
  factor matrices in host DRAM, bounded by ``ADAPTER_HOST_MB``. Unlike
  the prefix cache's host tier this one never silently evicts — an
  adapter was *registered*, so dropping it would turn requests into
  errors; registration past the budget raises instead. The registry also
  owns the per-adapter concurrency caps (``max_concurrency`` per spec,
  the per-tenant analog of QoS per-class caps) and each adapter's default
  QoS class, so ``adapter_id`` keys both admission and scheduling.
- **Device tier** — :class:`AdapterPool`. ``S`` fixed-shape pool slots in
  HBM (``ADAPTER_SLOTS`` / ``ADAPTER_POOL_MB``), refcounted by the engine
  slots currently decoding with each adapter, LRU-evicted only at
  ``refs == 0`` — eviction is just forgetting the device copy; the next
  acquire re-uploads from the registry (host-DRAM "swap-in", an async
  ``.at[slot].set`` dispatch that is safe under the engine state lock by
  the ``gather_pages`` discipline: dispatch-only, no readback). Slot 0 is
  the reserved all-zeros BASE adapter — ``adapter_id=None`` lanes select
  it and stay bit-identical to the pre-adapter engine.

The pool's arrays ride every packed program call as *dynamic* jit
arguments (like ``params``), so uploads and evictions never recompile —
the same property the live weight hot-swap path (engine.adopt_weights)
relies on for full-model adoption without a restart.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field

import numpy as np

from gofr_tpu.http.errors import TooManyRequests

__all__ = [
    "AdapterPool",
    "AdapterRegistry",
    "AdapterSpec",
    "random_adapter",
]


@dataclass
class AdapterSpec:
    """One registered LoRA adapter (host-tier record).

    ``a`` is the down-projection ``[embed, rank]``, ``b`` the
    up-projection ``[rank, vocab]`` (lm_head-site LoRA; ops/lora.py).
    ``scale`` is the usual alpha/rank factor. ``qos_class`` (optional)
    is the default QoS class for requests naming this adapter — the
    per-adapter SLO hook: map an adapter to a class and the SLO /
    autoscaler planes key on it. ``max_concurrency`` caps
    submitted-but-unfinished requests for this adapter (0 = uncapped)."""

    name: str
    a: np.ndarray
    b: np.ndarray
    scale: float = 1.0
    qos_class: str | None = None
    max_concurrency: int = 0

    def __post_init__(self):
        self.a = np.asarray(self.a, np.float32)
        self.b = np.asarray(self.b, np.float32)
        if self.a.ndim != 2 or self.b.ndim != 2 or self.a.shape[1] != self.b.shape[0]:
            raise ValueError(
                f"adapter {self.name!r}: a must be [embed, rank] and b "
                f"[rank, vocab] with matching rank; got {self.a.shape} / "
                f"{self.b.shape}")

    @property
    def rank(self) -> int:
        return int(self.a.shape[1])

    @property
    def nbytes(self) -> int:
        return int(self.a.nbytes + self.b.nbytes)


def random_adapter(name: str, embed: int, vocab: int, *, rank: int = 4,
                   scale: float = 1.0, seed: int = 0, **kw) -> AdapterSpec:
    """Deterministic random adapter for tests / examples / benches. Small
    magnitudes (~1e-2) so deltas perturb logits without drowning them."""
    rng = np.random.default_rng(seed)
    return AdapterSpec(
        name=name,
        a=rng.standard_normal((embed, rank)).astype(np.float32) * 0.1,
        b=rng.standard_normal((rank, vocab)).astype(np.float32) * 0.1,
        scale=scale, **kw)


class AdapterRegistry:
    """Host-DRAM adapter tier: registration, budget, concurrency caps.

    Thread-safe (registration arrives from app handlers, admission from
    ``_submit``, lookups from the engine device thread)."""

    def __init__(self, host_budget_mb: float = 256.0):
        self.host_budget_bytes = int(host_budget_mb * (1 << 20))
        self._specs: dict[str, AdapterSpec] = {}
        self._inflight: dict[str, int] = {}
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------

    def register(self, spec: AdapterSpec, pool: "AdapterPool | None" = None) -> None:
        """Admit ``spec`` into the host tier. Raises when the host budget
        would overflow (registered adapters are never silently evicted)
        or when replacing an adapter that is live on device (``pool``
        passed and the name has device refs) — replacing weights under
        an in-flight request would mix adapters mid-request."""
        with self._lock:
            current = self._specs.get(spec.name)
            total = sum(s.nbytes for n, s in self._specs.items()
                        if n != spec.name) + spec.nbytes
            if total > self.host_budget_bytes:
                raise ValueError(
                    f"adapter {spec.name!r} ({spec.nbytes >> 20} MiB) would "
                    f"overflow ADAPTER_HOST_MB "
                    f"({self.host_budget_bytes >> 20} MiB); registered "
                    f"adapters are never evicted — raise the budget or "
                    f"unregister first")
            if current is not None and pool is not None:
                pool.invalidate(spec.name)  # raises if device refs > 0
            self._specs[spec.name] = spec

    def unregister(self, name: str, pool: "AdapterPool | None" = None) -> None:
        with self._lock:
            if pool is not None:
                pool.invalidate(name)
            self._specs.pop(name, None)

    def get(self, name: str) -> AdapterSpec:
        with self._lock:
            spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown adapter {name!r}; registered: "
                           f"{sorted(self._specs)}")
        return spec

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._specs)

    def digest(self) -> str:
        """Order-independent fingerprint of the loaded adapter set, for
        the disaggregated handoff JOIN gate (tpu/handoff.py): prefill and
        decode peers must agree on which adapters exist (names + ranks +
        scales — factor bytes are deliberately excluded so re-registering
        identical metadata after a restart still matches)."""
        h = hashlib.blake2b(digest_size=8)
        with self._lock:
            for name in sorted(self._specs):
                s = self._specs[name]
                h.update(f"{name}:{s.rank}:{s.scale:.6g}\n".encode())
        return h.hexdigest()

    # -- per-adapter admission --------------------------------------------

    def admit(self, name: str) -> AdapterSpec:
        """Resolve + acquire one concurrency share for ``name``. Raises
        ``KeyError`` for unknown adapters and 429 ``TooManyRequests`` at
        the adapter's cap (mirrors qos.admit_engine's per-class gate —
        release via :meth:`release` on the request's done callback)."""
        spec = self.get(name)
        if spec.max_concurrency:
            with self._lock:
                if self._inflight.get(name, 0) >= spec.max_concurrency:
                    raise TooManyRequests(
                        f"adapter {name!r} at its concurrency cap "
                        f"({spec.max_concurrency})", retry_after=1.0)
                self._inflight[name] = self._inflight.get(name, 0) + 1
        return spec

    def release(self, name: str) -> None:
        with self._lock:
            if name in self._inflight:
                self._inflight[name] = max(0, self._inflight[name] - 1)

    def stats(self) -> dict:
        with self._lock:
            return {
                "registered": len(self._specs),
                "host_bytes": sum(s.nbytes for s in self._specs.values()),
                "host_budget_bytes": self.host_budget_bytes,
                "inflight": {k: v for k, v in self._inflight.items() if v},
            }


class AdapterPool:
    """Device (HBM) adapter tier: ``slots`` fixed-shape pool entries.

    All device state is three arrays — ``a [S, E, R]``, ``b [S, R, V]``,
    ``scale [S]`` — passed to every adapter-enabled program call as
    dynamic jit args. Host-side bookkeeping (slot map, refcounts, LRU
    ticks) is guarded by the ENGINE's state lock: acquire/release happen
    where KV pages are claimed/freed, so no separate lock is taken here
    (the registry above, which sees other threads, has its own).

    Ranks up to ``rank`` are supported; shorter ranks are zero-padded on
    upload (exact — padded columns contribute 0.0 to the delta)."""

    BASE_SLOT = 0

    def __init__(self, slots: int, embed: int, vocab: int, rank: int):
        import jax.numpy as jnp  # deferred: host-only users never pay jax

        if slots < 2:
            raise ValueError("adapter pool needs >= 2 slots (slot 0 is the "
                             "reserved base-model slot)")
        self.slots, self.embed, self.vocab, self.rank = slots, embed, vocab, rank
        self.a = jnp.zeros((slots, embed, rank), jnp.float32)
        self.b = jnp.zeros((slots, rank, vocab), jnp.float32)
        self.scale = jnp.zeros((slots,), jnp.float32)
        self._slot_of: dict[str, int] = {}
        self._name_of: dict[int, str] = {}
        self._refs = [0] * slots
        self._tick = 0
        self._lru = [0] * slots
        self.uploads = 0
        self.evictions = 0

    @property
    def pool_bytes(self) -> int:
        return int(self.a.nbytes + self.b.nbytes + self.scale.nbytes)

    @classmethod
    def slots_for_budget(cls, pool_mb: float, embed: int, vocab: int,
                         rank: int) -> int:
        """How many pool slots fit in ``pool_mb`` MiB of HBM (f32 factors)."""
        per_slot = 4 * (embed * rank + rank * vocab)
        return max(2, int(pool_mb * (1 << 20)) // max(1, per_slot))

    # -- device-tier paging ------------------------------------------------

    def acquire(self, spec: AdapterSpec) -> int | None:
        """Pin ``spec`` into a pool slot (upload if not resident) and take
        one reference. Returns the slot id, or ``None`` when every slot
        is referenced by a live lane — the caller requeues the request,
        exactly like KV page exhaustion in ``_admit_prefill``. Called
        under the engine state lock; the upload is an async dispatch."""
        slot = self._slot_of.get(spec.name)
        if slot is None:
            slot = self._pick_victim()
            if slot is None:
                return None
            self._upload(slot, spec)
        self._refs[slot] += 1
        self._tick += 1
        self._lru[slot] = self._tick
        return slot

    def release(self, slot: int) -> None:
        """Drop one reference (engine ``_free_slot``). Slot 0 is the base
        adapter — never refcounted, never evicted."""
        if slot != self.BASE_SLOT and self._refs[slot] > 0:
            self._refs[slot] -= 1

    def invalidate(self, name: str) -> None:
        """Forget the device copy of ``name`` (weights replaced in the
        registry). Raises while lanes still reference it."""
        slot = self._slot_of.get(name)
        if slot is None:
            return
        if self._refs[slot] > 0:
            raise ValueError(
                f"adapter {name!r} has {self._refs[slot]} in-flight "
                f"lane(s); drain before replacing its weights")
        self._forget(slot)

    def _pick_victim(self) -> int | None:
        best, best_tick = None, None
        for s in range(1, self.slots):
            if self._refs[s]:
                continue
            if s not in self._name_of:       # empty slot: take immediately
                return s
            if best_tick is None or self._lru[s] < best_tick:
                best, best_tick = s, self._lru[s]
        if best is not None:
            self._forget(best)
            self.evictions += 1
        return best

    def _forget(self, slot: int) -> None:
        name = self._name_of.pop(slot, None)
        if name is not None:
            self._slot_of.pop(name, None)

    def _upload(self, slot: int, spec: AdapterSpec) -> None:
        import jax.numpy as jnp

        r = spec.rank
        if r > self.rank:
            raise ValueError(
                f"adapter {spec.name!r} rank {r} exceeds the pool rank "
                f"{self.rank} (ADAPTER_RANK)")
        a = np.zeros((self.embed, self.rank), np.float32)
        b = np.zeros((self.rank, self.vocab), np.float32)
        a[:, :r] = spec.a
        b[:r, :] = spec.b
        # functional updates: new arrays, same shape/dtype -> the packed
        # programs never recompile; async dispatch, safe under the lock
        self.a = self.a.at[slot].set(jnp.asarray(a))
        self.b = self.b.at[slot].set(jnp.asarray(b))
        self.scale = self.scale.at[slot].set(jnp.float32(spec.scale))
        self._slot_of[spec.name] = slot
        self._name_of[slot] = spec.name
        self.uploads += 1

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "resident": len(self._slot_of),
            "referenced": sum(1 for s in range(1, self.slots) if self._refs[s]),
            "rank": self.rank,
            "pool_bytes": self.pool_bytes,
            "uploads": self.uploads,
            "evictions": self.evictions,
        }
