"""Context: the transport-neutral handler context (gofr `pkg/gofr/context.go`).

Every entrypoint — HTTP, gRPC, pub/sub message, cron firing, CLI invocation,
websocket — constructs a Context from (request, container) and passes it to the
user handler ``def handler(ctx) -> result``. Handlers reach infrastructure only
through the context: ``ctx.sql``, ``ctx.redis``, ``ctx.tpu``, ``ctx.infer``,
``ctx.http_service(name)``, never a transport.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from gofr_tpu.tracing import Span

if TYPE_CHECKING:
    from gofr_tpu.container import Container


class Context:
    __slots__ = ("request", "container", "responder", "span", "_values",
                 "_engine_requests")

    def __init__(self, request: Any, container: "Container", responder: Any = None, span: Span | None = None):
        self.request = request
        self.container = container
        self.responder = responder
        self.span = span
        self._values: dict[str, Any] = {}
        # engine Requests submitted through this context, so the transport
        # can cancel them all when the client disconnects mid-handler
        # (docs/resilience.md); populated via the _on_submit engine hook
        self._engine_requests: list[Any] = []

    # -- request passthrough ---------------------------------------------------

    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, target: Any = dict) -> Any:
        return self.request.bind(target)

    def header(self, key: str) -> str | None:
        headers = getattr(self.request, "headers", None)
        return headers.get(key) if headers else None

    @property
    def claims(self) -> dict[str, Any]:
        """JWT claims injected by the OAuth middleware (empty when unauthenticated)."""
        ctx = self.request.context() if hasattr(self.request, "context") else {}
        return ctx.get("jwt_claims", {})

    @property
    def auth_user(self) -> str | None:
        ctx = self.request.context() if hasattr(self.request, "context") else {}
        return ctx.get("auth_user")

    # -- container passthrough -------------------------------------------------

    @property
    def logger(self):
        return self.container.logger

    @property
    def config(self):
        return self.container.config

    @property
    def metrics(self):
        return self.container.metrics

    @property
    def sql(self):
        return self.container.sql

    @property
    def redis(self):
        return self.container.redis

    @property
    def kv(self):
        return self.container.kv

    @property
    def file(self):
        return self.container.file

    @property
    def mongo(self):
        return self.container.mongo

    @property
    def cassandra(self):
        return self.container.cassandra

    @property
    def clickhouse(self):
        return self.container.clickhouse

    @property
    def tpu(self):
        return self.container.tpu

    def http_service(self, name: str):
        return self.container.http_service(name)

    def publish(self, topic: str, payload: Any) -> None:
        headers = None
        if self.span is not None and self.span.sampled:
            # trace context rides the message, so the subscriber's span
            # joins this trace instead of starting a fresh one
            headers = {"traceparent": self.span.traceparent()}
        self.container.publish(topic, payload, headers=headers)

    # -- model inference (the TPU-native capability) ---------------------------

    def deadline_remaining(self) -> float | None:
        """Seconds left in the request's propagated deadline (can be <= 0
        once expired); None when the request carries none. Parsed at the
        transport edge from ``X-Request-Deadline-Ms`` or the gRPC
        deadline (gofr_tpu/deadline.py, docs/resilience.md)."""
        from gofr_tpu import deadline as _deadline

        req = self.request
        req_ctx = req.context() if hasattr(req, "context") else {}
        return _deadline.remaining(req_ctx)

    def cancel_inflight(self, reason: str = "client_disconnect") -> int:
        """Cancel every engine Request submitted through this context —
        the transport calls this when the client goes away, so slots and
        paged KV are reclaimed instead of computing for a ghost. Returns
        the number of requests flagged."""
        n = 0
        for r in self._engine_requests:
            if not r.cancelled:
                r.cancel(reason)
                n += 1
        return n

    def _qos_kw(self, kw: dict[str, Any]) -> dict[str, Any]:
        """Inject the request's QoS priority class (resolved by the QoS
        middleware/interceptor from the class header) into engine kwargs,
        unless the handler set one explicitly — scheduling follows the
        transport classification with zero handler cooperation. Also carries
        the request's server span to the engine (``_parent_span``): the
        engine device loop runs on another thread, where contextvars can't
        reach, so the span travels explicitly and the engine stitches its
        queue_wait/prefill/decode children under it."""
        from gofr_tpu import deadline as _deadline

        if self.span is not None and "_parent_span" not in kw:
            kw["_parent_span"] = self.span
        req = self.request
        req_ctx = req.context() if hasattr(req, "context") else {}
        # the propagated deadline becomes the engine timeout: the QoS
        # predicted-wait check then sheds doomed work pre-slot with 504
        # (docs/resilience.md). An explicit handler timeout can only
        # tighten the budget, never extend past the client's deadline.
        rem = _deadline.remaining(req_ctx)
        if rem is not None:
            t = kw.get("timeout")
            kw["timeout"] = rem if t is None else min(t, rem)
        # track the submitted Request so a client disconnect can cancel it
        kw.setdefault("_on_submit", self._engine_requests.append)
        # adapter routing header (X-Adapter-ID / gRPC x-adapter-id): a
        # handler-set adapter_id wins — the header is transport-level
        # default routing, same precedence rule as the QoS class below
        if "adapter_id" not in kw and "_adapter" not in kw:
            ad = req_ctx.get("adapter_id")
            if not ad:
                headers = getattr(req, "headers", None)
                if headers is not None:  # HTTP: case-insensitive header dict
                    ad = headers.get("X-Adapter-ID")
                elif hasattr(req, "param"):  # gRPC metadata (lowercased keys)
                    ad = req.param("x-adapter-id") or None
            if ad:
                kw["adapter_id"] = ad
        if "qos_class" in kw or "_qos_class" in kw:
            return kw
        cls = req_ctx.get("qos_class")
        if not cls and hasattr(req, "param"):
            # gRPC metadata fallback — the CONFIGURED class header (gRPC
            # lowercases metadata keys), not a hardcoded spelling
            controller = getattr(self.container, "qos", None)
            header = (controller.policy.class_header if controller is not None
                      else "X-QoS-Class")
            cls = req.param(header.lower()) or None
        if cls:
            kw["_qos_class"] = cls
        return kw

    def infer(self, model: str, inputs: Any, **kw: Any):
        """Enqueue ``inputs`` on a served model's continuous-batching engine and
        block until the result is ready. Works from sync handlers (the engine
        runs in its own device thread)."""
        return self.container.infer(model, inputs, **self._qos_kw(kw))

    def generate(self, model: str, prompt: Any, max_new_tokens: int = 64, **kw: Any):
        return self.container.generate(
            model, prompt, max_new_tokens=max_new_tokens, **self._qos_kw(kw))

    async def agenerate(self, model: str, prompt: Any, max_new_tokens: int = 64, **kw: Any):
        """Async-native generate for ``async def`` handlers: awaits the
        engine future via a completion callback — no thread parks per
        in-flight request, so one event loop sustains hundreds of
        concurrent generations."""
        import asyncio

        engine = self.container.engine(model)
        kw = self._qos_kw(kw)
        timeout = kw.get("timeout", None)
        if timeout is None:
            timeout = getattr(engine, "default_timeout", None)
        req = engine.submit(prompt, max_new_tokens=max_new_tokens, **kw)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def on_done(r) -> None:
            def resolve() -> None:
                if fut.cancelled():
                    return
                result, error = r.outcome()
                if error is not None:
                    fut.set_exception(error)
                else:
                    fut.set_result(result)
            loop.call_soon_threadsafe(resolve)

        req.add_done_callback(on_done)
        try:
            # the client-side backstop Request.result() has: a wedged device
            # thread never calls complete(), so the await must time out on
            # its own rather than hang the handler forever
            if timeout:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        except asyncio.TimeoutError:
            req.cancel()
            from gofr_tpu.http.errors import RequestTimeout

            raise RequestTimeout() from None
        except asyncio.CancelledError:
            req.cancel()  # free the slot when the client went away
            raise

    # -- tracing & scratch values ---------------------------------------------

    def trace(self, name: str) -> Span:
        """Open a user span as a child of the request span (gofr `context.go:45-55`).
        Use as a context manager: ``with ctx.trace("work"): ...``"""
        return self.container.tracer.start_span(name, parent=self.span)

    def set_value(self, key: str, value: Any) -> None:
        self._values[key] = value

    def get_value(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    # -- CLI output (cmd responder) -------------------------------------------

    def out(self, *args: Any) -> None:
        if self.responder is not None and hasattr(self.responder, "write"):
            self.responder.write(*args)
