"""Pub/Sub: publisher/subscriber interfaces + backend switch.

Parity with gofr `pkg/gofr/datasource/pubsub/`: ``Publisher``/``Subscriber``
interfaces (`interface.go:11-26`), a ``Message`` that implements the
transport-neutral Request interface so subscribe handlers look identical to
HTTP handlers (`message.go:13-103`), at-least-once commit semantics, and the
container's backend-by-config switch (`container/container.go:95-122`).

Backends: ``inmemory`` (in-tree, also the test double), ``file`` (in-tree,
cross-PROCESS coordination over a shared directory — pubsub/file.py),
``kafka``/``gcp``/``mqtt`` engage only when their client libraries are
importable — otherwise the container warns and leaves pub/sub unwired.
"""

from __future__ import annotations

import json
from typing import Any, Protocol

from gofr_tpu.utils import bind as binder


class Message:
    """A received message; implements the Request interface for handlers."""

    def __init__(self, topic: str, value: bytes, metadata: dict[str, Any] | None = None, committer=None):
        self.topic = topic
        self.value = value
        self.metadata = metadata or {}
        self._committer = committer
        self.committed = False
        self._ctx: dict[str, Any] = {}

    # -- Request interface -----------------------------------------------------

    def param(self, key: str) -> str:
        v = self.metadata.get(key)
        return str(v) if v is not None else ""

    def params(self, key: str) -> list[str]:
        v = self.param(key)
        return [v] if v else []

    def path_param(self, key: str) -> str:
        return self.topic if key in ("topic", "") else self.param(key)

    def bind(self, target: Any = dict) -> Any:
        if target is bytes:
            return self.value
        if target is str:
            return self.value.decode()
        text = self.value.decode()
        if target in (int, float, bool):
            return binder.bind_value(text, target)
        try:
            data = json.loads(text)
        except json.JSONDecodeError as e:
            raise binder.BindError(f"message on {self.topic!r} is not JSON") from e
        return binder.bind(data, target)

    def host_name(self) -> str:
        return self.topic

    def context(self) -> dict[str, Any]:
        return self._ctx

    # -- commit (at-least-once) ------------------------------------------------

    def commit(self) -> None:
        if self._committer is not None and not self.committed:
            self._committer()
        self.committed = True


class PubSub(Protocol):
    def publish(self, topic: str, payload: Any, headers: dict | None = None) -> None:
        """Publish; ``headers`` (optional, in-tree brokers support it) carry
        cross-cutting metadata like the W3C traceparent and surface on the
        consumer side through ``Message.param``."""
        ...

    def subscribe(self, topic: str, group: str = "", timeout: float | None = None) -> Message | None:
        """Block until the next message for ``topic`` (None on shutdown).
        ``timeout`` (supported by every in-tree broker; the app's
        subscriber loop and the router's gossip loop poll with it) bounds
        the wait and returns None on expiry."""
        ...

    def health_check(self) -> dict[str, Any]: ...

    def close(self) -> None: ...


def encode_payload(payload: Any) -> bytes:
    if isinstance(payload, bytes):
        return payload
    if isinstance(payload, str):
        return payload.encode()
    return json.dumps(payload, default=str).encode()


def connect_pubsub(backend: str, config, logger, metrics):
    if backend in ("inmemory", "memory", "mock"):
        from gofr_tpu.pubsub.inmemory import InMemoryBroker

        logger.info("using in-memory pubsub broker")
        return InMemoryBroker()
    if backend == "file":
        from gofr_tpu.pubsub.file import FileBroker

        directory = config.get_or_default("PUBSUB_DIR", "./pubsub-data")
        logger.infof("using file pubsub broker under %s", directory)
        return FileBroker(directory)
    if backend == "kafka":
        try:
            import kafka  # type: ignore[import-not-found]  # noqa: F401
        except ImportError:
            logger.warn("PUBSUB_BACKEND=kafka but no kafka client installed; pubsub not wired")
            return None
        from gofr_tpu.pubsub.kafka import KafkaBroker

        return KafkaBroker(config, logger, metrics)
    if backend in ("google", "gcp"):
        try:
            from google.cloud import pubsub_v1  # type: ignore[import-not-found]  # noqa: F401
        except ImportError:
            logger.warn("PUBSUB_BACKEND=google but google-cloud-pubsub not installed; pubsub not wired")
            return None
        from gofr_tpu.pubsub.google import GooglePubSubBroker

        return GooglePubSubBroker(config, logger, metrics)
    if backend == "mqtt":
        try:
            import paho.mqtt.client  # type: ignore[import-not-found]  # noqa: F401
        except ImportError:
            logger.warn("PUBSUB_BACKEND=mqtt but paho-mqtt not installed; pubsub not wired")
            return None
        from gofr_tpu.pubsub.mqtt import MqttBroker

        return MqttBroker(config, logger, metrics)
    logger.warnf("unknown PUBSUB_BACKEND %r; pubsub not wired", backend)
    return None
