"""MQTT backend (gofr `pkg/gofr/datasource/pubsub/mqtt/` parity).

Per-topic subscription queues under a lock (`mqtt.go:38,156-170`),
QoS/ordering/keepalive from config (`container.go:126-161`), and the
callback-style ``subscribe_with_function`` (`mqtt.go:298`). The paho client
is injectable (``client_factory``) so the driver tests hermetically;
``FakeMqttClient`` is an in-tree loopback implementing the client surface
the driver touches.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Any, Callable

from gofr_tpu.pubsub import Message, encode_payload


class MqttBroker:
    def __init__(self, config, logger, metrics, client_factory: Callable[..., Any] | None = None):
        self._logger = logger
        self._metrics = metrics
        self._host = config.get_or_default("MQTT_HOST", "localhost")
        self._port = config.get_int("MQTT_PORT", 1883)
        self._qos = config.get_int("MQTT_QOS", 1)
        self._keepalive = config.get_int("MQTT_KEEP_ALIVE", 30)
        client_id = config.get("MQTT_CLIENT_ID") or f"gofr-tpu-{uuid.uuid4().hex[:8]}"

        if client_factory is None:
            import paho.mqtt.client as paho  # type: ignore[import-not-found]

            def client_factory(cid):  # noqa: F811
                return paho.Client(client_id=cid, clean_session=False)

        self._client = client_factory(client_id)
        self._queues: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._client.on_message = self._on_message
        self._client.connect(self._host, self._port, self._keepalive)
        if hasattr(self._client, "loop_start"):
            self._client.loop_start()

    # -- internals -------------------------------------------------------------

    def _queue_for(self, topic: str) -> queue.Queue:
        with self._lock:
            if topic not in self._queues:
                self._queues[topic] = queue.Queue()
                self._client.subscribe(topic, qos=self._qos)
            return self._queues[topic]

    def _on_message(self, _client, _userdata, msg) -> None:
        with self._lock:
            q = self._queues.get(msg.topic)
        if q is not None:
            q.put(msg.payload)

    # -- broker interface ------------------------------------------------------

    def publish(self, topic: str, payload: Any) -> None:
        info = self._client.publish(topic, encode_payload(payload), qos=self._qos)
        if hasattr(info, "wait_for_publish"):
            info.wait_for_publish(timeout=30)

    def subscribe(self, topic: str, group: str = "default", timeout: float | None = None) -> Message | None:
        q = self._queue_for(topic)
        try:
            value = q.get(timeout=timeout if timeout is not None else 1.0)
        except queue.Empty:
            return None
        # MQTT QoS handles redelivery at the protocol layer; commit is a no-op
        return Message(topic, value, metadata={"group": group}, committer=lambda: None)

    def subscribe_with_function(self, topic: str, fn: Callable[[Message], Any]) -> None:
        """Callback-style subscription (`mqtt.go:298` parity): ``fn`` runs on
        a daemon thread per delivered message. Handler exceptions are logged
        and consumption continues; the thread exits when the broker closes."""

        def loop():
            while not self._closed.is_set():
                msg = self.subscribe(topic, timeout=1.0)
                if msg is None:
                    continue
                try:
                    fn(msg)
                except Exception as e:  # noqa: BLE001
                    if self._logger:
                        self._logger.error(f"mqtt handler for {topic!r} failed: {e!r}")

        threading.Thread(target=loop, daemon=True, name=f"mqtt-sub-{topic}").start()

    def create_topic(self, topic: str) -> None:
        self._queue_for(topic)

    def delete_topic(self, topic: str) -> None:
        with self._lock:
            if topic in self._queues:
                self._client.unsubscribe(topic)
                del self._queues[topic]

    def health_check(self) -> dict[str, Any]:
        connected = True
        if hasattr(self._client, "is_connected"):
            try:
                connected = bool(self._client.is_connected())
            except Exception:  # noqa: BLE001
                connected = False
        return {
            "status": "UP" if connected else "DOWN",
            "details": {"host": self._host, "port": self._port, "qos": self._qos},
        }

    def close(self) -> None:
        self._closed.set()
        if hasattr(self._client, "loop_stop"):
            self._client.loop_stop()
        self._client.disconnect()


class FakeMqttClient:
    """In-tree loopback client: publish delivers straight to on_message."""

    def __init__(self, *_a, **_kw):
        self.on_message = None
        self._subscribed: set[str] = set()
        self._connected = False

    def connect(self, *_a, **_kw):
        self._connected = True

    def disconnect(self):
        self._connected = False

    def is_connected(self):
        return self._connected

    def subscribe(self, topic, qos=0):
        self._subscribed.add(topic)

    def unsubscribe(self, topic):
        self._subscribed.discard(topic)

    def publish(self, topic, payload, qos=0):
        if topic in self._subscribed and self.on_message is not None:
            msg = type("_Msg", (), {"topic": topic, "payload": payload})()
            self.on_message(self, None, msg)
