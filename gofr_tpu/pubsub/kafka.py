"""Kafka backend (engages only when a ``kafka`` client library is importable).

Parity with gofr `pkg/gofr/datasource/pubsub/kafka/`: one shared producer with
batch size/timeout config (`kafka.go:83-89`), lazily-created per-(topic, group)
consumer readers guarded by a lock (`kafka.go:177-191`), per-message commit for
at-least-once delivery (`kafka.go:203`), topic admin, health check.
"""

from __future__ import annotations

import threading
from typing import Any

from gofr_tpu.pubsub import Message, encode_payload


class KafkaBroker:
    def __init__(self, config, logger, metrics):
        from kafka import KafkaConsumer, KafkaProducer  # type: ignore[import-not-found]

        self._KafkaConsumer = KafkaConsumer
        self._brokers = config.get_or_default("PUBSUB_BROKER", "localhost:9092").split(",")
        self._logger = logger
        self._metrics = metrics
        self._producer = KafkaProducer(
            bootstrap_servers=self._brokers,
            batch_size=config.get_int("KAFKA_BATCH_SIZE", 16384),
            linger_ms=config.get_int("KAFKA_BATCH_TIMEOUT", 5),
        )
        self._consumers: dict[tuple[str, str], Any] = {}
        self._lock = threading.Lock()

    def publish(self, topic: str, payload: Any, headers: dict | None = None) -> None:
        # Kafka record headers carry cross-cutting metadata (traceparent);
        # the wire type is (str, bytes) pairs
        hdrs = [(str(k), str(v).encode()) for k, v in headers.items()] if headers else None
        self._producer.send(topic, encode_payload(payload), headers=hdrs).get(timeout=30)

    def _consumer(self, topic: str, group: str):
        # Keyed by calling THREAD as well: KafkaConsumer is not thread-safe,
        # and concurrent subscriber workers (SUBSCRIBER_WORKERS > 1) must
        # each join the group as their own member — the group coordinator
        # then assigns them disjoint partitions, which is exactly how Kafka
        # scales a consumer group (and why per-worker commits stay safe:
        # commits are per-partition and each partition has one owner).
        key = (topic, group, threading.get_ident())
        with self._lock:
            if key not in self._consumers:
                self._consumers[key] = self._KafkaConsumer(
                    topic,
                    bootstrap_servers=self._brokers,
                    group_id=group or "gofr-tpu",
                    enable_auto_commit=False,
                    # a NEW group must start from the log's beginning, not
                    # its end — with 'latest' (the client default) any
                    # message published before the group's first poll is
                    # silently skipped, breaking at-least-once for
                    # publish-then-subscribe startups
                    auto_offset_reset="earliest",
                )
            return self._consumers[key]

    def subscribe(self, topic: str, group: str = "default", timeout: float | None = None) -> Message | None:
        consumer = self._consumer(topic, group)
        timeout_ms = int(timeout * 1000) if timeout else 1000
        records = consumer.poll(timeout_ms=timeout_ms, max_records=1)
        for batch in records.values():
            for record in batch:
                # max_records=1 ⇒ this consumer's position only covers the
                # one in-flight record, so commit() acknowledges exactly it
                metadata = {"offset": record.offset, "partition": record.partition, "group": group}
                for k, v in (getattr(record, "headers", None) or ()):
                    metadata.setdefault(k, v.decode(errors="replace") if isinstance(v, bytes) else v)
                return Message(
                    topic,
                    record.value,
                    metadata=metadata,
                    committer=consumer.commit,
                )
        return None

    def health_check(self) -> dict[str, Any]:
        try:
            ok = bool(self._producer.bootstrap_connected())
            return {"status": "UP" if ok else "DOWN", "details": {"brokers": self._brokers}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"brokers": self._brokers, "error": str(e)}}

    def close(self) -> None:
        self._producer.close()
        with self._lock:
            for c in self._consumers.values():
                c.close()
