"""Google Pub/Sub backend (gofr `pkg/gofr/datasource/pubsub/google/` parity).

Validates project/subscription config up front (`google.go:63-72`), publishes
via topic publish futures (`google.go:75-114`), pull-subscribes with explicit
ack for at-least-once (`google.go:117-`). The google-cloud client pair is
injectable for hermetic tests (``FakeGooglePubSub``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from gofr_tpu.pubsub import Message, encode_payload


class GooglePubSubBroker:
    def __init__(self, config, logger, metrics, client_factory: Callable[..., Any] | None = None):
        self._logger = logger
        self._project = config.get("GOOGLE_PROJECT_ID")
        self._sub_prefix = config.get_or_default("GOOGLE_SUBSCRIPTION_NAME", "gofr-tpu")
        if not self._project:
            raise ValueError("PUBSUB_BACKEND=google requires GOOGLE_PROJECT_ID")

        if client_factory is None:
            from google.cloud import pubsub_v1  # type: ignore[import-not-found]

            def client_factory():  # noqa: F811
                return pubsub_v1.PublisherClient(), pubsub_v1.SubscriberClient()

        self._publisher, self._subscriber = client_factory()
        self._lock = threading.Lock()
        self._known_subs: set[tuple[str, str]] = set()

    def _topic_path(self, topic: str) -> str:
        return f"projects/{self._project}/topics/{topic}"

    def _sub_path(self, topic: str, group: str) -> str:
        return f"projects/{self._project}/subscriptions/{self._sub_prefix}-{group}-{topic}"

    # -- broker interface ------------------------------------------------------

    def publish(self, topic: str, payload: Any) -> None:
        future = self._publisher.publish(self._topic_path(topic), encode_payload(payload))
        future.result(timeout=30)

    def _ensure_subscription(self, topic: str, group: str) -> str:
        sub = self._sub_path(topic, group)
        key = (topic, group)
        with self._lock:
            if key not in self._known_subs:
                try:
                    self._subscriber.create_subscription(
                        request={"name": sub, "topic": self._topic_path(topic)}
                    )
                except Exception:  # noqa: BLE001 - already exists
                    pass
                self._known_subs.add(key)
        return sub

    def subscribe(self, topic: str, group: str = "default", timeout: float | None = None) -> Message | None:
        sub = self._ensure_subscription(topic, group)
        try:
            resp = self._subscriber.pull(
                request={"subscription": sub, "max_messages": 1},
                timeout=timeout if timeout is not None else 1.0,
            )
        except Exception as e:  # noqa: BLE001
            # an idle pull ends in DeadlineExceeded/RetryError — that's the
            # broker contract's "no message", not an error
            if type(e).__name__ in ("DeadlineExceeded", "RetryError", "TimeoutError"):
                return None
            raise
        if not resp.received_messages:
            return None
        received = resp.received_messages[0]

        def committer(ack_id=received.ack_id):
            self._subscriber.acknowledge(request={"subscription": sub, "ack_ids": [ack_id]})

        return Message(
            topic, received.message.data,
            metadata={"group": group, "message_id": getattr(received.message, "message_id", "")},
            committer=committer,
        )

    def create_topic(self, topic: str) -> None:
        try:
            self._publisher.create_topic(request={"name": self._topic_path(topic)})
        except Exception:  # noqa: BLE001 - already exists
            pass

    def delete_topic(self, topic: str) -> None:
        self._publisher.delete_topic(request={"topic": self._topic_path(topic)})

    def health_check(self) -> dict[str, Any]:
        try:
            # listing is the cheapest authenticated round trip
            self._publisher.list_topics(request={"project": f"projects/{self._project}"})
            return {"status": "UP", "details": {"project": self._project}}
        except Exception as e:  # noqa: BLE001
            return {"status": "DOWN", "details": {"project": self._project, "error": str(e)}}

    def close(self) -> None:
        for c in (self._publisher, self._subscriber):
            close = getattr(c, "close", None)
            if close:
                close()


# -- in-tree fake --------------------------------------------------------------


class _FakeFuture:
    def result(self, timeout=None):
        return "msg-id"


class FakeGooglePubSub:
    """Publisher+Subscriber pair backed by shared in-process queues."""

    def __init__(self):
        self._topics: dict[str, list[bytes]] = {}
        self._acked: dict[str, int] = {}
        self._cursor: dict[str, int] = {}
        self._lock = threading.Lock()

    # publisher surface
    def publish(self, topic_path: str, data: bytes) -> _FakeFuture:
        with self._lock:
            self._topics.setdefault(topic_path, []).append(data)
        return _FakeFuture()

    def create_topic(self, request):
        with self._lock:
            self._topics.setdefault(request["name"], [])

    def delete_topic(self, request):
        with self._lock:
            self._topics.pop(request["topic"], None)

    def list_topics(self, request):
        return list(self._topics)

    # subscriber surface
    def create_subscription(self, request):
        with self._lock:
            self._cursor.setdefault(request["name"], 0)
            self._acked.setdefault(request["name"], 0)
            self._sub_topic = getattr(self, "_sub_topic", {})
            self._sub_topic[request["name"]] = request["topic"]

    def pull(self, request, timeout=None):
        sub = request["subscription"]
        with self._lock:
            topic = self._sub_topic.get(sub)
            log = self._topics.get(topic, [])
            pos = self._cursor.get(sub, 0)
            msgs = []
            if pos < len(log):
                self._cursor[sub] = pos + 1
                msg = type("_Msg", (), {"data": log[pos], "message_id": str(pos)})()
                msgs = [type("_Recv", (), {"ack_id": str(pos), "message": msg})()]
        return type("_Resp", (), {"received_messages": msgs})()

    def acknowledge(self, request):
        sub = request["subscription"]
        with self._lock:
            for ack in request["ack_ids"]:
                self._acked[sub] = max(self._acked.get(sub, 0), int(ack) + 1)
