"""File-transport pub/sub broker: the in-memory broker's semantics over a
shared directory, so SEPARATE PROCESSES can coordinate without Kafka.

This is the transport behind the two-process publisher/subscriber examples
(`examples/using-publisher` + `examples/using-subscriber`): a per-topic
append-only JSONL log plus a per-(topic, group) committed-offset file, all
under ``PUBSUB_DIR``. Appends are serialized with ``fcntl`` advisory locks;
offsets advance only across a contiguous committed prefix (the in-memory
broker's rule), so a consumer crash between handler and commit redelivers —
faithful at-least-once across process boundaries.

Not a Kafka replacement: one log per topic (no partitions), delivery fans
out per GROUP — run ONE consumer process per (topic, group). The delivery
cursor is process-local (only the committed offset is shared on disk), so
two same-group consumer processes would each receive every message; there
is no cross-process claim/lease protocol. Throughput is bounded by
fsync-free appends + poll-based subscribe. It exists so the example tier
and small deployments have a real cross-process broker with zero external
dependencies; production traffic and consumer scale-out belong on
``PUBSUB_BACKEND=kafka``.
"""

from __future__ import annotations

import base64
import fcntl
import json
import os
import threading
import time
from typing import Any

from gofr_tpu.pubsub import Message, encode_payload

_SAFE = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."


def _slug(name: str) -> str:
    return "".join(c if c in _SAFE else "_" for c in name) or "_"


class FileBroker:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        # per-(topic, group) delivery cursor for THIS process; starts at the
        # durable committed offset, so a process restart redelivers exactly
        # the uncommitted suffix (at-least-once)
        self._cursor: dict[tuple[str, str], int] = {}
        self._done: dict[tuple[str, str], set[int]] = {}
        # per-topic (bytes-consumed, committed lines) read cache
        self._log_cache: dict[str, tuple[int, list[str]]] = {}
        self._closed = False

    # -- paths -----------------------------------------------------------------

    def _log_path(self, topic: str) -> str:
        return os.path.join(self.dir, f"{_slug(topic)}.log")

    def _offset_path(self, topic: str, group: str) -> str:
        return os.path.join(self.dir, f"{_slug(topic)}.{_slug(group)}.offset")

    def _read_offset(self, topic: str, group: str) -> int:
        try:
            with open(self._offset_path(topic, group)) as f:
                return int(f.read().strip() or "0")
        except (FileNotFoundError, ValueError):
            return 0

    def _write_offset(self, topic: str, group: str, offset: int) -> None:
        path = self._offset_path(topic, group)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(offset))
        os.replace(tmp, path)  # atomic: readers never see a torn offset

    # -- publish ---------------------------------------------------------------

    def publish(self, topic: str, payload: Any, headers: dict | None = None) -> None:
        if self._closed:
            raise RuntimeError("broker closed")
        record = {"p": base64.b64encode(encode_payload(payload)).decode()}
        if headers:
            record["h"] = dict(headers)
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with open(self._log_path(topic), "a") as f:
            # advisory lock serializes concurrent publishers: one record is
            # one line, and interleaved partial writes would corrupt both
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                f.write(line)
                f.flush()
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)

    # -- subscribe -------------------------------------------------------------

    def _read_log(self, topic: str) -> list[str]:
        path = self._log_path(topic)
        try:
            size = os.stat(path).st_size
        except FileNotFoundError:
            return []
        # append-only log: unchanged size means unchanged content, so idle
        # polls are one stat, not a full re-read (delete_topic shrinks the
        # size, which also invalidates here)
        cached = self._log_cache.get(topic)
        if cached is not None and cached[0] == size:
            return cached[1]
        with open(path) as f:
            data = f.read()
        # only newline-TERMINATED lines are committed records: a publisher
        # in another process may be mid-append, and delivering the torn
        # tail would hand the handler truncated bytes (and a commit would
        # then skip the real message once the write completes)
        end = data.rfind("\n") + 1
        lines = data[:end].splitlines()
        self._log_cache[topic] = (size if end == len(data) else end, lines)
        return lines

    def subscribe(self, topic: str, group: str = "default",
                  timeout: float | None = None) -> Message | None:
        key = (topic, _slug(group))
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._closed:
                return None
            with self._lock:
                pos = self._cursor.get(key)
                if pos is None:
                    pos = self._cursor[key] = self._read_offset(topic, group)
                lines = self._read_log(topic)
                if pos < len(lines):
                    self._cursor[key] = pos + 1
                    try:
                        record = json.loads(lines[pos])
                    except json.JSONDecodeError:
                        record = {"p": base64.b64encode(lines[pos].encode()).decode()}
                    metadata = dict(record.get("h") or {})
                    metadata.update({"offset": pos, "group": group})
                    return Message(
                        topic,
                        base64.b64decode(record.get("p", "")),
                        metadata=metadata,
                        committer=lambda p=pos: self._commit(topic, group, p),
                    )
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.05)  # poll transport: no file-watch dependency

    def _commit(self, topic: str, group: str, pos: int) -> None:
        """Contiguous-prefix commit (inmemory._commit rule): with concurrent
        workers a fast worker's higher commit must not acknowledge a slower
        worker's uncommitted message."""
        key = (topic, _slug(group))
        with self._lock:
            done = self._done.setdefault(key, set())
            done.add(pos)
            offset = self._read_offset(topic, group)
            while offset in done:
                done.discard(offset)
                offset += 1
            self._write_offset(topic, group, offset)

    def rewind_uncommitted(self, topic: str, group: str = "default") -> None:
        """Redeliver consumed-but-uncommitted messages (what a process
        restart does implicitly; exposed for crash tests, like inmemory)."""
        key = (topic, _slug(group))
        with self._lock:
            self._cursor[key] = self._read_offset(topic, group)

    # -- topic admin -----------------------------------------------------------

    def create_topic(self, topic: str) -> None:
        with open(self._log_path(topic), "a"):
            pass

    def delete_topic(self, topic: str) -> None:
        with self._lock:
            self._log_cache.pop(topic, None)
        try:
            os.remove(self._log_path(topic))
        except FileNotFoundError:
            pass

    def topics(self) -> list[str]:
        return sorted(p[:-4] for p in os.listdir(self.dir) if p.endswith(".log"))

    def health_check(self) -> dict[str, Any]:
        status = "UP" if not self._closed and os.path.isdir(self.dir) else "DOWN"
        return {"status": status,
                "details": {"backend": "file", "dir": os.path.abspath(self.dir),
                            "topics": len(self.topics()) if status == "UP" else 0}}

    def close(self) -> None:
        self._closed = True
