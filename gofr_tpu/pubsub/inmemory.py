"""In-memory pub/sub broker: consumer groups, offsets, at-least-once redelivery.

The in-tree broker (and the hermetic test double, like the reference's
MockPubSub — but functional): per-topic append-only log, per-(topic, group)
committed offset, blocking subscribe with timeout, uncommitted messages are
redelivered — faithful at-least-once semantics so micro-batch commit logic can
be tested without Kafka.
"""

from __future__ import annotations

import threading
from typing import Any

from gofr_tpu.pubsub import Message, encode_payload


class InMemoryBroker:
    def __init__(self):
        # log entries are (payload bytes, headers-or-None): headers carry
        # cross-cutting metadata like the W3C traceparent alongside the value
        self._logs: dict[str, list[tuple[bytes, dict | None]]] = {}
        self._offsets: dict[tuple[str, str], int] = {}  # committed offset
        self._cursor: dict[tuple[str, str], int] = {}  # next delivery position
        # out-of-order commits (concurrent consumer workers): positions
        # committed ahead of the contiguous prefix wait here
        self._done: dict[tuple[str, str], set[int]] = {}
        self._cond = threading.Condition()
        self._closed = False

    def publish(self, topic: str, payload: Any, headers: dict | None = None) -> None:
        data = encode_payload(payload)
        with self._cond:
            if self._closed:
                raise RuntimeError("broker closed")
            self._logs.setdefault(topic, []).append((data, dict(headers) if headers else None))
            self._cond.notify_all()

    def subscribe(self, topic: str, group: str = "default", timeout: float | None = None) -> Message | None:
        key = (topic, group)
        with self._cond:
            while True:
                if self._closed:
                    return None
                log = self._logs.setdefault(topic, [])
                pos = self._cursor.get(key, self._offsets.get(key, 0))
                if pos < len(log):
                    self._cursor[key] = pos + 1
                    value, headers = log[pos]
                    # reserved delivery keys win over publisher headers — a
                    # hostile 'offset'/'group' header must not clobber them
                    metadata = dict(headers) if headers else {}
                    metadata.update({"offset": pos, "group": group})
                    return Message(
                        topic,
                        value,
                        metadata=metadata,
                        committer=lambda p=pos: self._commit(key, p),
                    )
                if not self._cond.wait(timeout=timeout):
                    return None

    def _commit(self, key: tuple[str, str], pos: int) -> None:
        """Advance the committed offset only across a CONTIGUOUS prefix of
        committed positions. With concurrent workers (SUBSCRIBER_WORKERS),
        a fast worker's higher commit must not acknowledge a slower
        worker's still-uncommitted (possibly failed) message — the group
        offset stays at the first gap, so a crash/rewind redelivers it
        (at-least-once; matches per-partition Kafka semantics)."""
        with self._cond:
            done = self._done.setdefault(key, set())
            done.add(pos)
            offset = self._offsets.get(key, 0)
            while offset in done:
                done.discard(offset)
                offset += 1
            self._offsets[key] = offset

    def rewind_uncommitted(self, topic: str, group: str = "default") -> None:
        """Redeliver messages consumed but never committed (crash simulation)."""
        key = (topic, group)
        with self._cond:
            self._cursor[key] = self._offsets.get(key, 0)
            self._cond.notify_all()

    def create_topic(self, topic: str) -> None:
        with self._cond:
            self._logs.setdefault(topic, [])

    def delete_topic(self, topic: str) -> None:
        with self._cond:
            self._logs.pop(topic, None)

    def topics(self) -> list[str]:
        with self._cond:
            return sorted(self._logs)

    def health_check(self) -> dict[str, Any]:
        with self._cond:
            return {
                "status": "UP" if not self._closed else "DOWN",
                "details": {"backend": "inmemory", "topics": len(self._logs)},
            }

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
