"""BERT encoder for embedding serving (BASELINE.md config #1).

Post-LayerNorm transformer encoder matching HF ``BertModel`` numerics
(oracle test in tests/test_models.py). Functional, stacked layers, scanned.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.base import fan_in_init, truncated_normal
from gofr_tpu.ops import layer_norm, mha_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def base(cls, **kw) -> "BertConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "BertConfig":
        return cls(**{**dict(
            vocab_size=256, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=2, max_seq_len=64,
        ), **kw})


def init(cfg: BertConfig, key: jax.Array) -> dict:
    e, m, nl = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    ks = jax.random.split(key, 12)
    dt = cfg.dtype
    return {
        "word_embed": truncated_normal(ks[0], (cfg.vocab_size, e), 0.02, dt),
        "pos_embed": truncated_normal(ks[1], (cfg.max_seq_len, e), 0.02, dt),
        "type_embed": truncated_normal(ks[2], (cfg.type_vocab_size, e), 0.02, dt),
        "embed_norm_w": jnp.ones((e,), dt),
        "embed_norm_b": jnp.zeros((e,), dt),
        "blocks": {
            "wq": fan_in_init(ks[3], (nl, e, e), fan_in=e, dtype=dt),
            "bq": jnp.zeros((nl, e), dt),
            "wk": fan_in_init(ks[4], (nl, e, e), fan_in=e, dtype=dt),
            "bk": jnp.zeros((nl, e), dt),
            "wv": fan_in_init(ks[5], (nl, e, e), fan_in=e, dtype=dt),
            "bv": jnp.zeros((nl, e), dt),
            "wo": fan_in_init(ks[6], (nl, e, e), fan_in=e, dtype=dt),
            "bo": jnp.zeros((nl, e), dt),
            "attn_norm_w": jnp.ones((nl, e), dt),
            "attn_norm_b": jnp.zeros((nl, e), dt),
            "w_inter": fan_in_init(ks[7], (nl, e, m), fan_in=e, dtype=dt),
            "b_inter": jnp.zeros((nl, m), dt),
            "w_out": fan_in_init(ks[8], (nl, m, e), fan_in=m, dtype=dt),
            "b_out": jnp.zeros((nl, e), dt),
            "mlp_norm_w": jnp.ones((nl, e), dt),
            "mlp_norm_b": jnp.zeros((nl, e), dt),
        },
        "pooler_w": fan_in_init(ks[9], (e, e), fan_in=e, dtype=dt),
        "pooler_b": jnp.zeros((e,), dt),
    }


def param_axes(cfg: BertConfig) -> dict:
    e2 = ("layers", "embed", "heads")
    vec = ("layers", None)
    axes = {
        "word_embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "type_embed": (None, "embed"),
        "embed_norm_w": (None,),
        "embed_norm_b": (None,),
        "blocks": {
            "wq": e2, "bq": ("layers", "heads"),
            "wk": e2, "bk": ("layers", "heads"),
            "wv": e2, "bv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "bo": vec,
            "attn_norm_w": vec, "attn_norm_b": vec,
            "w_inter": ("layers", "embed", "mlp"), "b_inter": ("layers", "mlp"),
            "w_out": ("layers", "mlp", "embed"), "b_out": vec,
            "mlp_norm_w": vec, "mlp_norm_b": vec,
        },
        "pooler_w": ("embed", None),
        "pooler_b": (None,),
    }
    return axes


@partial(jax.jit, static_argnums=0)
def encode(cfg: BertConfig, params: dict, tokens: jnp.ndarray,
           lengths: jnp.ndarray | None = None,
           token_types: jnp.ndarray | None = None) -> jnp.ndarray:
    """tokens [B,S] → hidden states [B,S,E]."""
    b, s = tokens.shape
    if token_types is None:
        token_types = jnp.zeros_like(tokens)
    x = (
        params["word_embed"][tokens]
        + params["pos_embed"][jnp.arange(s)][None]
        + params["type_embed"][token_types]
    ).astype(cfg.dtype)
    x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"], cfg.norm_eps)

    def body(x, lp):
        q = (x @ lp["wq"] + lp["bq"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        k = (x @ lp["wk"] + lp["bk"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        v = (x @ lp["wv"] + lp["bv"]).reshape(b, s, cfg.num_heads, cfg.head_size)
        attn = mha_attention(q, k, v, causal=False, kv_lengths=lengths).reshape(b, s, -1)
        x = layer_norm(x + attn @ lp["wo"] + lp["bo"], lp["attn_norm_w"], lp["attn_norm_b"], cfg.norm_eps)
        inter = jax.nn.gelu(x @ lp["w_inter"] + lp["b_inter"], approximate=False)
        x = layer_norm(x + inter @ lp["w_out"] + lp["b_out"], lp["mlp_norm_w"], lp["mlp_norm_b"], cfg.norm_eps)
        return x, None

    x, _ = lax.scan(body, x, params["blocks"])
    return x


@partial(jax.jit, static_argnums=0)
def embed_pooled(cfg: BertConfig, params: dict, tokens: jnp.ndarray,
                 lengths: jnp.ndarray) -> jnp.ndarray:
    """Mean-pooled, L2-normalized sentence embeddings [B,E] (f32) — the
    serving payload of the embedding endpoint."""
    hidden = encode(cfg, params, tokens, lengths).astype(jnp.float32)
    mask = (jnp.arange(tokens.shape[1])[None] < lengths[:, None]).astype(jnp.float32)
    summed = jnp.einsum("bse,bs->be", hidden, mask)
    pooled = summed / jnp.maximum(lengths[:, None].astype(jnp.float32), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)
