"""Model layer foundations.

Models in gofr_tpu are *functional modules*: a frozen config dataclass plus
pure functions ``init(cfg, key) → params``, ``param_axes(cfg) → logical
axes pytree``, and jittable ``forward_*`` functions. No module classes, no
framework state — params are plain pytrees the parallel layer can shard by
logical axes (gofr_tpu.parallel.sharding) and orbax can checkpoint.

Layer parameters are *stacked*: every per-layer weight carries a leading
``layers`` dimension and the forward pass runs ``lax.scan`` over it — one
traced block regardless of depth, which keeps XLA compile time flat and
maps cleanly onto pipeline stages later.

``ModelSpec`` is what users hand to ``app.serve_model`` — the serving-side
description (family, config, weights source, task) that ``build_engine``
turns into a running engine.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


def truncated_normal(key, shape, stddev: float, dtype=jnp.float32):
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32).astype(dtype) * stddev


def fan_in_init(key, shape, fan_in: int | None = None, dtype=jnp.float32):
    fan = fan_in if fan_in is not None else shape[-2] if len(shape) >= 2 else shape[-1]
    return truncated_normal(key, shape, 1.0 / math.sqrt(fan), dtype)


def param_count(params: Any) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


def param_bytes(params: Any) -> int:
    return sum(int(x.size) * x.dtype.itemsize for x in jax.tree.leaves(params))


def cast_floats(params: Any, dtype) -> Any:
    """Cast floating-point leaves (weights) to ``dtype``; leave ints alone."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, params
    )


@dataclass
class ModelSpec:
    """What ``app.serve_model`` consumes.

    family: "llama" | "bert" | "vit" (extensible via ``models.register``)
    config: the family's config dataclass (or dict of overrides)
    task: "generate" | "embed" | "classify" — selects the engine path
    weights: None (random init), a checkpoint path (orbax), or an HF model
             id/path to convert (gofr_tpu.models.convert)
    tokenizer: HF tokenizer id/path OR an object with encode/decode (e.g.
             utils.ByteTokenizer) for text models (optional — the engine
             also accepts pre-tokenized int arrays)
    """

    family: str
    config: Any = None
    task: str = "generate"
    weights: str | None = None
    tokenizer: Any = None
    dtype: Any = jnp.bfloat16
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)


_FAMILIES: dict[str, Any] = {}


def register_family(name: str, module: Any) -> None:
    _FAMILIES[name] = module


def get_family(name: str):
    try:
        return _FAMILIES[name]
    except KeyError:
        raise KeyError(f"unknown model family {name!r}; registered: {sorted(_FAMILIES)}") from None
