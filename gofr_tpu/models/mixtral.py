"""Mixtral-family sparse-MoE decoder LM.

Llama attention blocks (RMSNorm / RoPE / GQA — shared via gofr_tpu.ops)
with the dense SwiGLU MLP swapped for a top-k routed mixture of experts
(gofr_tpu.ops.moe). Expert weights carry the "expert" logical axis so a
mesh with an ``ep`` axis runs expert-parallel via GSPMD all-to-alls; tp
still shards the per-expert mlp dim, so EP×TP composes.

Same three entry points as llama (forward / prefill / decode_step) and the
same SlotKVCache, so the continuous-batching engine serves it unchanged —
the reference's "swap datasource behind the container" ergonomics applied
to model families (SURVEY.md §2.4 plugin pattern).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.base import fan_in_init, truncated_normal
from gofr_tpu.ops import apply_rope, mha_attention, rms_norm, rope_table
from gofr_tpu.ops.attention import decode_attention
from gofr_tpu.ops.kvcache import SlotKVCache, append_tokens, write_prompts
from gofr_tpu.ops.moe import moe_ffn


@dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    head_dim: int | None = None
    rope_theta: float = 1000000.0
    max_seq_len: int = 8192
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @property
    def head_size(self) -> int:
        return self.head_dim if self.head_dim is not None else self.hidden_size // self.num_heads

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MixtralConfig":
        return cls(**{**dict(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_heads=32, num_kv_heads=8, num_experts=8,
            experts_per_token=2,
        ), **kw})

    @classmethod
    def tiny(cls, **kw) -> "MixtralConfig":
        """Test-sized config for the CPU mesh."""
        return cls(**{**dict(
            vocab_size=256, hidden_size=64, intermediate_size=96,
            num_layers=2, num_heads=4, num_kv_heads=2, num_experts=4,
            experts_per_token=2, max_seq_len=128, rope_theta=10000.0,
            dtype=jnp.float32,
        ), **kw})


def init(cfg: MixtralConfig, key: jax.Array) -> dict:
    e, m, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hq, hkv, d, nl, ne = cfg.num_heads, cfg.num_kv_heads, cfg.head_size, cfg.num_layers, cfg.num_experts
    keys = jax.random.split(key, 10)
    dt = cfg.dtype
    return {
        "embed": truncated_normal(keys[0], (v, e), 0.02, dt),
        "blocks": {
            "attn_norm": jnp.ones((nl, e), dt),
            "wq": fan_in_init(keys[1], (nl, e, hq * d), fan_in=e, dtype=dt),
            "wk": fan_in_init(keys[2], (nl, e, hkv * d), fan_in=e, dtype=dt),
            "wv": fan_in_init(keys[3], (nl, e, hkv * d), fan_in=e, dtype=dt),
            "wo": fan_in_init(keys[4], (nl, hq * d, e), fan_in=hq * d, dtype=dt),
            "mlp_norm": jnp.ones((nl, e), dt),
            "router": fan_in_init(keys[5], (nl, e, ne), fan_in=e, dtype=jnp.float32),
            "w_gate": fan_in_init(keys[6], (nl, ne, e, m), fan_in=e, dtype=dt),
            "w_up": fan_in_init(keys[7], (nl, ne, e, m), fan_in=e, dtype=dt),
            "w_down": fan_in_init(keys[8], (nl, ne, m, e), fan_in=m, dtype=dt),
        },
        "final_norm": jnp.ones((e,), dt),
        "lm_head": truncated_normal(keys[9], (e, v), 0.02, dt),
    }


def param_axes(cfg: MixtralConfig) -> dict:
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", None),
            "router": ("layers", "embed", None),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def _rope(cfg: MixtralConfig):
    return rope_table(cfg.max_seq_len, cfg.head_size, theta=cfg.rope_theta)


def _qkv(cfg: MixtralConfig, lp: dict, x: jnp.ndarray):
    b, s, _ = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_size)
    k = (h @ lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_size)
    v = (h @ lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_size)
    return q, k, v


def _moe(cfg: MixtralConfig, lp: dict, x: jnp.ndarray,
         lengths: jnp.ndarray | None = None,
         capacity: int | None = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """x [B, S, E] → (moe output, aux loss). ``lengths`` masks padded
    positions out of routing so they never steal expert capacity."""
    b, s, e = x.shape
    h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    token_mask = None
    if lengths is not None:
        token_mask = (jnp.arange(s)[None, :] < lengths[:, None]).reshape(b * s)
    y, aux = moe_ffn(
        h.reshape(b * s, e),
        lp["router"],
        lp["w_gate"],
        lp["w_up"],
        lp["w_down"],
        k=cfg.experts_per_token,
        capacity_factor=cfg.capacity_factor,
        capacity=capacity,
        token_mask=token_mask,
    )
    return y.reshape(b, s, e), aux


@partial(jax.jit, static_argnums=(0, 4))
def forward_with_aux(cfg: MixtralConfig, params: dict, tokens: jnp.ndarray,
                     lengths: jnp.ndarray | None = None,
                     attn_fn: Any = None) -> tuple[jnp.ndarray, dict]:
    """Full causal forward → (logits [B,S,V] f32, {"load_balance": aux})."""
    attn = attn_fn or mha_attention
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    b, s = tokens.shape
    positions = jnp.arange(s)[None]

    def body(carry, lp):
        x, aux_sum = carry
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        a = attn(q, k, v, causal=True, kv_lengths=lengths)
        x = x + a.reshape(b, s, -1) @ lp["wo"]
        y, aux = _moe(cfg, lp, x, lengths)
        return (x + y, aux_sum + aux), None

    (x, aux_sum), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), params["blocks"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, {"load_balance": aux_sum / cfg.num_layers}


def forward(cfg: MixtralConfig, params: dict, tokens: jnp.ndarray,
            lengths: jnp.ndarray | None = None, attn_fn: Any = None) -> jnp.ndarray:
    return forward_with_aux(cfg, params, tokens, lengths, attn_fn)[0]


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def prefill(cfg: MixtralConfig, params: dict, tokens: jnp.ndarray, lengths: jnp.ndarray,
            cache: SlotKVCache, slots: jnp.ndarray) -> tuple[jnp.ndarray, SlotKVCache]:
    """Same contract as llama.prefill (llama.py docstring)."""
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    b, s = tokens.shape
    positions = jnp.arange(s)[None]
    row = jnp.arange(b)

    def body(x, xs):
        lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x)
        q = apply_rope(q, positions, cos, sin)
        k = apply_rope(k, positions, cos, sin)
        k_layer, v_layer = write_prompts(k_layer, v_layer, slots, k, v)
        a = mha_attention(q, k, v, causal=True, kv_lengths=lengths)
        x = x + a.reshape(b, s, -1) @ lp["wo"]
        y, _ = _moe(cfg, lp, x, lengths)
        return x + y, (k_layer, v_layer)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[row, lengths - 1]
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    return logits, SlotKVCache(k=new_k, v=new_v)


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def decode_step(cfg: MixtralConfig, params: dict, tokens: jnp.ndarray, positions: jnp.ndarray,
                cache: SlotKVCache) -> tuple[jnp.ndarray, SlotKVCache]:
    """Same contract as llama.decode_step (llama.py docstring)."""
    cos, sin = _rope(cfg)
    x = params["embed"][tokens].astype(cfg.dtype)
    n = tokens.shape[0]
    pos1 = positions[:, None]

    def body(x, xs):
        lp, k_layer, v_layer = xs
        q, k, v = _qkv(cfg, lp, x[:, None])
        q = apply_rope(q, pos1, cos, sin)[:, 0]
        k = apply_rope(k, pos1, cos, sin)[:, 0]
        v = v[:, 0]
        k_layer, v_layer = append_tokens(k_layer, v_layer, positions, k, v)
        a = decode_attention(q, k_layer, v_layer, positions + 1)
        x = x + a.reshape(n, -1) @ lp["wo"]
        # capacity == n: a skewed slot batch can never drop a live token
        y, _ = _moe(cfg, lp, x[:, None], capacity=n)
        return x + y[:, 0], (k_layer, v_layer)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, SlotKVCache(k=new_k, v=new_v)


def make_cache(cfg: MixtralConfig, slots: int, max_len: int | None = None) -> SlotKVCache:
    return SlotKVCache.create(
        cfg.num_layers, slots, max_len or cfg.max_seq_len, cfg.num_kv_heads,
        cfg.head_size, dtype=cfg.dtype,
    )
