"""Pipeline-parallel SERVING family for llama (slot KV layout).

``build_engine`` wraps the llama family with :class:`PPLlamaFamily` when the
container's mesh has a ``pp`` axis of size > 1: block params AND the slot KV
cache shard over ``pp`` on the layer dim — the 70B weight-fit story
(BASELINE.md row 4) — and every engine device call runs a GPipe-style
schedule (``parallel.pipeline.spmd_pipeline_stateful``) where microbatches
of slots stream through the stage ring. Composes with ``tp``: head/mlp dims
of the stage weights and the cache's kv-head dim stay tp-sharded inside the
pipeline region with Megatron-style psums (same layout as
``llama.forward_pipelined``). A ``dp`` axis, if present, replicates the
serving work — shard serving replicas at the engine level instead.

The reference has no model execution at all (SURVEY.md §2.9); within this
framework the shim matches the GenerateEngine family contract
(``prefill`` / ``decode_step`` / ``make_cache``, engine.py:508) so slot
continuous batching, chunked decode, pipelined dispatch, and warmup all work
unchanged over a pp mesh.

Correctness relies on the engine's dropped-write conventions:
- bubble ticks carry OOB positions (decode) / OOB slot ids (prefill), so
  their cache writes vanish exactly like the engine's padding rows;
- drain-tick re-feeds recompute identical K/V (deterministic), so their
  rewrites are no-ops.

v1 limits: no chunked prefill (prompts must fit the largest prefill
bucket), no weight-only int8 (QUANTIZABLE False), no paged layout.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from gofr_tpu.models import llama
from gofr_tpu.models.llama import LlamaConfig, _rope
from gofr_tpu.ops.attention import decode_attention, mha_attention
from gofr_tpu.ops.kvcache import SlotKVCache, append_tokens, write_prompts
from gofr_tpu.ops.norms import rms_norm
from gofr_tpu.ops.rope import apply_rope
from gofr_tpu.parallel.pipeline import spmd_pipeline_stateful


class PPLlamaFamily:
    """llama with pp-sharded blocks/cache behind the engine family API."""

    __name__ = "llama_pp"
    SLOT_CHUNKED_PREFILL = False
    QUANTIZABLE = False

    def __init__(self, mesh, microbatches: int | None = None, rules=None):
        self.mesh = mesh
        self.pp = int(mesh.shape["pp"])
        self.microbatches = int(microbatches) if microbatches else self.pp
        self.tp = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 else None
        # the SAME rules build_engine shards the params with (layers→pp
        # already applied) — shard_map in_specs asserting a different
        # layout would silently reshard the full blocks every call
        if rules is None:
            from gofr_tpu.parallel.sharding import ShardingRules

            rules = ShardingRules().with_overrides(layers="pp")
        self.rules = rules

    # passthroughs so build_engine treats this like the plain family
    def init(self, cfg, key):
        return llama.init(cfg, key)

    def param_axes(self, cfg):
        return llama.param_axes(cfg)

    def _block_specs(self, cfg) -> dict:
        return {
            name: self.rules.spec(axes, self.mesh)
            for name, axes in llama.param_axes(cfg)["blocks"].items()
        }

    def _cache_spec(self) -> P:
        # [L, N, Hkv, Smax, D]: layers over pp, kv-heads over tp
        return P("pp", None, self.tp) if self.tp else P("pp")

    def make_cache(self, cfg: LlamaConfig, slots: int, max_len: int | None = None) -> SlotKVCache:
        if cfg.num_layers % self.pp:
            raise ValueError(f"num_layers {cfg.num_layers} not divisible by pp {self.pp}")
        cache = llama.make_cache(cfg, slots, max_len)
        sharding = NamedSharding(self.mesh, self._cache_spec())
        return SlotKVCache(
            k=jax.device_put(cache.k, sharding), v=jax.device_put(cache.v, sharding)
        )

    # -- decode ---------------------------------------------------------------

    def decode_step(self, cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
                    positions: jnp.ndarray, cache: SlotKVCache):
        n = tokens.shape[0]
        m = self.microbatches if n % self.microbatches == 0 else math.gcd(n, self.microbatches)
        mbs = n // m
        d = cfg.head_size
        tp = self.tp
        cos, sin = _rope(cfg)
        smax = cache.k.shape[3]
        x = params["embed"][tokens].astype(cfg.dtype)  # [N,E]
        cspec = self._cache_spec()

        @partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(self._block_specs(cfg), (cspec, cspec),
                      P(None, None), P(None), P(None)),
            out_specs=(P(None, None), (cspec, cspec)),
            check_vma=False,
        )
        def run(blocks, state, x_mb, pos_mb, off_mb):
            def stage_fn(blocks, st, act):
                k_all, v_all = st
                x, pos, off = act  # [mbs,E], [mbs], scalar slot-row offset
                pos1 = pos[:, None]

                def body(x, xs):
                    lp, k_layer, v_layer = xs  # k_layer [N, Hkv_local, Smax, D]
                    h = rms_norm(x[:, None], lp["attn_norm"], cfg.norm_eps)
                    q = (h @ lp["wq"]).reshape(mbs, 1, -1, d)
                    k = (h @ lp["wk"]).reshape(mbs, 1, -1, d)
                    v = (h @ lp["wv"]).reshape(mbs, 1, -1, d)
                    q = apply_rope(q, pos1, cos, sin)[:, 0]
                    k = apply_rope(k, pos1, cos, sin)[:, 0]
                    v = v[:, 0]
                    k_sl = lax.dynamic_slice_in_dim(k_layer, off, mbs, axis=0)
                    v_sl = lax.dynamic_slice_in_dim(v_layer, off, mbs, axis=0)
                    k_sl, v_sl = append_tokens(k_sl, v_sl, pos, k, v)
                    attn = decode_attention(q, k_sl, v_sl, pos + 1)
                    k_layer = lax.dynamic_update_slice_in_dim(k_layer, k_sl, off, axis=0)
                    v_layer = lax.dynamic_update_slice_in_dim(v_layer, v_sl, off, axis=0)
                    o = attn.reshape(mbs, -1) @ lp["wo"]
                    if tp:
                        o = lax.psum(o, tp)
                    x = x + o
                    h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
                    mo = (jax.nn.silu(h2 @ lp["w_gate"]) * (h2 @ lp["w_up"])) @ lp["w_down"]
                    if tp:
                        mo = lax.psum(mo, tp)
                    return x + mo, (k_layer, v_layer)

                x, (k_all, v_all) = lax.scan(body, x, (blocks, k_all, v_all))
                return (k_all, v_all), (x, pos, off)

            # bubble ticks: OOB positions -> append's masked select drops
            # every write (same convention as engine padding rows)
            init_act = (
                jnp.zeros((mbs, x.shape[1]), x.dtype),
                jnp.full((mbs,), smax, pos_mb.dtype),
                jnp.zeros((), off_mb.dtype),
            )
            (x_out, _, _), state = spmd_pipeline_stateful(
                stage_fn, blocks, state, (x_mb, pos_mb, off_mb),
                microbatches=m, init_act=init_act,
            )
            return x_out, state

        x_mb = x.reshape(m, mbs, -1)
        pos_mb = positions.reshape(m, mbs)
        off_mb = jnp.arange(m, dtype=jnp.int32) * mbs
        x_mb, (new_k, new_v) = run(
            params["blocks"], (cache.k, cache.v), x_mb, pos_mb, off_mb)
        x = x_mb.reshape(n, -1)

        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        logits = (x @ head).astype(jnp.float32)
        return logits, SlotKVCache(k=new_k, v=new_v)

    # -- prefill --------------------------------------------------------------

    def prefill(self, cfg: LlamaConfig, params: dict, tokens: jnp.ndarray,
                lengths: jnp.ndarray, cache: SlotKVCache, slots: jnp.ndarray,
                offsets: jnp.ndarray | None = None):
        if offsets is not None:
            raise ValueError("pp serving does not support chunked prefill (v1)")
        b, s = tokens.shape
        m = self.microbatches if b % self.microbatches == 0 else math.gcd(b, self.microbatches)
        mbs = b // m
        d = cfg.head_size
        tp = self.tp
        cos, sin = _rope(cfg)
        num_slots = cache.k.shape[1]
        positions = jnp.arange(s)[None]
        x = params["embed"][tokens].astype(cfg.dtype)  # [B,S,E]
        cspec = self._cache_spec()

        @partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(self._block_specs(cfg), (cspec, cspec),
                      P(None, None, None), P(None, None), P(None, None)),
            out_specs=(P(None, None, None), (cspec, cspec)),
            check_vma=False,
        )
        def run(blocks, state, x_mb, len_mb, row_mb):
            def stage_fn(blocks, st, act):
                k_all, v_all = st
                x, lens, rows = act  # [mbs,S,E], [mbs], [mbs]

                def body(x, xs):
                    lp, k_layer, v_layer = xs
                    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
                    q = (h @ lp["wq"]).reshape(mbs, s, -1, d)
                    k = (h @ lp["wk"]).reshape(mbs, s, -1, d)
                    v = (h @ lp["wv"]).reshape(mbs, s, -1, d)
                    q = apply_rope(q, positions, cos, sin)
                    k = apply_rope(k, positions, cos, sin)
                    # OOB rows (bubbles / padding) scatter nowhere
                    k_layer, v_layer = write_prompts(k_layer, v_layer, rows, k, v)
                    a = mha_attention(q, k, v, causal=True, kv_lengths=lens)
                    o = a.reshape(mbs, s, -1) @ lp["wo"]
                    if tp:
                        o = lax.psum(o, tp)
                    x = x + o
                    h2 = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
                    mo = (jax.nn.silu(h2 @ lp["w_gate"]) * (h2 @ lp["w_up"])) @ lp["w_down"]
                    if tp:
                        mo = lax.psum(mo, tp)
                    return x + mo, (k_layer, v_layer)

                x, (k_all, v_all) = lax.scan(body, x, (blocks, k_all, v_all))
                return (k_all, v_all), (x, lens, rows)

            init_act = (
                jnp.zeros((mbs, s, x.shape[2]), x.dtype),
                jnp.ones((mbs,), len_mb.dtype),
                jnp.full((mbs,), num_slots, row_mb.dtype),  # OOB slot ids
            )
            (x_out, _, _), state = spmd_pipeline_stateful(
                stage_fn, blocks, state, (x_mb, len_mb, row_mb),
                microbatches=m, init_act=init_act,
            )
            return x_out, state

        x_mb = x.reshape(m, mbs, s, -1)
        len_mb = lengths.reshape(m, mbs)
        row_mb = slots.reshape(m, mbs)
        x_mb, (new_k, new_v) = run(
            params["blocks"], (cache.k, cache.v), x_mb, len_mb, row_mb)
        x = x_mb.reshape(b, s, -1)

        x = rms_norm(x, params["final_norm"], cfg.norm_eps)
        last = x[jnp.arange(b), lengths - 1]
        head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
        logits = (last @ head).astype(jnp.float32)
        return logits, SlotKVCache(k=new_k, v=new_v)
