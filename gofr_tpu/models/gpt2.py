"""GPT-2 decoder family (learned position embeddings, pre-LN, gelu MLP,
tied lm head) — the classic HF checkpoint format, servable through the
same engine contract as llama: ``init / forward / prefill / decode_step /
make_cache / param_axes`` (HF oracle in tests/test_models.py; converter in
models/convert.py). Linear sites route through ops.quant.qdot, so int8
weight-only serving works here too.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from gofr_tpu.models.base import truncated_normal
from gofr_tpu.ops import layer_norm, mha_attention
from gofr_tpu.ops.attention import decode_attention
from gofr_tpu.ops.kvcache import SlotKVCache, append_tokens, write_prompts
from gofr_tpu.ops.quant import qdot


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: Any = jnp.float32

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def intermediate_size(self) -> int:
        return 4 * self.hidden_size

    @classmethod
    def small(cls, **kw) -> "GPT2Config":
        return cls(**kw)  # gpt2 (124M) defaults

    @classmethod
    def tiny(cls, **kw) -> "GPT2Config":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("max_seq_len", 128)
        return cls(**kw)


# every linear site routes through ops.quant.qdot, so QTensor params serve
QUANTIZABLE = True
# prefill() accepts chunk offsets (slot-layout chunked prefill)
SLOT_CHUNKED_PREFILL = True


def init(cfg: GPT2Config, key: jax.Array) -> dict:
    e, L = cfg.hidden_size, cfg.num_layers
    keys = jax.random.split(key, 8)
    dt = cfg.dtype

    def mat(k, shape, std=0.02):
        return truncated_normal(k, shape, std, dt)

    return {
        "wte": mat(keys[0], (cfg.vocab_size, e)),
        "wpe": mat(keys[1], (cfg.max_seq_len, e)),
        "blocks": {
            "ln1_g": jnp.ones((L, e), dt), "ln1_b": jnp.zeros((L, e), dt),
            "wq": mat(keys[2], (L, e, e)), "bq": jnp.zeros((L, e), dt),
            "wk": mat(keys[3], (L, e, e)), "bk": jnp.zeros((L, e), dt),
            "wv": mat(keys[4], (L, e, e)), "bv": jnp.zeros((L, e), dt),
            "wo": mat(keys[5], (L, e, e)), "bo": jnp.zeros((L, e), dt),
            "ln2_g": jnp.ones((L, e), dt), "ln2_b": jnp.zeros((L, e), dt),
            "w_fc": mat(keys[6], (L, e, cfg.intermediate_size)),
            "b_fc": jnp.zeros((L, cfg.intermediate_size), dt),
            "w_proj": mat(keys[7], (L, cfg.intermediate_size, e)),
            "b_proj": jnp.zeros((L, e), dt),
        },
        "lnf_g": jnp.ones((e,), dt), "lnf_b": jnp.zeros((e,), dt),
    }


def param_axes(cfg: GPT2Config) -> dict:
    """Logical sharding axes (tp shards heads/mlp; embed replicated on tp)."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_g": (None, None), "ln1_b": (None, None),
            "wq": (None, "embed", "heads"), "bq": (None, "heads"),
            "wk": (None, "embed", "heads"), "bk": (None, "heads"),
            "wv": (None, "embed", "heads"), "bv": (None, "heads"),
            "wo": (None, "heads", "embed"), "bo": (None, None),
            "ln2_g": (None, None), "ln2_b": (None, None),
            "w_fc": (None, "embed", "mlp"), "b_fc": (None, "mlp"),
            "w_proj": (None, "mlp", "embed"), "b_proj": (None, None),
        },
        "lnf_g": (None,), "lnf_b": (None,),
    }


def _attn_qkv(cfg: GPT2Config, lp: dict, x: jnp.ndarray):
    """x [B,S,E] (post-ln1) → q/k/v [B,S,H,D]."""
    b, s, _ = x.shape
    q = (qdot(x, lp["wq"]) + lp["bq"]).reshape(b, s, cfg.num_heads, cfg.head_size)
    k = (qdot(x, lp["wk"]) + lp["bk"]).reshape(b, s, cfg.num_heads, cfg.head_size)
    v = (qdot(x, lp["wv"]) + lp["bv"]).reshape(b, s, cfg.num_heads, cfg.head_size)
    return q, k, v


def _mlp(cfg: GPT2Config, lp: dict, x: jnp.ndarray) -> jnp.ndarray:
    h = layer_norm(x, lp["ln2_g"], lp["ln2_b"], cfg.norm_eps)
    return qdot(jax.nn.gelu(qdot(h, lp["w_fc"]) + lp["b_fc"], approximate=True),
                lp["w_proj"]) + lp["b_proj"]


@partial(jax.jit, static_argnums=0)
def forward(cfg: GPT2Config, params: dict, tokens: jnp.ndarray,
            lengths: jnp.ndarray | None = None) -> jnp.ndarray:
    """tokens [B,S] → logits [B,S,V] f32 (dense, no cache)."""
    b, s = tokens.shape
    pos = jnp.arange(s)
    x = (params["wte"][tokens] + params["wpe"][pos][None]).astype(cfg.dtype)

    def body(x, lp):
        h = layer_norm(x, lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
        q, k, v = _attn_qkv(cfg, lp, h)
        a = mha_attention(q, k, v, causal=True, kv_lengths=lengths)
        x = x + qdot(a.reshape(b, s, -1), lp["wo"]) + lp["bo"]
        x = x + _mlp(cfg, lp, x)
        return x, None

    x, _ = lax.scan(body, x, params["blocks"])
    x = layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.norm_eps)
    return qdot(x, params["wte"].T).astype(jnp.float32)


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def prefill(cfg: GPT2Config, params: dict, tokens: jnp.ndarray, lengths: jnp.ndarray,
            cache: SlotKVCache, slots: jnp.ndarray,
            offsets: jnp.ndarray | None = None) -> tuple[jnp.ndarray, SlotKVCache]:
    """Engine contract — see llama.prefill (offsets = chunked prefill)."""
    b, s = tokens.shape
    chunked = offsets is not None
    positions = (offsets[:, None] if chunked else 0) + jnp.arange(s)[None]  # [B,S] or [1,S]
    pe = params["wpe"][jnp.minimum(positions, cfg.max_seq_len - 1)]
    x = (params["wte"][tokens] + pe).astype(cfg.dtype)
    row = jnp.arange(b)
    total = (offsets + lengths) if chunked else lengths

    def body(x, xs):
        lp, k_layer, v_layer = xs
        h = layer_norm(x, lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
        q, k, v = _attn_qkv(cfg, lp, h)
        k_layer, v_layer = write_prompts(k_layer, v_layer, slots, k, v, offsets)
        if chunked:
            k_view = jnp.take(k_layer, slots, axis=0)
            v_view = jnp.take(v_layer, slots, axis=0)
            a = mha_attention(
                q, k_view.swapaxes(1, 2), v_view.swapaxes(1, 2),
                causal=True, q_offset=offsets, kv_lengths=total,
            )
        else:
            a = mha_attention(q, k, v, causal=True, kv_lengths=lengths)
        x = x + qdot(a.reshape(b, s, -1), lp["wo"]) + lp["bo"]
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
    x = layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.norm_eps)
    last = x[row, lengths - 1]
    logits = qdot(last, params["wte"].T).astype(jnp.float32)
    return logits, SlotKVCache(k=new_k, v=new_v)


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def verify_step(cfg: GPT2Config, params: dict, tokens: jnp.ndarray,
                positions: jnp.ndarray, cache: SlotKVCache) -> tuple[jnp.ndarray, SlotKVCache]:
    """Speculative-decoding verification — contract and stale-draft-KV
    invariants as llama.verify_step: tokens [N, T] per slot written and
    attended at positions[n]..positions[n]+T-1, logits for ALL T positions."""
    n, t = tokens.shape
    pos2d = positions[:, None] + jnp.arange(t)[None]
    pe = params["wpe"][jnp.minimum(pos2d, cfg.max_seq_len - 1)]
    x = (params["wte"][tokens] + pe).astype(cfg.dtype)
    rows = jnp.arange(n)
    total = positions + t

    def body(x, xs):
        lp, k_layer, v_layer = xs
        h = layer_norm(x, lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
        q, k, v = _attn_qkv(cfg, lp, h)
        k_layer, v_layer = write_prompts(k_layer, v_layer, rows, k, v, positions)
        a = mha_attention(
            q, k_layer.swapaxes(1, 2), v_layer.swapaxes(1, 2),
            causal=True, q_offset=positions, kv_lengths=total,
        )
        x = x + qdot(a.reshape(n, t, -1), lp["wo"]) + lp["bo"]
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
    x = layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.norm_eps)
    logits = qdot(x, params["wte"].T).astype(jnp.float32)
    return logits, SlotKVCache(k=new_k, v=new_v)


@partial(jax.jit, static_argnums=0, donate_argnums=4)
def decode_step(cfg: GPT2Config, params: dict, tokens: jnp.ndarray, positions: jnp.ndarray,
                cache: SlotKVCache) -> tuple[jnp.ndarray, SlotKVCache]:
    """Engine contract — see llama.decode_step."""
    n = tokens.shape[0]
    # learned positional embedding at each slot's own position (clamped so
    # garbage positions on idle slots stay in bounds)
    pe = params["wpe"][jnp.minimum(positions, cfg.max_seq_len - 1)]
    x = (params["wte"][tokens] + pe).astype(cfg.dtype)

    def body(x, xs):
        lp, k_layer, v_layer = xs
        h = layer_norm(x, lp["ln1_g"], lp["ln1_b"], cfg.norm_eps)
        q, k, v = _attn_qkv(cfg, lp, h[:, None])
        q, k, v = q[:, 0], k[:, 0], v[:, 0]
        k_layer, v_layer = append_tokens(k_layer, v_layer, positions, k, v)
        a = decode_attention(q, k_layer, v_layer, positions + 1)
        x = x + qdot(a.reshape(n, -1), lp["wo"]) + lp["bo"]
        x = x + _mlp(cfg, lp, x)
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache.k, cache.v))
    x = layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.norm_eps)
    logits = qdot(x, params["wte"].T).astype(jnp.float32)
    return logits, SlotKVCache(k=new_k, v=new_v)


def make_cache(cfg: GPT2Config, slots: int, max_len: int | None = None) -> SlotKVCache:
    return SlotKVCache.create(
        cfg.num_layers, slots, max_len or cfg.max_seq_len,
        cfg.num_heads, cfg.head_size, dtype=cfg.dtype,
    )
